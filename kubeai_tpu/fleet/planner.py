"""CapacityPlanner: cluster-wide coordinated capacity planning.

Every model's autoscaler computes its desired replicas independently —
nothing arbitrates when the sum of desires exceeds the cluster chip
budget, so realtime models can starve behind batch models while idle
chips sit on the wrong slice shape. The planner closes that gap: each
planning tick it consumes the latest fleet snapshot (queue pressure,
TTFT, KV/slot utilization per model+role — already aggregated by
`FleetStateAggregator`) plus the chip inventory of heterogeneous slice
shapes, computes each model's unconstrained desire with the SAME math
the per-model autoscaler uses (`desired_unified_replicas` /
`desired_prefill_replicas` / `desired_decode_replicas` in
kubeai_tpu/autoscaler/autoscaler.py), then bin-packs replicas under the
chip budget by scheduling class:

  - classes allocate in strict priority order (realtime → standard →
    batch), so batch-class replicas are preempted to free chips before a
    realtime-class model under SLO pressure is ever throttled;
  - CRD `minReplicas` floors are honored first across ALL classes (a
    guarantee is a guarantee), then demand water-fills per class one
    replica per model per round — fair within a class, deterministic;
  - each replica is right-sized onto the CHEAPEST slice shape that can
    host it (smallest per-slice chip count ≥ the model's chips per
    replica), spilling to larger shapes only when the cheap pool runs
    dry;
  - disaggregated models damp the prefill/decode pair JOINTLY: under
    chip pressure the role with the lowest allocated/desired fraction is
    granted next, so both roles shrink toward their desired ratio
    instead of one role being chopped.

The resulting allocation is an override channel into the autoscaler:
`Autoscaler` consults `allocation_for(model)` before calling
`ModelClient.scale`/`scale_role`, and falls back to its direct per-model
path whenever the plan (or the snapshot behind it) is stale. Decisions
are published three ways, mirroring the autoscaler's decision trail:
`kubeai_planner_*` gauges, `GET /v1/fleet/plan`, and one structured JSON
record per (tick, model) on the `kubeai.planner.decisions` logger
(`last_decisions` holds the in-process view). Preemption picks are
honored by the operator: victim pods get the
`kubeai.org/planner-preempt` annotation and pod_plan deletes them first.

A cluster whose store carries no Node objects (or whose nodes expose no
`google.com/tpu` capacity) has an UNKNOWN budget: the planner then plans
unconstrained — allocations equal desires, nothing is preempted — which
is exactly the pre-planner behavior.

When a `DemandForecaster` (kubeai_tpu/fleet/forecaster) is wired in, the
planner additionally runs a PREWARM pass after demand is satisfied: a
model whose forecast fires a warm trigger (rising demand trend, or spot
preemptions eating its capacity) is granted extra replicas from the
REMAINING free chips — gated per model by `governor.allow_prewarm` and
clamped by `maxReplicas` — so snapshot-warm pods are Ready before the
spike lands instead of cold-booting into it. The forecaster's measured
cold-start cost is also priced into arbitration: within a class, demand
chips flow to expensive-to-boot models first, so when preemption must
happen it lands on the models whose replicas restore from a snapshot in
seconds rather than the ones that recompile for minutes.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time

from kubeai_tpu.autoscaler.autoscaler import (
    aggregate_role_signals,
    desired_decode_replicas,
    desired_prefill_replicas,
    desired_unified_replicas,
)
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.metrics import flightrecorder
from kubeai_tpu.metrics.registry import DEFAULT_METRICS, Metrics
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.k8s.store import Conflict, NotFound

logger = logging.getLogger(__name__)

# One structured JSON record per (tick, model): the planner's decision
# trail, same contract as kubeai.autoscaler.decisions.
decision_log = logging.getLogger("kubeai.planner.decisions")

# Strict priority order: earlier classes take chips first; later classes
# are preempted first. Mirrors the engine scheduler's priority bands
# (kubeai_tpu/scheduling) — a model's class is its CRD
# `scheduling.defaultPriority` (standard when unset).
SCHEDULING_CLASSES = ("realtime", "standard", "batch")


def model_scheduling_class(model) -> str:
    cls = model.spec.scheduling.default_priority or "standard"
    return cls if cls in SCHEDULING_CLASSES else "standard"


def model_num_hosts(model, cfg) -> int:
    """Host pods per replica: spec.sharding.hosts when set, else the
    resource profile's numHosts, else 1. A multi-host replica is an
    atomic N-pod group — the planner sizes and places it whole."""
    sharding = getattr(model.spec, "sharding", None)
    if sharding is not None and sharding.hosts:
        return max(1, sharding.hosts)
    if cfg is not None and model.spec.resource_profile:
        name, _, _count = model.spec.resource_profile.partition(":")
        prof = (cfg.resource_profiles or {}).get(name)
        if prof is not None:
            return max(1, getattr(prof, "num_hosts", 1) or 1)
    return 1


def model_chips_per_replica(model, cfg, pods_entry: dict | None) -> int:
    """Chips one replica of this model occupies: observed from its live
    pods' `google.com/tpu` requests when any exist, else derived from
    its resource profile (`name:count` multiplies the profile's chip
    request), else 1 — a model the planner cannot size still costs
    SOMETHING, or an unsizable model would bin-pack for free. For a
    multi-host model one replica is `hosts` pods, so both paths scale
    by the group size: a 2-host x8-chip replica is 16 chips, placed
    atomically in one slice."""
    pods_entry = pods_entry or {}
    total = pods_entry.get("total") or 0
    chips = pods_entry.get("chips") or 0
    hosts = model_num_hosts(model, cfg)
    if total > 0 and chips > 0:
        return max(1, round(chips / total)) * hosts
    if cfg is not None and model.spec.resource_profile:
        name, _, count_s = model.spec.resource_profile.partition(":")
        prof = (cfg.resource_profiles or {}).get(name)
        try:
            count = max(1, int(count_s))
        except (TypeError, ValueError):
            count = 1
        if prof is not None:
            v = (prof.limits or {}).get(k8sutils.TPU_RESOURCE) or (
                prof.requests or {}
            ).get(k8sutils.TPU_RESOURCE)
            per = k8sutils.parse_chip_quantity(v, where=f"profile {name}")
            if per > 0:
                return per * count * hosts
    return 1


class _ShapePool:
    """Mutable free-chip accounting for one slice shape during packing."""

    __slots__ = ("shape", "slice_chips", "chips", "free")

    def __init__(self, shape: str, slice_chips: int, chips: int):
        self.shape = shape
        self.slice_chips = slice_chips
        self.chips = chips
        self.free = chips


class CapacityPlanner:
    """Fleet-level replica arbiter over one `FleetStateAggregator`.

    `avg_lookup(model_name) -> float | None` is injectable: the manager
    wires it to `Autoscaler.current_average` so plan desires use the
    same smoothed active-request signal the direct scaling path uses
    (falling back to the snapshot's instantaneous active-request sum).
    `clock` drives plan timestamps and staleness (FakeClock in the
    deterministic sim)."""

    def __init__(
        self,
        fleet,
        model_client,
        store=None,
        cfg=None,
        namespace: str = "default",
        metrics: Metrics = DEFAULT_METRICS,
        leader=None,
        interval_s: float = 10.0,
        staleness_s: float | None = None,
        preemption_enabled: bool = True,
        budget_override: dict | None = None,
        clock=time.time,
        governor=None,
        forecaster=None,
    ):
        self.fleet = fleet
        self.model_client = model_client
        self.store = store
        self.cfg = cfg
        self.namespace = namespace
        self.metrics = metrics
        self.leader = leader
        self.interval_s = interval_s
        # Plans (and the snapshots they came from) older than this are
        # stale: allocation_for returns None and the autoscaler scales
        # directly. Same 3×interval default as the aggregator.
        self.staleness_s = (
            staleness_s if staleness_s is not None else 3.0 * interval_s
        )
        self.preemption_enabled = preemption_enabled
        # {shape: {"chips": N, "slice_chips": c}} — overrides the
        # snapshot's Node-derived budget (clusters where the operator
        # cannot list Nodes configure capacity explicitly).
        self.budget_override = budget_override
        # Actuation governor (operator/governor): preemption marks are
        # fenced on lease validity and gated on telemetry coverage; the
        # permissive default never refuses.
        from kubeai_tpu.operator import governor as governor_mod

        self.governor = governor or governor_mod.PERMISSIVE
        # DemandForecaster (fleet/forecaster): enables the prewarm pass
        # and cold-start-priced arbitration. None → both are no-ops.
        self.forecaster = forecaster
        self.avg_lookup = None
        # SLO evaluator (fleet/slo) + flight recorder, wired by the
        # manager: a fast-burning objective asserts slo_pressure even
        # when the queue looks calm (latency regressions burn budget
        # without backlog), and preemption marks land in the flight
        # ring so incident bundles show capacity decisions.
        self.slo = None
        self.recorder = None
        self._clock = clock
        self._lock = threading.Lock()
        self._plan: dict | None = None
        self.last_decisions: list[dict] = []
        self._prev_series: dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — next tick retries
                logger.warning("capacity planning tick failed: %s", e)

    # -- one planning tick -----------------------------------------------------

    def tick(self, force: bool = False) -> dict | None:
        """Plan from the aggregator's latest snapshot. Returns the plan,
        or None when not leader (unless forced) or the snapshot is
        stale/missing — in which case the previous plan ages out and the
        autoscaler falls back to direct scaling."""
        if not force and self.leader is not None and not self.leader.is_leader:
            return None
        snap = self.fleet.snapshot() if self.fleet is not None else None
        now = self._clock()
        if snap is None or now - snap["ts"] > self.staleness_s:
            self.metrics.planner_stale_ticks.inc()
            return None
        plan = self.plan_from_snapshot(snap)
        with self._lock:
            self._plan = plan
            self.last_decisions = list(plan["models"].values())
        self._publish(plan)
        if self.store is not None and self.preemption_enabled:
            try:
                self._mark_preemption_victims(plan)
            except Exception as e:  # noqa: BLE001 — marking is advisory
                logger.warning("preemption marking failed: %s", e)
        self.metrics.planner_ticks.inc()
        return plan

    # -- desires ---------------------------------------------------------------

    def _threshold(self) -> float:
        if self.cfg is not None:
            return self.cfg.model_autoscaling.queue_pressure_max_wait_seconds
        return 3.0

    def _slo_burn(self, model_name: str) -> dict | None:
        """The SLO evaluator's pressure read for this model, or None
        when no evaluator is wired / the model was not judged."""
        if self.slo is None:
            return None
        try:
            return self.slo.pressure(model_name)
        except Exception:  # noqa: BLE001 — advisory signal only
            return None

    def _unified_desire(self, model, entry: dict) -> dict:
        avg = self.avg_lookup(model.name) if self.avg_lookup else None
        if avg is None:
            avg = sum(
                e.get("active_requests", 0.0)
                for e in (entry.get("endpoints") or {}).values()
                if not e.get("stale")
            )
        queue = entry.get("queue") or {
            "depth": 0.0, "oldest_wait_s": 0.0, "per_class": {},
        }
        threshold = self._threshold()
        burn = self._slo_burn(model.name)
        desired = desired_unified_replicas(
            avg, queue, model.spec.target_requests, threshold
        )
        floor = model.spec.min_replicas
        target = max(desired, floor)
        if model.spec.max_replicas is not None:
            target = min(target, model.spec.max_replicas)
        return {
            "kind": "unified",
            "signal": avg,
            "desired": desired,
            "target": target,
            "floor": floor,
            "target_requests": model.spec.target_requests,
            "max_replicas": model.spec.max_replicas,
            "prewarm_allowed": model.spec.cold_start.prewarm,
            "slo_pressure": bool(
                (threshold > 0 and queue["oldest_wait_s"] >= threshold)
                or (burn is not None and burn["level"] >= 2)
            ),
            "slo_burn": (burn or {}).get("state", ""),
            "queue_depth": queue["depth"],
            "queue_oldest_wait_s": queue["oldest_wait_s"],
        }

    def _disagg_desire(self, model, entry: dict) -> dict:
        dis = model.spec.disaggregation
        replicas = entry.get("replicas") or {}
        roles = entry.get("roles") or {}
        pre_sig = roles.get(md.ROLE_PREFILL) or aggregate_role_signals({})
        dec_sig = roles.get(md.ROLE_DECODE) or aggregate_role_signals({})
        threshold = self._threshold()
        burn = self._slo_burn(model.name)
        desired_pre = desired_prefill_replicas(
            pre_sig, replicas.get(md.ROLE_PREFILL, 0), dis, threshold
        )
        desired_dec, slot_occ, util = desired_decode_replicas(
            dec_sig, replicas.get(md.ROLE_DECODE, 0), dis
        )
        desired_roles = {
            md.ROLE_PREFILL: desired_pre, md.ROLE_DECODE: desired_dec,
        }
        floor_roles: dict[str, int] = {}
        target_roles: dict[str, int] = {}
        for role, desired in desired_roles.items():
            rs = dis.role(role)
            floor = max(1, rs.min_replicas)
            target = max(desired, floor)
            if rs.max_replicas is not None:
                target = min(target, rs.max_replicas)
            floor_roles[role] = floor
            target_roles[role] = target
        return {
            "kind": "disagg",
            "signal": pre_sig["depth"],
            "desired_roles": desired_roles,
            "target_roles": target_roles,
            "floor_roles": floor_roles,
            "slo_pressure": bool(
                (threshold > 0 and pre_sig["oldest_wait_s"] >= threshold)
                or (
                    dis.prefill_target_ttft_seconds > 0
                    and pre_sig["ttft_mean_s"]
                    > dis.prefill_target_ttft_seconds
                )
                or (burn is not None and burn["level"] >= 2)
            ),
            "slo_burn": (burn or {}).get("state", ""),
            "kv_utilization": util,
            "slot_occupancy": slot_occ,
        }

    # -- bin-packing -----------------------------------------------------------

    def _pools(self, snap: dict) -> list[_ShapePool]:
        if self.budget_override is not None:
            src = {
                shape: (
                    int(b.get("chips", 0)),
                    int(b.get("slice_chips", b.get("chips", 0))),
                )
                for shape, b in self.budget_override.items()
            }
        else:
            budget = (snap.get("chips") or {}).get("budget") or {}
            src = {
                shape: (
                    int(chips),
                    int((budget.get("slice_chips") or {}).get(shape, chips)),
                )
                for shape, chips in (budget.get("by_shape") or {}).items()
            }
        pools = [
            _ShapePool(shape, slice_chips, chips)
            for shape, (chips, slice_chips) in src.items()
            if chips > 0
        ]
        # Cheapest slice first: right-sizing tries the smallest slice
        # that can host the replica before spilling to bigger iron.
        pools.sort(key=lambda p: (p.slice_chips, p.shape))
        return pools

    @staticmethod
    def _place(pools: list[_ShapePool], chips: int) -> str | None:
        for p in pools:
            if p.slice_chips >= chips and p.free >= chips:
                p.free -= chips
                return p.shape
        return None

    @staticmethod
    def _next_role(e: dict) -> str | None:
        """The disagg role to grant next: lowest allocated/target
        fraction first, so both roles fill (and shrink) toward the
        desired ratio jointly instead of per-role."""
        best, best_frac = None, None
        for role in md.DISAGG_ROLES:
            target = e["target_roles"][role]
            if e["alloc_roles"][role] >= target:
                continue
            frac = e["alloc_roles"][role] / target
            if best is None or frac < best_frac:
                best, best_frac = role, frac
        return best

    def _grant_rounds(
        self, entries: list[dict], pools: list[_ShapePool],
        to_floor: bool,
    ) -> None:
        """Water-fill: one replica per model per round until either the
        target (floor or full) is met everywhere or nothing fits."""
        progressed = True
        while progressed:
            progressed = False
            for e in entries:
                if e["kind"] == "disagg":
                    role = None
                    if to_floor:
                        for r in md.DISAGG_ROLES:
                            if e["alloc_roles"][r] < min(
                                e["floor_roles"][r], e["target_roles"][r]
                            ):
                                role = r
                                break
                    else:
                        role = self._next_role(e)
                    if role is None:
                        continue
                    shape = self._place(pools, e["chips_per_replica"])
                    if shape is None:
                        continue
                    e["alloc_roles"][role] += 1
                    e["shapes"][shape] = e["shapes"].get(shape, 0) + 1
                    progressed = True
                else:
                    limit = (
                        min(e["floor"], e["target"]) if to_floor
                        else e["target"]
                    )
                    if e["alloc"] >= limit:
                        continue
                    shape = self._place(pools, e["chips_per_replica"])
                    if shape is None:
                        continue
                    e["alloc"] += 1
                    e["shapes"][shape] = e["shapes"].get(shape, 0) + 1
                    progressed = True

    # -- predictive prewarm / cold-start pricing -------------------------------

    def _attach_forecasts(self, planned: list[dict]) -> dict:
        """Forecast every planned model once per tick and stamp the
        measured cold-start cost onto its entry (the arbitration price).
        No forecaster → every model prices at the conservative default
        and nothing triggers."""
        from kubeai_tpu.fleet import forecaster as forecaster_mod

        forecasts: dict[str, object] = {}
        for e in planned:
            fc = None
            if self.forecaster is not None:
                try:
                    fc = self.forecaster.forecast(e["model"])
                except Exception as err:  # noqa: BLE001 — advisory path
                    logger.warning(
                        "demand forecast for %s failed: %s",
                        e["model"], err,
                    )
            forecasts[e["model"]] = fc
            e["coldstart_cost_s"] = (
                fc.coldstart_cost_s if fc is not None
                else forecaster_mod.DEFAULT_COLDSTART_S
            )
            e["prewarm"] = 0
            e["prewarm_trigger"] = ""
        return forecasts

    @staticmethod
    def _priced(entries: list[dict]) -> list[dict]:
        """Demand-fill order within a class: expensive-to-boot models
        take chips first, so when the class's budget runs out the
        shortfall (throttle, then preemption) lands on the models whose
        replicas restore from a snapshot in seconds — re-adding THEIR
        capacity later is cheap."""
        return sorted(
            entries,
            key=lambda e: (-e["coldstart_cost_s"], e["model"]),
        )

    def _prewarm_pass(
        self, planned: list[dict], forecasts: dict,
        pools: list[_ShapePool], budget_known: bool,
    ) -> None:
        """Grant warm replicas ahead of forecast demand from whatever
        chips the demand fill left free. Unified models only (a disagg
        pair's role balance is the demand pass's job); each grant is
        clamped by `maxReplicas` and gated per model by the actuation
        governor — a prewarm creates pods and obeys the same fencing
        and coverage gates as any other scale actuation."""
        from kubeai_tpu.fleet import forecaster as forecaster_mod

        for e in planned:
            fc = forecasts.get(e["model"])
            if fc is None or not fc.warm_trigger or e["kind"] != "unified":
                continue
            if not e.get("prewarm_allowed", True):
                continue  # CRD coldStart.prewarm=false opts the model out
            if fc.trigger == forecaster_mod.TRIGGER_SPOT:
                # Capacity is being reclaimed: warm one replacement per
                # disrupted pod before the autoscaler notices the gap.
                need = max(1, fc.spot_disruptions)
            else:
                per = max(1.0, float(e.get("target_requests") or 1))
                need = max(
                    1, math.ceil((fc.predicted - fc.current) / per)
                )
            if e.get("max_replicas") is not None:
                need = min(need, e["max_replicas"] - e["alloc"])
            if need <= 0:
                continue
            if not self.governor.allow_prewarm(e["model"]):
                continue  # the governor counted and logged the denial
            granted = 0
            for _ in range(need):
                if budget_known:
                    shape = self._place(pools, e["chips_per_replica"])
                    if shape is None:
                        break
                    e["shapes"][shape] = e["shapes"].get(shape, 0) + 1
                e["alloc"] += 1
                granted += 1
            if granted:
                e["prewarm"] = granted
                e["prewarm_trigger"] = fc.trigger
                self.metrics.prewarm_orders.inc(
                    granted, model=e["model"], trigger=fc.trigger
                )

    def plan_from_snapshot(self, snap: dict) -> dict:
        now = self._clock()
        models = self.model_client.list_all_models()
        pools = self._pools(snap)
        budget_known = bool(pools)
        budget_total = sum(p.chips for p in pools)

        entries: list[dict] = []
        for model in sorted(models, key=lambda m: m.name):
            entry = (snap.get("models") or {}).get(model.name) or {}
            pods_entry = entry.get("pods") or {}
            cpr = model_chips_per_replica(model, self.cfg, pods_entry)
            cls = model_scheduling_class(model)
            replicas = entry.get("replicas") or {}
            # Replica counts, not pod counts: a multi-host model's pod
            # inventory is hosts× its replica count.
            hosts = model_num_hosts(model, self.cfg)
            pod_total = pods_entry.get("total") or 0
            current_pods = pod_total // hosts if hosts > 1 else pod_total
            if model.spec.autoscaling_disabled:
                # Not under plan control, but its chips are spoken for:
                # reserve them off the top so arbitration sees the true
                # remaining budget.
                current = current_pods or (
                    model.spec.replicas or 0
                )
                e = {
                    "kind": "fixed", "model": model.name, "class": cls,
                    "chips_per_replica": cpr, "current": current,
                    "alloc": current, "shapes": {},
                }
                for _ in range(current):
                    shape = self._place(pools, cpr)
                    if shape is None:
                        break
                    e["shapes"][shape] = e["shapes"].get(shape, 0) + 1
                entries.append(e)
                continue
            if model.spec.disaggregation.enabled:
                d = self._disagg_desire(model, entry)
                by_role = pods_entry.get("by_role") or {}
                d["current_roles"] = {
                    role: by_role.get(role) or replicas.get(role, 0)
                    for role in md.DISAGG_ROLES
                }
                d["alloc_roles"] = {role: 0 for role in md.DISAGG_ROLES}
            else:
                d = self._unified_desire(model, entry)
                d["current"] = current_pods or sum(
                    replicas.values()
                ) or (model.spec.replicas or 0)
                d["alloc"] = 0
            d.update(
                model=model.name, **{"class": cls},
                chips_per_replica=cpr, shapes={},
            )
            entries.append(d)

        planned = [e for e in entries if e["kind"] != "fixed"]
        forecasts = self._attach_forecasts(planned)
        # The demand-fill pricing, made observable: each model's position
        # in its class's `_priced` order rides on the plan record (0 =
        # granted first = most expensive to boot). The federation router
        # reads the same records to rank remote-cold-start costs, so the
        # ordering must be inspectable at /v1/fleet/plan, not implicit.
        for cls in SCHEDULING_CLASSES:
            for rank, e in enumerate(
                self._priced([e for e in planned if e["class"] == cls])
            ):
                e["priced_rank"] = rank
        if budget_known:
            # Floors are CRD guarantees — honored across ALL classes
            # first (in priority order), then demand water-fills per
            # class so batch demand only sees what realtime left over.
            for cls in SCHEDULING_CLASSES:
                self._grant_rounds(
                    [e for e in planned if e["class"] == cls], pools,
                    to_floor=True,
                )
            for cls in SCHEDULING_CLASSES:
                self._grant_rounds(
                    self._priced(
                        [e for e in planned if e["class"] == cls]
                    ),
                    pools,
                    to_floor=False,
                )
        else:
            # Unknown budget: plan unconstrained (allocation == desire,
            # no preemption) — exactly the pre-planner behavior.
            for e in planned:
                if e["kind"] == "disagg":
                    e["alloc_roles"] = dict(e["target_roles"])
                else:
                    e["alloc"] = e["target"]
        self._prewarm_pass(planned, forecasts, pools, budget_known)

        records: dict[str, dict] = {}
        chips_allocated = 0
        preemptions: list[dict] = []
        for e in entries:
            base = {
                "ts": now,
                "model": e["model"],
                "class": e["class"],
                "kind": e["kind"],
                "chips_per_replica": e["chips_per_replica"],
                "shapes": dict(e["shapes"]),
                "telemetry_source": "aggregator",
                "snapshot_age_s": round(max(0.0, now - snap["ts"]), 3),
            }
            if e["kind"] == "fixed":
                chips = e["alloc"] * e["chips_per_replica"]
                base.update(
                    current_replicas=e["current"],
                    allocated_replicas=e["alloc"],
                    chips_allocated=chips,
                )
            elif e["kind"] == "disagg":
                alloc_total = sum(e["alloc_roles"].values())
                chips = alloc_total * e["chips_per_replica"]
                preempted = {
                    role: max(
                        0,
                        min(e["current_roles"][role],
                            e["target_roles"][role])
                        - e["alloc_roles"][role],
                    )
                    for role in md.DISAGG_ROLES
                }
                throttled = sum(
                    max(0, e["target_roles"][r] - e["alloc_roles"][r])
                    for r in md.DISAGG_ROLES
                )
                base.update(
                    signal=e["signal"],
                    slo_pressure=e["slo_pressure"],
                    slo_burn=e.get("slo_burn", ""),
                    desired_roles=dict(e["desired_roles"]),
                    target_roles=dict(e["target_roles"]),
                    allocated_roles=dict(e["alloc_roles"]),
                    current_roles=dict(e["current_roles"]),
                    kv_utilization=e["kv_utilization"],
                    slot_occupancy=e["slot_occupancy"],
                    throttled_replicas=throttled,
                    preempted_replicas=sum(preempted.values()),
                    preempted_roles=preempted,
                    chips_allocated=chips,
                )
            else:
                chips = e["alloc"] * e["chips_per_replica"]
                preempted = max(
                    0, min(e["current"], e["target"]) - e["alloc"]
                )
                base.update(
                    signal=e["signal"],
                    slo_pressure=e["slo_pressure"],
                    slo_burn=e.get("slo_burn", ""),
                    queue_depth=e["queue_depth"],
                    queue_oldest_wait_s=e["queue_oldest_wait_s"],
                    desired_replicas=e["desired"],
                    target_replicas=e["target"],
                    allocated_replicas=e["alloc"],
                    current_replicas=e["current"],
                    throttled_replicas=max(0, e["target"] - e["alloc"]),
                    preempted_replicas=preempted,
                    chips_allocated=chips,
                )
            if e["kind"] != "fixed":
                base.update(
                    coldstart_cost_s=round(e["coldstart_cost_s"], 3),
                    priced_rank=e["priced_rank"],
                    prewarm_replicas=e.get("prewarm", 0),
                    prewarm_trigger=e.get("prewarm_trigger", ""),
                )
                fc = forecasts.get(e["model"])
                if fc is not None:
                    base["forecast"] = fc.payload()
            chips_allocated += chips
            if base.get("preempted_replicas"):
                preemptions.append(
                    {
                        "model": e["model"],
                        "class": e["class"],
                        "replicas": base["preempted_replicas"],
                    }
                )
            records[e["model"]] = base

        return {
            "ts": now,
            "snapshot_ts": snap["ts"],
            "telemetry_source": "aggregator",
            "budget_known": budget_known,
            "budget": {
                "total": budget_total,
                "by_shape": {p.shape: p.chips for p in pools},
                "slice_chips": {p.shape: p.slice_chips for p in pools},
            },
            "allocated_chips": {
                "total": chips_allocated,
                "by_shape": {
                    p.shape: p.chips - p.free for p in pools
                },
            },
            "free_chips": {
                "total": max(0, budget_total - chips_allocated),
                "by_shape": {p.shape: p.free for p in pools},
            },
            "preemptions": preemptions,
            "models": records,
        }

    # -- preemption marking (pod_plan honors the annotation) -------------------

    def _mark_preemption_victims(self, plan: dict) -> None:
        """Annotate the pods the plan takes away so pod_plan deletes
        exactly them first; strip the mark from pods no longer picked so
        a recovered model's deletions revert to the generic ordering.

        Every record — including `fixed` (autoscaling-disabled) models
        and models the governor refuses preemption for — still runs the
        unmark sweep: a `kubeai.org/planner-preempt` annotation from an
        outdated tick must never linger where the current plan (or the
        governor) no longer selects a victim, or
        `sort_pods_by_deletion_order` would act on stale picks."""
        for name, rec in plan["models"].items():
            pods = self.store.list(
                "Pod", self.namespace, {md.POD_MODEL_LABEL: name}
            )
            victims: set[str] = set()
            if rec["kind"] == "fixed":
                pass  # not under plan control: clear stale marks only
            elif rec["kind"] == "disagg":
                for role in md.DISAGG_ROLES:
                    if not rec["preempted_roles"].get(role):
                        continue
                    n_del = max(
                        0,
                        rec["current_roles"][role]
                        - rec["allocated_roles"][role],
                    )
                    role_pods = [
                        p for p in pods
                        if k8sutils.get_label(p, md.POD_ROLE_LABEL) == role
                    ]
                    victims.update(self._pick_victims(role_pods, n_del))
            elif rec.get("preempted_replicas"):
                n_del = max(
                    0, rec["current_replicas"] - rec["allocated_replicas"]
                )
                victims.update(self._pick_victims(pods, n_del))
            if victims and not self.governor.allow_preemption(name):
                # Governor refused (stale telemetry, low coverage, or an
                # invalid lease): mark nothing — and fall through so any
                # marks from an earlier tick are stripped too.
                victims = set()
            for pod in pods:
                pod_name = pod["metadata"]["name"]
                ann = (pod.get("metadata") or {}).get("annotations") or {}
                marked = md.PLANNER_PREEMPT_ANNOTATION in ann
                want = pod_name in victims
                if marked == want:
                    continue
                if want:
                    pod["metadata"].setdefault("annotations", {})[
                        md.PLANNER_PREEMPT_ANNOTATION
                    ] = md.PREEMPT_REASON_CAPACITY
                    if self.recorder is not None:
                        self.recorder.record(
                            flightrecorder.PLANNER_PREEMPT, "planner",
                            target=name, pod=pod_name,
                            cls=rec.get("class", ""),
                        )
                else:
                    pod["metadata"]["annotations"].pop(
                        md.PLANNER_PREEMPT_ANNOTATION, None
                    )
                try:
                    self.store.update(pod)
                except (Conflict, NotFound):
                    continue  # next tick re-marks against fresh state

    @staticmethod
    def _pick_victims(pods: list[dict], n: int) -> list[str]:
        """Youngest non-terminating pods first — the least-warm replicas
        (matching the generic ordering's final tiebreak, but pinned by
        the planner so the choice survives whatever else the reconcile
        is doing)."""
        if n <= 0:
            return []
        candidates = [
            p for p in pods if not k8sutils.pod_is_terminating(p)
        ]
        candidates.sort(
            key=lambda p: -(
                (p.get("metadata") or {}).get("creationTimestamp") or 0
            )
        )
        return [p["metadata"]["name"] for p in candidates[:n]]

    # -- publishing ------------------------------------------------------------

    def _publish(self, plan: dict) -> None:
        m = self.metrics
        new_series: dict[str, tuple] = {}

        def set_(gauge, value, **labels):
            gauge.set(value, **labels)
            new_series.setdefault(gauge.name, (gauge, set()))[1].add(
                tuple(sorted(labels.items()))
            )

        for name, rec in plan["models"].items():
            decision_log.info(json.dumps(rec, sort_keys=True))
            if rec["kind"] == "disagg":
                for role in md.DISAGG_ROLES:
                    set_(
                        m.planner_desired_replicas,
                        rec["desired_roles"][role], model=name, role=role,
                    )
                    set_(
                        m.planner_allocated_replicas,
                        rec["allocated_roles"][role], model=name, role=role,
                    )
            else:
                role = md.ROLE_UNIFIED
                if rec["kind"] == "fixed":
                    set_(
                        m.planner_allocated_replicas,
                        rec["allocated_replicas"], model=name, role=role,
                    )
                else:
                    set_(
                        m.planner_desired_replicas,
                        rec["desired_replicas"], model=name, role=role,
                    )
                    set_(
                        m.planner_allocated_replicas,
                        rec["allocated_replicas"], model=name, role=role,
                    )
            if rec["kind"] != "fixed":
                set_(
                    m.planner_throttled_replicas,
                    rec["throttled_replicas"], model=name,
                )
                set_(
                    m.planner_preempted_replicas,
                    rec["preempted_replicas"], model=name,
                )
                if rec["preempted_replicas"]:
                    m.planner_preemptions.inc(
                        rec["preempted_replicas"], model=name
                    )
                set_(
                    m.prewarm_replicas,
                    rec.get("prewarm_replicas", 0), model=name,
                )
                set_(
                    m.prewarm_coldstart_cost,
                    rec.get("coldstart_cost_s", 0.0), model=name,
                )
                fc = rec.get("forecast")
                if fc is not None:
                    set_(
                        m.prewarm_forecast_demand,
                        fc["predicted"], model=name,
                    )
        for shape, chips in plan["allocated_chips"]["by_shape"].items():
            set_(m.planner_chips_allocated, chips, shape=shape)
        for shape, chips in plan["free_chips"]["by_shape"].items():
            set_(m.planner_chips_free, chips, shape=shape)
        m.planner_plan_ts.set(plan["ts"])
        # Retired label sets (model deleted, shape drained) must not
        # linger as frozen series.
        for name, (gauge, keys) in self._prev_series.items():
            current = new_series.get(name, (gauge, set()))[1]
            for k in keys - current:
                gauge.remove(**dict(k))
        self._prev_series = new_series

    # -- consumer API ----------------------------------------------------------

    def current_plan(self) -> dict | None:
        with self._lock:
            return self._plan

    def _fresh_plan(self) -> dict | None:
        plan = self.current_plan()
        if plan is None:
            return None
        if self._clock() - plan["ts"] > self.staleness_s:
            return None
        return plan

    def allocation_for(self, model_name: str) -> dict | None:
        """The autoscaler's override read: the fresh plan's allocation
        for one model (`{"replicas": n}` unified, `{"roles": {...}}`
        disaggregated), or None when the plan is stale/missing or the
        model is not under plan control (→ direct scaling fallback)."""
        plan = self._fresh_plan()
        if plan is None:
            return None
        rec = plan["models"].get(model_name)
        if rec is None or rec["kind"] == "fixed":
            return None
        if rec["kind"] == "disagg":
            return {
                "roles": dict(rec["allocated_roles"]),
                "class": rec["class"],
                "plan_ts": plan["ts"],
            }
        return {
            "replicas": rec["allocated_replicas"],
            "class": rec["class"],
            "plan_ts": plan["ts"],
            # Prewarm grants are already folded into the replica count —
            # the autoscaler actuates them through the governed pod path
            # like any other scale-up; this field is visibility only.
            "prewarm_replicas": rec.get("prewarm_replicas", 0),
        }

    def plan_payload(self) -> dict:
        """`GET /v1/fleet/plan`: the latest plan, recomputed when none
        exists or the latest aged out (forced past the leader gate — a
        read must answer on any replica that can see a snapshot)."""
        plan = self._fresh_plan()
        if plan is None:
            self.tick(force=True)
            plan = self.current_plan()
        if plan is None:
            return {
                "object": "fleet.plan",
                "plan_available": False,
                "stale": True,
            }
        age = max(0.0, self._clock() - plan["ts"])
        payload = {
            "object": "fleet.plan",
            "plan_available": True,
            "stale": age > self.staleness_s,
            "age_s": round(age, 3),
        }
        payload.update(plan)
        return payload
