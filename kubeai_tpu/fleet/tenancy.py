"""Tenant-aware overload protection: the front door's admission layer.

One abusive tenant must not be able to queue unbounded work into every
engine and move every other tenant's p99. ``TenantGovernor`` is
enforced in the front door (routing/openai_server.py) and the pub/sub
messenger (routing/messenger.py) BEFORE any work is queued anywhere —
before model scale-up, before the load-balancer wait, before a byte
reaches an engine. Three independent checks, cheapest first:

  1. **Per-tenant token buckets** — requests/s and estimated-tokens/s
     with configurable burst, keyed (tenant, model). The token estimate
     is body bytes / 4 plus the request's ``max_tokens``: cheap, done
     before any queueing, and good enough for flow control (exact
     accounting stays with the UsageMeter ledger).
  2. **Rolling-window token-budget quotas** — fed by the existing
     ``UsageMeter`` ledger's exact integers: usage inside the window is
     the ledger's cumulative count minus its value at the window start.
     A tenant over budget is refused until the window resets.
  3. **Global overload mode** — when fleet-wide queue pressure (summed
     from the FleetStateAggregator snapshot, with a direct collect()
     sweep as the stale fallback) crosses the configured high-water
     mark, the door sheds lowest-scheduling-class-first: ``batch`` at
     the high-water mark, ``standard`` at ``overload_standard_factor``
     times it, and ``realtime`` NEVER (realtime degrades last; the
     engine scheduler's own admission control remains its backstop).
     A low-water mark provides hysteresis.

Every refusal carries a COMPUTED, jittered ``Retry-After``
(kubeai_tpu/utils/retryafter): time-to-bucket-refill for rate limits,
time-to-window-reset for quotas, the fleet's oldest queued wait for
overload sheds — never a magic constant.

Config: system ``tenancy:`` defaults (config/system.py TenancyConfig)
plus a per-model CRD ``tenancy:`` block (crd/model.py Tenancy) that
overrides the per-tenant limits. This is DOOR state — it renders into
no engine flag or pod spec. Disabled (the default) means the governor
is never constructed and the serving path is byte-identical to a
build without it.

Metric cardinality is bounded: at most ``max_tenant_series`` distinct
tenant label values appear on ``kubeai_door_*`` series (overflow
tenants aggregate into ``other``), and churned tenants' series are
removed by the idle-cleanup pass, the same label-churn discipline the
fleet aggregator applies to endpoint gauges.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

from kubeai_tpu.fleet.metering import ANONYMOUS_TENANT, tenant_of
from kubeai_tpu.metrics import flightrecorder
from kubeai_tpu.metrics.registry import DEFAULT_METRICS, Metrics
from kubeai_tpu.utils import retryafter

# Scheduling classes, highest precedence first (duplicated from
# kubeai_tpu/scheduling/scheduler.py PRIORITY_CLASSES so the door stays
# import-light — the engine package pulls in jax).
PRIORITY_CLASSES = ("realtime", "standard", "batch")
CLASS_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}

OVERFLOW_TENANT_LABEL = "other"

REASON_RATE = "rate"
REASON_TOKENS = "tokens"
REASON_QUOTA = "quota"
REASON_OVERLOAD = "overload"


@dataclasses.dataclass(frozen=True)
class DoorPolicy:
    """The resolved per-model admission policy: system ``tenancy:``
    defaults with the model's CRD ``tenancy:`` overrides applied.
    0 = unlimited for every rate/budget field."""

    requests_per_second: float = 0.0
    request_burst: float = 0.0
    tokens_per_second: float = 0.0
    token_burst: float = 0.0
    window_seconds: float = 0.0
    window_token_budget: int = 0
    exempt: bool = False


@dataclasses.dataclass
class Refusal:
    """One admission refusal: everything the HTTP/messenger layer needs
    to answer 429 honestly."""

    tenant: str
    model: str
    reason: str          # rate | tokens | quota | overload
    message: str
    retry_after_s: float  # computed + jittered, never a constant
    status: int = 429


class _TokenBucket:
    """Classic token bucket on an injected clock. ``take`` either
    consumes and admits, or refuses with the computed time until enough
    tokens will have refilled."""

    __slots__ = ("rate", "burst", "tokens", "updated", "clipped")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.tokens = self.burst
        self.updated = now
        self.clipped = 0.0

    def take(self, n: float, now: float) -> tuple[bool, float]:
        if now > self.updated:
            raw = self.tokens + (now - self.updated) * self.rate
            if raw > self.burst:
                self.clipped += raw - self.burst
                raw = self.burst
            self.tokens = raw
        self.updated = max(self.updated, now)
        if self.tokens >= n:
            self.tokens -= n
            return True, 0.0
        deficit = n - self.tokens
        if self.rate <= 0.0:
            return False, float("inf")
        return False, deficit / self.rate

    def drain(self, n: float, now: float) -> None:
        """Remove ``n`` tokens without the admit gate — folds in
        consumption observed from peer door shards via gossip. May push
        the balance negative (debt): every shard's bucket is drained by
        every shard's admissions, which is exactly what makes N doors
        enforce ONE global budget instead of N.

        The refill and the fold are applied ATOMICALLY — subtract
        before clipping at burst. Folds lag real consumption by the
        gossip interval; clipping the refill first would discard
        tokens the already-pending fold still claims, ratcheting
        every shard's balance toward zero even when the tenant runs
        exactly at its global rate."""
        raw = self.tokens + max(0.0, now - self.updated) * self.rate - n
        if raw > self.burst:
            self.clipped += raw - self.burst
            raw = self.burst
        self.tokens = raw
        self.updated = max(self.updated, now)

    def pop_clipped(self) -> float:
        """Return and reset refill lost to the burst cap since the
        last call. Only the gossip fold path reads this — a single
        door discards clip exactly as the classic bucket does."""
        c = self.clipped
        self.clipped = 0.0
        return c


def estimate_tokens(body: bytes, parsed: dict | None = None) -> int:
    """Pre-queue token estimate for the tokens/s bucket: prompt bytes at
    ~4 bytes/token plus the requested completion budget. Deliberately
    crude — it runs before any tokenizer and only drives flow control;
    billing uses the UsageMeter's exact post-hoc counts."""
    est = max(1, len(body) // 4)
    if isinstance(parsed, dict):
        for key in ("max_tokens", "max_completion_tokens"):
            v = parsed.get(key)
            if isinstance(v, int) and not isinstance(v, bool) and v > 0:
                est += v
                break
    return est


class TenantGovernor:
    """Front-door admission governor. Thread-safe; shared by the HTTP
    front door and every messenger stream. Clock-injected so the abuse
    sim (benchmarks/tenant_isolation_sim.py) drives it deterministically.
    """

    def __init__(
        self,
        cfg,                      # config.system.TenancyConfig
        usage=None,               # fleet.metering.UsageMeter (quota feed)
        fleet=None,               # fleet.aggregator.FleetStateAggregator
        model_client=None,        # routing.modelclient.ModelClient
        metrics: Metrics = DEFAULT_METRICS,
        clock=time.monotonic,
        pressure_fn=None,         # test seam: () -> {"depth", "oldest_wait_s"}
        pressure_ttl_s: float = 1.0,
        gossip=None,              # routing.gossip.DoorGossipNode (sharded door)
    ):
        self.cfg = cfg
        self.usage = usage
        self.fleet = fleet
        self.model_client = model_client
        self.metrics = metrics
        self._clock = clock
        self._pressure_fn = pressure_fn
        self._pressure_ttl = pressure_ttl_s
        # The gossiped CRDT state plane handle when this governor is one
        # of N door shards: bucket consumption folds through it, the
        # overload latch lives in its LWW register, and quota reads span
        # peer-shard ledgers. None -> classic single-door arithmetic,
        # byte-identical to the pre-sharding build.
        self.gossip = gossip
        # Flight recorder (metrics.flightrecorder.FlightRecorder), wired
        # by the manager when the SLO plane is on: every refusal lands
        # in the door ring so an incident bundle shows WHO was turned
        # away in the minutes before a page, not just how many.
        self.recorder = None  # local-state: wiring seam set by the manager, not request state
        self._lock = threading.Lock()  # local-state: process-local mutex, not replicated data
        # (tenant, model) -> {"req": bucket|None, "tok": bucket|None,
        #  "seen": ts, "req_rem"/"tok_rem": peer consumption already
        #  folded into the bucket}. CRDT-backed: consumption is gossiped
        #  as per-shard G-Counters and folded via _TokenBucket.drain.
        self._buckets: dict[tuple[str, str], dict] = {}
        # (tenant, model) -> (window_start_ts, ledger_tokens_at_start).
        self._windows: dict[tuple[str, str], tuple[float, int]] = {}  # local-state: window anchors over the CRDT-merged ledger; the cumulative reads they anchor are global
        # Overload latch (mirrors the gossiped LWW register when
        # sharded) + cached fleet pressure.
        self._overload = False
        self._pressure = {"depth": 0.0, "oldest_wait_s": 0.0,
                          "source": "none"}  # local-state: TTL cache of this shard's fleet-pressure view
        self._pressure_at = float("-inf")  # local-state: cache timestamp for _pressure
        # Bounded metric cardinality: tenant -> label (own name or
        # "other"), plus the (model, reason) series each label has
        # emitted so churn cleanup can remove them.
        self._labels: dict[str, str] = {}  # local-state: exposition label map, not admission state
        self._door_series: dict[str, set[tuple[str, str]]] = {}  # local-state: exposition series map, not admission state
        self._last_seen: dict[str, float] = {}  # local-state: per-shard idle tracking; churn is per-process by design
        self._last_cleanup = clock()
        # Exact refusal tallies for /v1/usage (ints, not float counters).
        self._tally = {REASON_RATE: 0, REASON_TOKENS: 0,
                       REASON_QUOTA: 0, REASON_OVERLOAD: 0}  # local-state: per-shard tallies; ShardedDoor.state_payload sums shards
        self._admitted = 0  # local-state: per-shard tally; ShardedDoor.state_payload sums shards

    # -- public admission entry points ---------------------------------------

    def active(self) -> bool:
        return bool(self.cfg and getattr(self.cfg, "enabled", False))

    def admit_http(self, headers: dict, body: bytes) -> Refusal | None:
        """The HTTP front door's check: resolve tenant from headers
        (API-key digest wins over X-Client-Id — fleet.metering.tenant_of)
        and model/priority/token-estimate from the request body. Runs
        BEFORE proxy.handle, i.e. before any queueing anywhere."""
        if not self.active():
            return None
        tenant = tenant_of(headers)
        parsed = self._parse_body(body)
        model_name = ""
        if isinstance(parsed, dict):
            model_name = str(parsed.get("model") or "")
        priority = (headers.get("x-priority") or "").strip()
        return self.admit(
            tenant, model_name, priority=priority,
            est_tokens=estimate_tokens(body, parsed),
        )

    def admit_message(self, metadata: dict, model, body: bytes) -> Refusal | None:
        """The messenger's check: same policy, tenant from
        ``metadata.client_id`` (the pub/sub path's only identity)."""
        if not self.active():
            return None
        tenant = str(metadata.get("client_id") or "").strip() or ANONYMOUS_TENANT
        priority = str(metadata.get("priority") or "").strip()
        return self.admit(
            tenant, model.name, priority=priority,
            est_tokens=estimate_tokens(body, self._parse_body(body)),
            model=model,
        )

    def admit(
        self,
        tenant: str,
        model_name: str,
        *,
        priority: str = "",
        est_tokens: int = 1,
        model=None,
    ) -> Refusal | None:
        """Admit or refuse one request. Returns None (admitted) or a
        Refusal carrying the computed, jittered Retry-After."""
        if not self.active():
            return None
        tenant = tenant or ANONYMOUS_TENANT
        now = self._clock()
        if model is None:
            model = self._lookup_model(model_name)
        policy = self.resolve_policy(model)
        cls = self._request_class(priority, model)
        refusal = None
        if not policy.exempt:
            refusal = (
                self._check_buckets(tenant, model_name, policy, est_tokens, now)
                or self._check_quota(tenant, model_name, policy, now)
                or self._check_overload(tenant, model_name, cls, now)
            )
        with self._lock:
            self._last_seen[tenant] = now
            if refusal is None:
                self._admitted += 1
            else:
                self._tally[refusal.reason] += 1
        if refusal is None:
            self.metrics.door_admitted.inc(model=model_name or "unknown")
        else:
            label = self._tenant_label(tenant)
            mlabel = model_name or "unknown"
            self.metrics.door_rejections.inc(
                tenant=label, model=mlabel, reason=refusal.reason
            )
            with self._lock:
                self._door_series.setdefault(label, set()).add(
                    (mlabel, refusal.reason)
                )
            self.metrics.door_retry_after.observe(refusal.retry_after_s)
            if self.recorder is not None:
                kind = (
                    flightrecorder.DOOR_QUOTA
                    if refusal.reason == REASON_QUOTA
                    else flightrecorder.DOOR_SHED
                )
                self.recorder.record(
                    kind, "door", target=mlabel, tenant=label,
                    reason=refusal.reason, cls=cls,
                    retry_after_s=round(refusal.retry_after_s, 3),
                )
        self._maybe_cleanup(now)
        return refusal

    # -- the three checks ----------------------------------------------------

    def _check_buckets(self, tenant, model_name, policy, est_tokens, now):
        key = (tenant, model_name)
        g = self.gossip
        with self._lock:
            entry = self._buckets.get(key)
            if entry is None:
                entry = {
                    "req": self._make_bucket(
                        policy.requests_per_second, policy.request_burst, now
                    ),
                    "tok": self._make_bucket(
                        policy.tokens_per_second, policy.token_burst, now
                    ),
                    "seen": now,
                }
                if g is not None:
                    # The bucket starts full; peer consumption from
                    # before it existed was already charged against the
                    # peers' own buckets, so the fold baseline is "what
                    # the global counters say right now".
                    entry["req_rem"] = g.remote_consumed(
                        "req", tenant, model_name
                    )
                    entry["tok_rem"] = g.remote_consumed(
                        "tok", tenant, model_name
                    )
                    # Degraded-mode overcharge insurance: the extra
                    # (split-1) charged per admission while partitioned
                    # pre-pays for remote consumption we cannot see yet.
                    # When the fold eventually arrives it is paid from
                    # this pool first, so heal does not double-bill.
                    entry["req_over"] = 0.0
                    entry["tok_over"] = 0.0
                self._buckets[key] = entry
            entry["seen"] = now
            # Partition degradation: fully connected -> split == 1.0 and
            # this is byte-identical single-door arithmetic; with stale
            # peers each admission is charged a conservative multiple so
            # any split of N shards still admits at most ONE budget.
            split = g.split(now) if g is not None else 1.0
            if entry["req"] is not None:
                if g is not None:
                    # Refill lost to the burst cap is the conservative
                    # reserve this shard withheld for consumption it
                    # could not see; bank it (up to one burst) so the
                    # matching folds don't bill the tenant twice.
                    c = entry["req"].pop_clipped()
                    if c > 0.0 and entry["req_over"] < policy.request_burst:
                        entry["req_over"] = min(
                            policy.request_burst, entry["req_over"] + c
                        )
                    rem = g.remote_consumed("req", tenant, model_name)
                    delta = rem - entry["req_rem"]
                    if delta > 0.0:
                        use = min(entry["req_over"], delta)
                        entry["req_over"] -= use
                        if delta > use:
                            entry["req"].drain(delta - use, now)
                        entry["req_rem"] = rem
                ok, wait = entry["req"].take(1.0 * split, now)
                if ok and g is not None:
                    g.consume("req", tenant, model_name, 1.0)
                    if split > 1.0:
                        entry["req_over"] += split - 1.0
                if not ok:
                    return self._refuse(
                        tenant, model_name, REASON_RATE,
                        f"tenant {tenant!r} exceeds its request rate "
                        "limit", wait,
                    )
            if entry["tok"] is not None and est_tokens > 0:
                if g is not None:
                    c = entry["tok"].pop_clipped()
                    if c > 0.0 and entry["tok_over"] < policy.token_burst:
                        entry["tok_over"] = min(
                            policy.token_burst, entry["tok_over"] + c
                        )
                    rem = g.remote_consumed("tok", tenant, model_name)
                    delta = rem - entry["tok_rem"]
                    if delta > 0.0:
                        use = min(entry["tok_over"], delta)
                        entry["tok_over"] -= use
                        if delta > use:
                            entry["tok"].drain(delta - use, now)
                        entry["tok_rem"] = rem
                ok, wait = entry["tok"].take(float(est_tokens) * split, now)
                if ok and g is not None:
                    g.consume("tok", tenant, model_name, float(est_tokens))
                    if split > 1.0:
                        entry["tok_over"] += float(est_tokens) * (split - 1.0)
                if not ok:
                    return self._refuse(
                        tenant, model_name, REASON_TOKENS,
                        f"tenant {tenant!r} exceeds its token throughput "
                        "limit", wait,
                    )
        return None

    def _check_quota(self, tenant, model_name, policy, now):
        if (
            policy.window_seconds <= 0.0
            or policy.window_token_budget <= 0
            or self.usage is None
        ):
            return None
        ledger = self.usage.tenant_model_tokens(tenant, model_name)
        key = (tenant, model_name)
        with self._lock:
            start = self._windows.get(key)
            if start is None or now - start[0] >= policy.window_seconds:
                start = (now, ledger)
                self._windows[key] = start
            used = ledger - start[1]
            if used < policy.window_token_budget:
                return None
            reset_in = start[0] + policy.window_seconds - now
        return self._refuse(
            tenant, model_name, REASON_QUOTA,
            f"tenant {tenant!r} is over its {policy.window_token_budget}"
            f"-token budget for the current window", reset_in,
        )

    def _check_overload(self, tenant, model_name, cls, now):
        high = float(getattr(self.cfg, "overload_high_water", 0.0) or 0.0)
        if high <= 0.0:
            return None
        pressure = self.fleet_pressure(now)
        depth = pressure["depth"]
        low = float(getattr(self.cfg, "overload_low_water", 0.0) or 0.0)
        if low <= 0.0:
            low = 0.8 * high
        g = self.gossip
        if g is not None:
            # Sharded door: the latch lives in the gossiped LWW
            # register. Adopt the merged view, then apply this shard's
            # pressure reading as a read-modify-write — any shard may
            # flip it either way, and HLC ordering settles races.
            self._overload = g.overload(default=self._overload)
        if self._overload:
            if depth <= low:
                self._overload = False
                if g is not None:
                    g.set_overload(False)
        elif depth >= high:
            self._overload = True
            if g is not None:
                g.set_overload(True)
        shed = set()
        if self._overload:
            shed.add("batch")
            factor = float(
                getattr(self.cfg, "overload_standard_factor", 2.0) or 2.0
            )
            if depth >= factor * high:
                shed.add("standard")
        # realtime is NEVER door-shed: it degrades last, bounded only by
        # the engine scheduler's own admission control.
        self.metrics.door_overload.set(1.0 if self._overload else 0.0)
        self.metrics.door_queue_pressure.set(depth)
        for c in PRIORITY_CLASSES:
            self.metrics.door_shedding.set(
                1.0 if c in shed else 0.0, priority=c
            )
        if cls not in shed:
            return None
        # Retry hint: the fleet's oldest queued wait is the measured
        # drain horizon — clients should come back roughly when the
        # current backlog has moved.
        return self._refuse(
            tenant, model_name, REASON_OVERLOAD,
            f"fleet overloaded (queue pressure {depth:.0f} >= "
            f"{high:.0f}); shedding {cls!r}-class work",
            max(pressure["oldest_wait_s"], 1.0),
        )

    # -- fleet pressure (aggregator snapshot, direct sweep fallback) ---------

    def fleet_pressure(self, now: float | None = None) -> dict:
        """Fleet-wide queue pressure, cached for ``pressure_ttl_s``.
        Sums every model's queue depth from the aggregator's fresh
        snapshot; when the snapshot is stale (or absent) falls back to a
        direct collect() sweep — the same freshness discipline the
        autoscaler applies."""
        now = self._clock() if now is None else now
        if now - self._pressure_at < self._pressure_ttl:
            return self._pressure
        depth, oldest, source = 0.0, 0.0, "none"
        if self._pressure_fn is not None:
            try:
                p = self._pressure_fn() or {}
                depth = float(p.get("depth", 0.0))
                oldest = float(p.get("oldest_wait_s", 0.0))
                source = "injected"
            except Exception:
                source = "error"
        elif self.fleet is not None:
            snap = self.fleet.snapshot()
            fresh = False
            if snap is not None:
                for name in list(snap.get("models") or {}):
                    q = self.fleet.queue_pressure(name)
                    if q is None:
                        continue
                    fresh = True
                    depth += float(q["depth"])
                    oldest = max(oldest, float(q["oldest_wait_s"]))
            if fresh:
                source = "aggregator"
            else:
                # Stale/absent snapshot: direct sweep, never silently 0.
                try:
                    snap = self.fleet.collect()
                    for entry in (snap.get("models") or {}).values():
                        q = entry.get("queue") or {}
                        depth += float(q.get("depth", 0.0))
                        oldest = max(
                            oldest, float(q.get("oldest_wait_s", 0.0))
                        )
                    source = "direct"
                except Exception:
                    source = "error"
        self._pressure = {
            "depth": depth, "oldest_wait_s": oldest, "source": source,
        }
        self._pressure_at = now
        return self._pressure

    # -- policy resolution ---------------------------------------------------

    def resolve_policy(self, model=None) -> DoorPolicy:
        """System ``tenancy:`` defaults with the model CRD block's
        overrides applied (a CRD field set > 0 wins; ``exempt`` opts the
        model out of door admission entirely)."""
        c = self.cfg
        fields = {
            "requests_per_second": float(c.requests_per_second),
            "request_burst": float(c.request_burst),
            "tokens_per_second": float(c.tokens_per_second),
            "token_burst": float(c.token_burst),
            "window_seconds": float(c.window_seconds),
            "window_token_budget": int(c.window_token_budget),
            "exempt": False,
        }
        t = getattr(getattr(model, "spec", None), "tenancy", None)
        if t is not None and t.enabled():
            for name in (
                "requests_per_second", "request_burst",
                "tokens_per_second", "token_burst", "window_seconds",
                "window_token_budget",
            ):
                v = getattr(t, name)
                if v:
                    fields[name] = type(fields[name])(v)
            fields["exempt"] = bool(t.exempt)
        return DoorPolicy(**fields)

    def _lookup_model(self, model_name: str):
        if not self.model_client or not model_name:
            return None
        from kubeai_tpu.routing.apiutils import split_model_adapter

        base, adapter = split_model_adapter(model_name)
        for candidate in (model_name, base):
            try:
                return self.model_client.lookup_model(candidate, "", None)
            except Exception:
                continue
        return None

    def _request_class(self, priority: str, model) -> str:
        if priority in PRIORITY_CLASSES:
            return priority
        default = getattr(
            getattr(getattr(model, "spec", None), "scheduling", None),
            "default_priority", "",
        )
        return default if default in PRIORITY_CLASSES else "standard"

    # -- internals -----------------------------------------------------------

    def _parse_body(self, body: bytes):
        try:
            return json.loads(body) if body else None
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None  # the proxy/engine will answer 400 on its own

    def _make_bucket(self, rate: float, burst: float, now: float):
        if rate <= 0.0:
            return None
        return _TokenBucket(rate, burst if burst > 0.0 else max(rate, 1.0), now)

    def _refuse(self, tenant, model_name, reason, message, wait_s) -> Refusal:
        return Refusal(
            tenant=tenant,
            model=model_name,
            reason=reason,
            message=message,
            retry_after_s=retryafter.jittered(
                wait_s,
                min_s=float(self.cfg.min_retry_after_seconds),
                max_s=float(self.cfg.max_retry_after_seconds),
            ),
        )

    def _tenant_label(self, tenant: str) -> str:
        cap = int(getattr(self.cfg, "max_tenant_series", 0) or 0)
        with self._lock:
            label = self._labels.get(tenant)
            if label is None:
                label = (
                    tenant if cap <= 0 or len(self._labels) < cap
                    else OVERFLOW_TENANT_LABEL
                )
                self._labels[tenant] = label
            return label

    def _maybe_cleanup(self, now: float) -> None:
        idle = float(getattr(self.cfg, "tenant_idle_seconds", 0.0) or 0.0)
        if idle <= 0.0 or now - self._last_cleanup < idle / 2.0:
            return
        self.cleanup(now=now)

    def cleanup(self, now: float | None = None) -> int:
        """Churn pass: drop buckets/windows/labels (and their
        ``kubeai_door_*`` series) for tenants idle past
        ``tenant_idle_seconds``, and prune their ``kubeai_tenant_*``
        series from the UsageMeter's mirror (the exact ledger is never
        touched). Returns the number of tenants expired."""
        now = self._clock() if now is None else now
        idle = float(getattr(self.cfg, "tenant_idle_seconds", 0.0) or 0.0)
        self._last_cleanup = now
        if idle <= 0.0:
            return 0
        with self._lock:
            gone = {
                t for t, seen in self._last_seen.items()
                if now - seen > idle
            }
            keep = set(self._last_seen) - gone
            for t in gone:
                self._last_seen.pop(t, None)
                label = self._labels.pop(t, None)
                if label and label != OVERFLOW_TENANT_LABEL and (
                    label not in self._labels.values()
                ):
                    for mlabel, reason in self._door_series.pop(label, ()):
                        self.metrics.door_rejections.remove(
                            tenant=label, model=mlabel, reason=reason
                        )
            for key in [k for k in self._buckets if k[0] in gone]:
                del self._buckets[key]
            for key in [k for k in self._windows if k[0] in gone]:
                del self._windows[key]
        if gone and self.usage is not None:
            self.usage.prune_tenant_series(keep)
        self.metrics.door_tenants_tracked.set(float(len(keep)))
        return len(gone)

    # -- surfaces ------------------------------------------------------------

    def state_payload(self) -> dict:
        """The ``GET /v1/usage`` tenancy block: door state an operator
        can read at a glance."""
        with self._lock:
            tracked = len(self._last_seen)
            tally = dict(self._tally)
            admitted = self._admitted
        self.metrics.door_tenants_tracked.set(float(tracked))
        pressure = dict(self._pressure)
        return {
            "enabled": self.active(),
            "overload": self._overload,
            "queue_pressure": pressure,
            "tenants_tracked": tracked,
            "admitted": admitted,
            "rejections": tally,
            "limits": {
                "requestsPerSecond": self.cfg.requests_per_second,
                "tokensPerSecond": self.cfg.tokens_per_second,
                "window": self.cfg.window_seconds,
                "windowTokenBudget": self.cfg.window_token_budget,
                "overloadHighWater": self.cfg.overload_high_water,
            },
        }


class ShardedDoor:
    """N in-process door shards behind a deterministic round-robin
    shard picker, sharing one gossiped CRDT state plane
    (routing/gossip.DoorShardSet).

    Same surface as a single TenantGovernor (``admit`` /
    ``admit_http`` / ``admit_message`` / ``active`` /
    ``state_payload`` / ``cleanup`` / ``recorder``), so the HTTP front
    door and the messenger take either without caring. The round-robin
    picker models an external L4 balancer spraying requests across N
    door replicas — the adversarial case for budget enforcement, since
    an abuser's traffic splits evenly across every shard's local view.

    Anti-entropy is driven lazily from the admission path (no
    background thread): each admission runs a gossip round when the
    configured interval has elapsed on the injected clock, which keeps
    FakeClock sims bit-deterministic.
    """

    def __init__(self, shards, shard_set, usage=None):
        if not shards:
            raise ValueError("ShardedDoor needs at least one shard")
        self.shards = list(shards)
        self.shard_set = shard_set
        self.usage = usage
        self._rr = 0
        self._recorder = None

    # -- TenantGovernor surface ------------------------------------------

    def active(self) -> bool:
        return any(s.active() for s in self.shards)

    def admit_http(self, headers: dict, body: bytes) -> Refusal | None:
        self._tick()
        return self._pick().admit_http(headers, body)

    def admit_message(self, metadata, model, body) -> Refusal | None:
        self._tick()
        return self._pick().admit_message(metadata, model, body)

    def admit(self, tenant, model_name, *, priority="", est_tokens=1,
              model=None) -> Refusal | None:
        self._tick()
        return self._pick().admit(
            tenant, model_name, priority=priority,
            est_tokens=est_tokens, model=model,
        )

    @property
    def recorder(self):
        return self._recorder

    @recorder.setter
    def recorder(self, rec) -> None:
        self._recorder = rec
        for s in self.shards:
            s.recorder = rec

    @property
    def overload(self) -> bool:
        """The fleet-wide overload latch: any shard's view (converged
        via the gossiped LWW register)."""
        return any(s._overload for s in self.shards)

    @property
    def cfg(self):
        return self.shards[0].cfg

    def fleet_pressure(self, now: float | None = None) -> dict:
        return self.shards[0].fleet_pressure(now)

    def cleanup(self, now: float | None = None) -> int:
        return sum(s.cleanup(now=now) for s in self.shards)

    def state_payload(self) -> dict:
        """Aggregate door state across shards: exact tallies summed,
        plus per-shard gossip health."""
        payload = self.shards[0].state_payload()
        for s in self.shards[1:]:
            p = s.state_payload()
            payload["admitted"] += p["admitted"]
            payload["tenants_tracked"] += p["tenants_tracked"]
            for reason, n in p["rejections"].items():
                payload["rejections"][reason] += n
        payload["overload"] = self.overload
        now = float(self.shard_set.clock())
        payload["shards"] = {
            name: {
                "degraded": node.degraded(now),
                "stale_peers": list(node.stale_peers(now)),
                "state_entries": len(node.state),
            }
            for name, node in sorted(self.shard_set.nodes.items())
        }
        return payload

    # -- shard plumbing ---------------------------------------------------

    def _pick(self) -> TenantGovernor:
        i = self._rr % len(self.shards)
        self._rr += 1
        return self.shards[i]

    def _tick(self) -> None:
        now = float(self.shard_set.clock())
        if self.shard_set.maybe_step(now):
            self._after_round()

    def step_gossip(self, now: float | None = None) -> None:
        """Explicit anti-entropy round (sims and tests)."""
        self.shard_set.step(now)
        self._after_round()

    def _after_round(self) -> None:
        # Per-shard UsageMeters (cross-process deployments and the
        # sharded sims) absorb peer ledgers after every round; with one
        # shared in-process meter usage_source is unwired and this is a
        # no-op.
        for s in self.shards:
            if (
                s.usage is not None
                and s.gossip is not None
                and s.gossip.usage_source is not None
            ):
                s.usage.absorb_gossip(s.gossip)

    def replace_shard(self, index: int, governor: TenantGovernor) -> None:
        """Swap in a restarted shard (door_crash chaos): the fresh
        governor starts with empty local state and reconstructs the
        replicated portion from its peers via anti-entropy."""
        self.shards[index] = governor
        governor.recorder = self._recorder


def build_door(
    cfg,
    *,
    usage=None,
    fleet=None,
    model_client=None,
    metrics: Metrics = DEFAULT_METRICS,
    clock=time.monotonic,
    pressure_fn=None,
    pressure_ttl_s: float = 1.0,
    seed: int = 0,
):
    """Build the front door from TenancyConfig: a single TenantGovernor
    when ``door_shards <= 1`` (byte-identical to the pre-sharding
    build), else N governors sharing a gossiped state plane behind a
    ShardedDoor."""
    n = int(getattr(cfg, "door_shards", 1) or 1)

    def _governor(gossip=None):
        return TenantGovernor(
            cfg=cfg, usage=usage, fleet=fleet, model_client=model_client,
            metrics=metrics, clock=clock, pressure_fn=pressure_fn,
            pressure_ttl_s=pressure_ttl_s, gossip=gossip,
        )

    if n <= 1:
        return _governor()
    from kubeai_tpu.routing.gossip import DoorShardSet

    names = [f"door-{i}" for i in range(n)]
    shard_set = DoorShardSet(
        names, clock, seed=seed,
        interval_s=float(
            getattr(cfg, "gossip_interval_seconds", 1.0) or 1.0
        ),
        stale_after_s=float(
            getattr(cfg, "gossip_stale_seconds", 5.0) or 5.0
        ),
        metrics=metrics,
    )
    shards = [_governor(gossip=shard_set.node(name)) for name in names]
    return ShardedDoor(shards, shard_set, usage=usage)
