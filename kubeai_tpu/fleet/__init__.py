"""Fleet telemetry plane: cluster state aggregation, per-tenant usage
metering, and engine step profiling.

Three pillars (see docs/concepts/observability.md — Fleet telemetry):

  - `FleetStateAggregator` — one concurrent sweep over every serving
    endpoint's `/metrics` + `/v1/state`, joined with the operator's pod
    inventory into a timestamped snapshot with explicit staleness;
    exposed as `GET /v1/fleet/state`, `kubeai_fleet_*` gauges, and a
    snapshot ring (`/v1/fleet/history`). The autoscaler reads it
    instead of re-scraping, with direct-scrape fallback.
  - `UsageMeter` — per-tenant×model token/request/stream/shed ledger
    (`kubeai_tenant_*` counters, `GET /v1/usage`).
  - `StepProfiler` — per-phase Engine.step timeline
    (`kubeai_engine_step_phase_seconds`, `POST /v1/profile`).
"""

from kubeai_tpu.fleet.aggregator import (
    FleetStateAggregator,
    endpoint_signals,
    hist_quantiles,
)
from kubeai_tpu.fleet.metering import (
    ANONYMOUS_TENANT,
    UsageMeter,
    tenant_of,
)
from kubeai_tpu.fleet.profiler import PHASES, StepProfiler, phase_totals

__all__ = [
    "ANONYMOUS_TENANT",
    "FleetStateAggregator",
    "PHASES",
    "StepProfiler",
    "UsageMeter",
    "endpoint_signals",
    "hist_quantiles",
    "phase_totals",
    "tenant_of",
]
