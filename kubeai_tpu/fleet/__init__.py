"""Fleet telemetry plane: cluster state aggregation, per-tenant usage
metering, and engine step profiling.

Three pillars (see docs/concepts/observability.md — Fleet telemetry):

  - `FleetStateAggregator` — one concurrent sweep over every serving
    endpoint's `/metrics` + `/v1/state`, joined with the operator's pod
    inventory into a timestamped snapshot with explicit staleness;
    exposed as `GET /v1/fleet/state`, `kubeai_fleet_*` gauges, and a
    snapshot ring (`/v1/fleet/history`). The autoscaler reads it
    instead of re-scraping, with direct-scrape fallback.
  - `UsageMeter` — per-tenant×model token/request/stream/shed ledger
    (`kubeai_tenant_*` counters, `GET /v1/usage`).
  - `StepProfiler` — per-phase Engine.step timeline
    (`kubeai_engine_step_phase_seconds`, `POST /v1/profile`).
  - `TenantGovernor` — the front door's admission layer
    (docs/concepts/tenancy.md): per-tenant token buckets, rolling
    token-budget quotas fed by the `UsageMeter` ledger, and
    lowest-class-first overload shedding driven by the aggregator's
    queue pressure; `kubeai_door_*` metrics.

Plus the consumer that makes the aggregated state actionable:

  - `CapacityPlanner` — cluster-wide coordinated capacity planning
    (docs/concepts/capacity-planning.md): priority bin-packing of every
    model's replicas onto the heterogeneous chip budget, scheduling-
    class preemption, slice right-sizing, and joint prefill/decode
    damping; `kubeai_planner_*` gauges, `GET /v1/fleet/plan`, and an
    override channel into the autoscaler.
  - `DemandForecaster` — least-squares demand trend + spot-preemption
    early warning over the snapshot ring (docs/concepts/cold-start.md):
    feeds the planner's predictive prewarm pass and prices measured
    cold-start cost into its preemption choices; `kubeai_prewarm_*`
    gauges.
  - `SLOEvaluator` — the judge over all of it (docs/concepts/slo.md):
    declarative per-model objectives (TTFT p95, ITL p99, availability,
    shed rate) evaluated each tick from the aggregator's snapshots with
    multi-window multi-burn-rate alerting and an exact error-budget
    ledger; `kubeai_slo_*` metrics, `GET /v1/slo`, and burn-rate
    pressure fed into the autoscaler and planner.
"""

from kubeai_tpu.fleet.aggregator import (
    FleetStateAggregator,
    endpoint_signals,
    hist_detail,
    hist_quantiles,
)
from kubeai_tpu.fleet.forecaster import (
    DemandForecaster,
    Forecast,
)
from kubeai_tpu.fleet.planner import (
    CapacityPlanner,
    SCHEDULING_CLASSES,
    model_chips_per_replica,
    model_scheduling_class,
)
from kubeai_tpu.fleet.metering import (
    ANONYMOUS_TENANT,
    UsageMeter,
    tenant_of,
)
from kubeai_tpu.fleet.profiler import PHASES, StepProfiler, phase_totals
from kubeai_tpu.fleet.slo import OBJECTIVE_KINDS, SLOEvaluator
from kubeai_tpu.fleet.tenancy import (
    Refusal,
    ShardedDoor,
    TenantGovernor,
    build_door,
)

__all__ = [
    "ANONYMOUS_TENANT",
    "CapacityPlanner",
    "DemandForecaster",
    "Forecast",
    "FleetStateAggregator",
    "OBJECTIVE_KINDS",
    "PHASES",
    "Refusal",
    "SCHEDULING_CLASSES",
    "SLOEvaluator",
    "ShardedDoor",
    "StepProfiler",
    "TenantGovernor",
    "UsageMeter",
    "build_door",
    "endpoint_signals",
    "hist_detail",
    "hist_quantiles",
    "model_chips_per_replica",
    "model_scheduling_class",
    "phase_totals",
    "tenant_of",
]
