"""Per-tenant usage metering: the enforcement-ready ledger behind
the door's quotas (kubeai_tpu/fleet/tenancy).

Every request through the front door or messenger is attributed to a
tenant — a stable digest of the API-key principal when an Authorization
header is present (the authenticated identity always wins), else the
`X-Client-Id` header (the same WFQ fairness key the scheduler uses),
else `anonymous`. A `UsageMeter` accumulates prompt/completion tokens,
request counts, stream-seconds, and shed/429 counts per tenant×model,
mirrored to `kubeai_tenant_*` counters and summarized by `GET /v1/usage`.

The ledger keeps EXACT integer token counts (the counters are floats by
exposition necessity); billing-grade accounting must not depend on float
accumulation staying integral. The metric MIRROR, by contrast, bounds
its cardinality: at most `max_tenant_series` distinct tenant label
values ever appear on `kubeai_tenant_*` series — overflow tenants
aggregate into the `other` label, and `prune_tenant_series` removes
churned tenants' series (the ledger itself is never pruned).
"""

from __future__ import annotations

import hashlib
import threading

from kubeai_tpu.metrics.registry import DEFAULT_METRICS, Metrics

ANONYMOUS_TENANT = "anonymous"
OVERFLOW_TENANT_LABEL = "other"


def tenant_of(headers: dict) -> str:
    """Resolve the tenant identity from request headers (lowercase keys,
    as the front door normalizes them). Trust ordering matters: the
    API-key principal (`Authorization: Bearer ...`, as a stable
    `key-<digest>` pseudonym — the raw key must never become a metric
    label) wins over the client-supplied `X-Client-Id`, otherwise a
    spoofed header could bill/attribute one tenant's traffic to another.
    `X-Client-Id` only identifies otherwise-anonymous callers."""
    auth = (headers.get("authorization") or "").strip()
    if auth.lower().startswith("bearer "):
        key = auth[7:].strip()
        if key:
            return "key-" + hashlib.sha256(key.encode()).hexdigest()[:12]
    cid = (headers.get("x-client-id") or "").strip()
    if cid:
        return cid
    return ANONYMOUS_TENANT


def _zero() -> dict:
    return {
        "requests": 0,
        "prompt_tokens": 0,
        "completion_tokens": 0,
        "stream_seconds": 0.0,
        "shed": 0,
    }


class UsageMeter:
    """Thread-safe tenant×model usage ledger + `kubeai_tenant_*` counter
    mirror. One instance per operator replica (shared by the front door
    and every messenger stream)."""

    def __init__(self, metrics: Metrics = DEFAULT_METRICS,
                 max_tenant_series: int = 512):
        self.metrics = metrics
        self.max_tenant_series = int(max_tenant_series)
        self._lock = threading.Lock()  # local-state: process-local mutex, not replicated data
        self._ledger: dict[tuple[str, str], dict] = {}
        # Peer door-shard ledgers learned via the gossip state plane:
        # shard -> (tenant, model) -> counts. Cumulative snapshots
        # merged with per-field max, so re-delivered gossip deltas (any
        # suffix, any order) never double-bill — totals are exact.
        self._remote: dict[str, dict[tuple[str, str], dict]] = {}
        # tenant -> metric label (own name, or "other" past the cap),
        # and label -> model labels emitted, so churned tenants' series
        # can be removed without touching the exact ledger.
        self._labels: dict[str, str] = {}  # local-state: exposition label map, not billing state
        self._series: dict[str, set[str]] = {}  # local-state: exposition series map, not billing state

    def _label_for(self, tenant: str) -> str:
        label = self._labels.get(tenant)
        if label is None:
            label = (
                tenant
                if self.max_tenant_series <= 0
                or len(self._labels) < self.max_tenant_series
                else OVERFLOW_TENANT_LABEL
            )
            self._labels[tenant] = label
        return label

    def record(
        self,
        tenant: str,
        model: str,
        *,
        prompt_tokens: int = 0,
        completion_tokens: int = 0,
        requests: int = 1,
        stream_seconds: float = 0.0,
        shed: bool = False,
    ) -> None:
        tenant = tenant or ANONYMOUS_TENANT
        model = model or "unknown"
        with self._lock:
            entry = self._ledger.setdefault((tenant, model), _zero())
            entry["requests"] += int(requests)
            entry["prompt_tokens"] += int(prompt_tokens)
            entry["completion_tokens"] += int(completion_tokens)
            entry["stream_seconds"] += float(stream_seconds)
            if shed:
                entry["shed"] += 1
            label = self._label_for(tenant)
            self._series.setdefault(label, set()).add(model)
        m = self.metrics
        labels = {"tenant": label, "model": model}
        if requests:
            m.tenant_requests.inc(requests, **labels)
        if prompt_tokens:
            m.tenant_prompt_tokens.inc(prompt_tokens, **labels)
        if completion_tokens:
            m.tenant_completion_tokens.inc(completion_tokens, **labels)
        if stream_seconds:
            m.tenant_stream_seconds.inc(stream_seconds, **labels)
        if shed:
            m.tenant_shed.inc(**labels)

    def record_response(
        self,
        tenant: str,
        model: str,
        status: int,
        usage: dict | None = None,
        stream_seconds: float = 0.0,
        completion_tokens: int | None = None,
    ) -> None:
        """Attribute one completed HTTP exchange: token counts from the
        response's OpenAI `usage` block when present (unary), or from
        counted stream tokens (SSE); a 429 counts as a shed."""
        usage = usage if isinstance(usage, dict) else {}

        def _int(key: str) -> int:
            v = usage.get(key)
            return v if isinstance(v, int) and not isinstance(v, bool) else 0

        self.record(
            tenant,
            model,
            prompt_tokens=_int("prompt_tokens"),
            completion_tokens=(
                _int("completion_tokens")
                if completion_tokens is None else int(completion_tokens)
            ),
            stream_seconds=stream_seconds,
            shed=status == 429,
        )

    # -- gossip merge (sharded front door) -------------------------------

    def shard_snapshot(self) -> dict[str, float]:
        """This shard's cumulative ledger flattened to
        `tenant|model|field` keys — the G-Counter component this door
        publishes into the gossip state plane. Cumulative (not deltas),
        so publication is idempotent by construction."""
        out: dict[str, float] = {}
        with self._lock:
            for (tenant, model), entry in self._ledger.items():
                for fld, value in entry.items():
                    if value:
                        out[f"{tenant}|{model}|{fld}"] = value
        return out

    def merge_shard_snapshot(self, shard: str,
                             snapshot: dict[str, float]) -> None:
        """Merge a peer door-shard's cumulative ledger snapshot.
        Per-field max keeps every component monotone, so replaying any
        gossip delta suffix — stale, reordered, or duplicated — leaves
        the exact-integer totals unchanged."""
        parsed: dict[tuple[str, str], dict] = {}
        for key, value in snapshot.items():
            tenant, model, fld = key.split("|", 2)
            if fld not in _zero():
                continue
            entry = parsed.setdefault((tenant, model), {})
            entry[fld] = (
                float(value) if fld == "stream_seconds" else int(value)
            )
        with self._lock:
            held = self._remote.setdefault(shard, {})
            for tm, fields in parsed.items():
                entry = held.setdefault(tm, _zero())
                for fld, value in fields.items():
                    if value > entry[fld]:
                        entry[fld] = value

    def absorb_gossip(self, node) -> None:
        """Pull every peer shard's ledger components out of a
        DoorGossipNode and merge them (idempotent)."""
        for shard, snapshot in node.ledger_components().items():
            self.merge_shard_snapshot(shard, snapshot)

    def tenant_model_tokens(self, tenant: str, model: str) -> int:
        """Exact cumulative prompt+completion tokens for one
        tenant×model pair, across every door shard — the quota feed for
        the door's rolling windows (window usage = this value now minus
        its value at the window start)."""
        tenant = tenant or ANONYMOUS_TENANT
        model = model or "unknown"
        with self._lock:
            total = 0
            entry = self._ledger.get((tenant, model))
            if entry is not None:
                total += entry["prompt_tokens"] + entry["completion_tokens"]
            for held in self._remote.values():
                remote = held.get((tenant, model))
                if remote is not None:
                    total += (
                        remote["prompt_tokens"] + remote["completion_tokens"]
                    )
            return total

    def prune_tenant_series(self, keep) -> int:
        """Label-churn pass: remove `kubeai_tenant_*` series for tenants
        not in `keep` (the door's still-active set). The exact ledger is
        deliberately untouched — billing history survives churn; only
        the exposition-side label space is bounded. Returns the number
        of tenant labels removed."""
        keep = set(keep)
        m = self.metrics
        removed = 0
        with self._lock:
            gone = [
                t for t in self._labels
                if t not in keep and self._labels[t] != OVERFLOW_TENANT_LABEL
            ]
            for tenant in gone:
                label = self._labels.pop(tenant)
                removed += 1
                for model in self._series.pop(label, ()):
                    labels = {"tenant": label, "model": model}
                    for metric in (
                        m.tenant_requests, m.tenant_prompt_tokens,
                        m.tenant_completion_tokens, m.tenant_stream_seconds,
                        m.tenant_shed,
                    ):
                        metric.remove(**labels)
        return removed

    def summary(self, tenant: str | None = None) -> dict:
        """The `/v1/usage` payload: per-tenant per-model entries plus
        per-tenant and global totals, spanning this shard's ledger and
        every peer shard learned via gossip. `tenant` filters to one
        tenant."""
        with self._lock:
            merged: dict[tuple[str, str], dict] = {}
            for (t, m), e in self._ledger.items():
                entry = merged.setdefault((t, m), _zero())
                for k in e:
                    entry[k] += e[k]
            for held in self._remote.values():
                for (t, m), e in held.items():
                    entry = merged.setdefault((t, m), _zero())
                    for k in e:
                        entry[k] += e[k]
            items = [
                (t, m, e) for (t, m), e in merged.items()
                if tenant is None or t == tenant
            ]
        tenants: dict[str, dict] = {}
        totals = _zero()
        for t, m, entry in items:
            bucket = tenants.setdefault(
                t, {"models": {}, "totals": _zero()}
            )
            bucket["models"][m] = entry
            for k in entry:
                bucket["totals"][k] += entry[k]
                totals[k] += entry[k]
        for bucket in tenants.values():
            bucket["totals"]["stream_seconds"] = round(
                bucket["totals"]["stream_seconds"], 6
            )
        totals["stream_seconds"] = round(totals["stream_seconds"], 6)
        return {
            "object": "usage.summary",
            "tenants": tenants,
            "totals": totals,
        }

    def totals(self) -> dict:
        return self.summary()["totals"]
