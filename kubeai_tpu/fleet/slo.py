"""SLO plane: declarative objectives judged each tick from fleet state.

The stack already emits rich raw telemetry (request lifecycle
histograms, fleet aggregator snapshots, door/governor/planner decision
records), but nothing JUDGES it — whether the fleet is meeting its
latency and availability objectives was a Grafana-and-human problem.
This module makes SLO attainment a first-class control signal:

  * **Objectives** are declared per model (CRD `slo:` block, system
    `slo:` config defaults): TTFT p95, ITL p99, availability, and
    door shed-rate. Every objective reduces to one discipline — a
    (total, bad) event count per evaluation tick — so one burn-rate
    engine and one error-budget ledger serve all four kinds.

  * **Evaluation** runs each tick from `FleetStateAggregator`
    snapshots (latency bucket deltas, with per-endpoint monotone
    accumulation so an engine restart's counter reset never counts
    history twice) and the front-door instrument bundle (availability
    and shed counters). Ticks whose telemetry coverage is below the
    governor's `minTelemetryCoverage` are REFUSED and counted — a
    blind judge recuses itself rather than guessing.

  * **Multi-window multi-burn-rate alerting** (the SRE-workbook
    shape): fast burn pages when both the short and long fast windows
    burn above `fastBurnThreshold`; slow burn warns on the slow
    window alone. The error-budget ledger is EXACT — integer event
    counts and `fractions.Fraction` arithmetic, so "budget remaining"
    in a decision record is a statement, not a float estimate.

  * **Outputs**: `kubeai_slo_*` gauges/counters, one JSON decision
    record per (model, objective) per tick on `kubeai.slo.alerts`,
    `GET /v1/slo`, a `pressure(model)` read the autoscaler and
    planner surface in their own decision records (`slo_pressure`),
    and — on a fast-burn page — the flight recorder's incident
    bundling, so the breach ships with its own evidence.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from fractions import Fraction

from kubeai_tpu.fleet.planner import model_scheduling_class
from kubeai_tpu.metrics import flightrecorder
from kubeai_tpu.metrics.registry import (
    DEFAULT_METRICS,
    Metrics,
    count_over_threshold,
    quantiles_from_buckets,
)

logger = logging.getLogger(__name__)

# One structured JSON record per (tick, model, objective): the SLO
# plane's decision trail, same contract as kubeai.autoscaler.decisions.
alert_log = logging.getLogger("kubeai.slo.alerts")

OBJ_TTFT_P95 = "ttft_p95"
OBJ_ITL_P99 = "itl_p99"
OBJ_AVAILABILITY = "availability"
OBJ_SHED_RATE = "shed_rate"

OBJECTIVE_KINDS = (OBJ_TTFT_P95, OBJ_ITL_P99, OBJ_AVAILABILITY, OBJ_SHED_RATE)

# Alert states (the kubeai_slo_alert_state gauge values).
STATE_OK = 0
STATE_SLOW_BURN = 1
STATE_FAST_BURN = 2
STATE_NAMES = {STATE_OK: "ok", STATE_SLOW_BURN: "slow", STATE_FAST_BURN: "fast"}

# Consecutive below-coverage refusals before the flight recorder's
# coverage-collapse trigger fires (one flap must not dump a bundle).
COVERAGE_COLLAPSE_TICKS = 3


class Objective:
    """One resolved objective: a latency threshold or a rate bound,
    reduced to an allowed-bad-fraction. `allowed` is an exact Fraction
    (1/20 for p95, 1/100 for p99, 1 - target for availability, the
    configured rate for shed)."""

    def __init__(self, kind: str, allowed: Fraction, threshold: float = 0.0,
                 target: float = 0.0):
        if kind not in OBJECTIVE_KINDS:
            raise ValueError(f"unknown objective kind {kind!r}")
        self.kind = kind
        self.allowed = allowed
        self.threshold = threshold  # seconds (latency kinds only)
        self.target = target        # the declared target, for records

    def describe(self) -> dict:
        d = {"kind": self.kind, "allowed": str(self.allowed)}
        if self.threshold:
            d["threshold_s"] = self.threshold
        if self.target:
            d["target"] = self.target
        return d


def resolve_objectives(model, cfg) -> list[Objective]:
    """The model's effective objectives: CRD `slo:` fields override the
    system `slo:` defaults field-by-field; a resolved 0 disables that
    objective. `Fraction(str(x))` keeps user-written decimals exact
    (0.999 stays 999/1000, not a binary-float neighborhood)."""
    spec = model.spec.slo
    out: list[Objective] = []
    ttft = spec.ttft_p95_seconds or cfg.ttft_p95_seconds
    if ttft > 0:
        out.append(Objective(OBJ_TTFT_P95, Fraction(5, 100), threshold=ttft))
    itl = spec.itl_p99_seconds or cfg.itl_p99_seconds
    if itl > 0:
        out.append(Objective(OBJ_ITL_P99, Fraction(1, 100), threshold=itl))
    avail = spec.availability or cfg.availability
    if avail > 0:
        out.append(Objective(
            OBJ_AVAILABILITY, Fraction(1) - Fraction(str(avail)),
            target=avail,
        ))
    shed = spec.max_shed_rate or cfg.max_shed_rate
    if shed > 0:
        out.append(Objective(
            OBJ_SHED_RATE, Fraction(str(shed)), target=shed,
        ))
    return out


class _HistAccumulator:
    """Monotone per-(model, histogram) bucket totals accumulated from
    per-endpoint cumulative scrapes. Engine restarts reset an
    endpoint's counters to zero; differencing raw sums across a restart
    would count all surviving history as fresh observations (or go
    negative). Per-endpoint deltas clamped at >= 0 — with a full
    restart detected as ANY bucket shrinking, in which case the
    endpoint's current totals count as the delta — keep the model-level
    series monotone and honest."""

    def __init__(self):
        # (model, hist) -> {"buckets": {le: float}, "count": float}
        self.totals: dict[tuple, dict] = {}
        # (model, hist, endpoint) -> last seen {"buckets", "count"}
        self._last: dict[tuple, dict] = {}

    def absorb(self, model: str, hist: str, endpoint: str,
               detail: dict) -> None:
        if not detail:
            return
        cur = {
            "buckets": {le: float(c) for le, c in detail.get("buckets", [])},
            "count": float(detail.get("count", 0.0)),
        }
        key = (model, hist, endpoint)
        prev = self._last.get(key)
        if prev is None or self._reset(prev, cur):
            delta = cur
        else:
            delta = {
                "buckets": {
                    le: max(0.0, c - prev["buckets"].get(le, 0.0))
                    for le, c in cur["buckets"].items()
                },
                "count": max(0.0, cur["count"] - prev["count"]),
            }
        self._last[key] = cur
        tot = self.totals.setdefault(
            (model, hist), {"buckets": {}, "count": 0.0}
        )
        for le, c in delta["buckets"].items():
            tot["buckets"][le] = tot["buckets"].get(le, 0.0) + c
        tot["count"] += delta["count"]

    @staticmethod
    def _reset(prev: dict, cur: dict) -> bool:
        if cur["count"] < prev["count"]:
            return True
        return any(
            cur["buckets"].get(le, 0.0) < c
            for le, c in prev["buckets"].items()
        )

    def forget_endpoint(self, model: str, endpoint: str) -> None:
        for hist in ("ttft", "itl"):
            self._last.pop((model, hist, endpoint), None)

    def model_total(self, model: str, hist: str) -> tuple[list, float]:
        """(sorted cumulative [(bound, cum)], total) of everything
        absorbed for the model so far."""
        tot = self.totals.get((model, hist))
        if not tot:
            return [], 0.0
        buckets = sorted(
            (float(le), c) for le, c in tot["buckets"].items()
        )
        return buckets, tot["count"]


class SLOEvaluator:
    """Judges every model's objectives each tick; owns the burn-rate
    state machine, the exact budget ledger, and the alert trail.

    `clock` is injectable (FakeClock in sims and tests); the evaluator
    never reads the wall directly. `min_telemetry_coverage` is the
    governor's threshold — the SLO plane refuses to judge what the
    governor would refuse to act on."""

    def __init__(
        self,
        cfg,
        aggregator,
        model_client,
        metrics: Metrics = DEFAULT_METRICS,
        recorder: flightrecorder.FlightRecorder | None = None,
        min_telemetry_coverage: float = 0.0,
        interval_s: float = 10.0,
        clock=time.time,
    ):
        self.cfg = cfg
        self.aggregator = aggregator
        self.model_client = model_client
        self.metrics = metrics
        self.recorder = recorder
        self.min_telemetry_coverage = float(min_telemetry_coverage)
        self.interval_s = (
            cfg.interval_seconds if cfg.interval_seconds > 0 else interval_s
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._accum = _HistAccumulator()
        # (model, objective) -> deque[(ts, total_cum:int, bad_cum:int)]
        # cumulative from evaluator start; the implicit epoch sample is
        # (start, 0, 0), so a window with no baseline uses zeros.
        self._samples: dict[tuple, deque] = {}
        self._alert_state: dict[tuple, int] = {}
        self._coverage_refusals: dict[str, int] = {}
        # Counter baselines (the bundle counters predate the evaluator).
        self._counter_base: dict[tuple, float] = {}
        self._last_eval: dict = {}
        self._prev_series: dict[str, tuple] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — next tick retries
                logger.warning("slo evaluation failed: %s", e)

    # -- SLI extraction ------------------------------------------------------

    def _counter_sum(self, counter, model: str, bad_only=None) -> float:
        total = 0.0
        for labels, value in counter.samples():
            if labels.get("model") != model:
                continue
            if bad_only is not None and not bad_only(labels):
                continue
            total += value
        return total

    def _rebased(self, key: tuple, value: float) -> float:
        """Counter value relative to the evaluator's first sight of it."""
        base = self._counter_base.setdefault(key, value)
        return max(0.0, value - base)

    def _sli_totals(self, model: str, obj: Objective,
                    entry: dict) -> tuple[int, int]:
        """Cumulative (total, bad) for one objective since evaluator
        start — integer event counts, the ledger's raw material."""
        m = self.metrics
        if obj.kind in (OBJ_TTFT_P95, OBJ_ITL_P99):
            hist = "ttft" if obj.kind == OBJ_TTFT_P95 else "itl"
            buckets, total = self._accum.model_total(model, hist)
            bad = count_over_threshold(buckets, total, obj.threshold)
            return int(round(total)), int(round(bad))
        if obj.kind == OBJ_AVAILABILITY:
            total = self._rebased(
                (model, "requests"),
                self._counter_sum(m.inference_requests_total, model),
            )
            bad = self._rebased(
                (model, "failures"),
                self._counter_sum(m.proxy_stream_resume_failures, model)
                + self._counter_sum(m.proxy_deadline_exhausted, model),
            )
            return int(round(total)), int(round(min(bad, total)))
        # OBJ_SHED_RATE: everything that knocked on the door vs refusals.
        admitted = self._rebased(
            (model, "admitted"),
            self._counter_sum(m.door_admitted, model),
        )
        shed = self._rebased(
            (model, "shed"),
            self._counter_sum(m.door_rejections, model),
        )
        return int(round(admitted + shed)), int(round(shed))

    def _absorb_snapshot(self, snap: dict) -> None:
        """Fold every fresh endpoint's latency buckets into the monotone
        per-model accumulators."""
        for model, entry in snap.get("models", {}).items():
            for addr, ep in entry.get("endpoints", {}).items():
                if ep.get("stale"):
                    continue
                self._accum.absorb(model, "ttft", addr, ep.get("ttft_hist"))
                self._accum.absorb(model, "itl", addr, ep.get("itl_hist"))

    # -- burn-rate windows ---------------------------------------------------

    def _window_counts(self, ring, now: float,
                       window_s: float) -> tuple[int, int]:
        """(total, bad) events inside the window ending now. Baseline =
        the newest sample at or before the window start (zeros when the
        evaluator is younger than the window — the window is then
        effectively 'since start', the standard cold-start behavior)."""
        if not ring:
            return 0, 0
        cur_ts, cur_total, cur_bad = ring[-1]
        base_total = base_bad = 0
        start = now - window_s
        for ts, total, bad in ring:
            if ts <= start:
                base_total, base_bad = total, bad
            else:
                break
        return cur_total - base_total, cur_bad - base_bad

    def _burn(self, ring, now: float, window_s: float,
              allowed: Fraction) -> float:
        total, bad = self._window_counts(ring, now, window_s)
        if total <= 0 or allowed <= 0:
            return 0.0
        return float(Fraction(bad, total) / allowed)

    def _ledger(self, ring, now: float, allowed: Fraction) -> dict:
        """The exact error-budget ledger over the budget window:
        integer counts in, Fractions out. `remaining` and
        `remaining_frac` are exact strings alongside the float gauges —
        the decision record states arithmetic, not an estimate."""
        total, bad = self._window_counts(
            ring, now, self.cfg.budget_window_seconds
        )
        if total <= 0:
            return {
                "window_s": self.cfg.budget_window_seconds,
                "total": 0, "bad": 0, "allowed": str(allowed),
                "budget": "0", "remaining": "0", "remaining_frac": 1.0,
                "remaining_frac_exact": "1", "exhausted": False,
            }
        budget = allowed * total
        remaining = budget - bad
        remaining_frac = (
            remaining / budget if budget > 0 else Fraction(0)
        )
        return {
            "window_s": self.cfg.budget_window_seconds,
            "total": total,
            "bad": bad,
            "allowed": str(allowed),
            "budget": str(budget),
            "remaining": str(remaining),
            "remaining_frac": float(remaining_frac),
            "remaining_frac_exact": str(remaining_frac),
            "exhausted": remaining < 0,
        }

    # -- one tick ------------------------------------------------------------

    def tick(self) -> dict:
        now = self._clock()
        cfg = self.cfg
        snap = self.aggregator.snapshot()
        results: dict = {"ts": now, "models": {}, "skipped": {}}
        models = self.model_client.list_all_models()
        snap_fresh = (
            snap is not None
            and now - snap["ts"] <= self.aggregator.staleness_s
        )
        if snap_fresh:
            self._absorb_snapshot(snap)
        for model in models:
            objectives = resolve_objectives(model, cfg)
            if not objectives:
                continue
            name = model.name
            coverage, fresh = self.aggregator.model_coverage(name)
            if not fresh or not snap_fresh:
                self.metrics.slo_skipped_ticks.inc(model=name, reason="stale")
                results["skipped"][name] = "stale"
                continue
            if (
                self.min_telemetry_coverage > 0
                and coverage is not None
                and coverage < self.min_telemetry_coverage
            ):
                self.metrics.slo_skipped_ticks.inc(
                    model=name, reason="coverage"
                )
                results["skipped"][name] = "coverage"
                n = self._coverage_refusals.get(name, 0) + 1
                self._coverage_refusals[name] = n
                if n == COVERAGE_COLLAPSE_TICKS and self.recorder:
                    self.recorder.trigger(
                        flightrecorder.TRIGGER_COVERAGE_COLLAPSE,
                        detail=(
                            f"model {name} telemetry coverage "
                            f"{coverage:.2f} < "
                            f"{self.min_telemetry_coverage:.2f} for "
                            f"{n} ticks"
                        ),
                    )
                continue
            self._coverage_refusals[name] = 0
            entry = snap["models"].get(name, {})
            results["models"][name] = self._judge_model(
                model, name, objectives, entry, now
            )
        self.metrics.slo_evaluations.inc()
        with self._lock:
            self._last_eval = results
        self._publish_gauges(results)
        if self.recorder is not None:
            self.recorder.capture_metrics(self.metrics.registry)
            for name in results["models"]:
                ex = self.metrics.request_ttft.exemplars(model=name)
                if ex:
                    self.recorder.note_exemplars(f"door_ttft/{name}", ex)
        return results

    def _judge_model(self, model, name: str, objectives, entry: dict,
                     now: float) -> dict:
        cfg = self.cfg
        cls = model_scheduling_class(model)
        out = {"class": cls, "objectives": {}}
        for obj in objectives:
            key = (name, obj.kind)
            ring = self._samples.setdefault(key, deque())
            total, bad = self._sli_totals(name, obj, entry)
            ring.append((now, total, bad))
            # Prune: keep one baseline sample older than the budget
            # window so _window_counts always finds its anchor.
            horizon = now - cfg.budget_window_seconds
            while len(ring) > 2 and ring[1][0] <= horizon:
                ring.popleft()
            burn_short = self._burn(
                ring, now, cfg.fast_burn_short_window_seconds, obj.allowed
            )
            burn_fast = self._burn(
                ring, now, cfg.fast_burn_window_seconds, obj.allowed
            )
            burn_slow = self._burn(
                ring, now, cfg.slow_burn_window_seconds, obj.allowed
            )
            if (
                burn_short >= cfg.fast_burn_threshold
                and burn_fast >= cfg.fast_burn_threshold
            ):
                state = STATE_FAST_BURN
            elif burn_slow >= cfg.slow_burn_threshold:
                state = STATE_SLOW_BURN
            else:
                state = STATE_OK
            prev_state = self._alert_state.get(key, STATE_OK)
            self._alert_state[key] = state
            ledger = self._ledger(ring, now, obj.allowed)
            record = {
                "ts": now,
                "model": name,
                "class": cls,
                "objective": obj.kind,
                **obj.describe(),
                "total": total,
                "bad": bad,
                "burn": {
                    "short": round(burn_short, 6),
                    "fast": round(burn_fast, 6),
                    "slow": round(burn_slow, 6),
                },
                "thresholds": {
                    "fast": cfg.fast_burn_threshold,
                    "slow": cfg.slow_burn_threshold,
                },
                "budget": ledger,
                "state": STATE_NAMES[state],
                "prev_state": STATE_NAMES[prev_state],
            }
            alert_log.info(json.dumps(record, sort_keys=True))
            out["objectives"][obj.kind] = record
            if state != prev_state:
                self._on_transition(name, obj, prev_state, state, record)
        return out

    def _on_transition(self, name: str, obj: Objective, prev: int,
                       state: int, record: dict) -> None:
        if state == STATE_FAST_BURN:
            self.metrics.slo_alerts.inc(
                model=name, objective=obj.kind, severity="fast"
            )
        elif state == STATE_SLOW_BURN and prev < STATE_SLOW_BURN:
            self.metrics.slo_alerts.inc(
                model=name, objective=obj.kind, severity="slow"
            )
        if self.recorder is None:
            return
        self.recorder.record(
            flightrecorder.SLO_ALERT, "slo", target=name,
            objective=obj.kind,
            state=STATE_NAMES[state], prev_state=STATE_NAMES[prev],
            burn=record["burn"],
        )
        if state == STATE_FAST_BURN:
            # The page IS the incident: dump the bundle while the rings
            # still hold the decisions that led here.
            self.recorder.trigger(
                flightrecorder.TRIGGER_FAST_BURN,
                detail=(
                    f"{name}/{obj.kind} fast burn "
                    f"(short={record['burn']['short']}, "
                    f"fast={record['burn']['fast']})"
                ),
            )

    # -- gauges (with label-churn hygiene) ----------------------------------

    def _publish_gauges(self, results: dict) -> None:
        m = self.metrics
        new_series: dict[str, tuple] = {}

        def set_(gauge, value, **labels):
            gauge.set(value, **labels)
            new_series.setdefault(gauge.name, (gauge, set()))[1].add(
                tuple(sorted(labels.items()))
            )

        for name, entry in results["models"].items():
            for kind, rec in entry["objectives"].items():
                for window, value in rec["burn"].items():
                    set_(
                        m.slo_burn_rate, value,
                        model=name, objective=kind, window=window,
                    )
                set_(
                    m.slo_error_budget_remaining,
                    rec["budget"]["remaining_frac"],
                    model=name, objective=kind,
                )
                state_value = {
                    v: k for k, v in STATE_NAMES.items()
                }[rec["state"]]
                set_(
                    m.slo_alert_state, state_value,
                    model=name, objective=kind,
                )
                key = (name, kind)
                prev_counts = getattr(self, "_prev_counts", {})
                p_total, p_bad = prev_counts.get(key, (0, 0))
                if rec["total"] >= p_total:
                    m.slo_events.inc(
                        rec["total"] - p_total, model=name, objective=kind
                    )
                if rec["bad"] >= p_bad:
                    m.slo_bad_events.inc(
                        rec["bad"] - p_bad, model=name, objective=kind
                    )
                prev_counts[key] = (rec["total"], rec["bad"])
                self._prev_counts = prev_counts
        for gname, (gauge, keys) in self._prev_series.items():
            current = new_series.get(gname, (gauge, set()))[1]
            for k in keys - current:
                gauge.remove(**dict(k))
        self._prev_series = new_series

    # -- consumer API --------------------------------------------------------

    def pressure(self, model: str) -> dict | None:
        """The control loops' read: the model's worst alert state and
        which objective drove it, or None when the model was not judged
        (no objectives, skipped tick, or no tick yet)."""
        with self._lock:
            entry = (self._last_eval.get("models") or {}).get(model)
        if entry is None:
            return None
        worst = STATE_OK
        driver = None
        for kind, rec in entry["objectives"].items():
            value = {v: k for k, v in STATE_NAMES.items()}[rec["state"]]
            if value > worst:
                worst, driver = value, kind
        return {
            "state": STATE_NAMES[worst],
            "level": worst,
            "objective": driver,
        }

    def state_payload(self) -> dict:
        """`GET /v1/slo`: the latest evaluation plus the flight
        recorder's incident index."""
        with self._lock:
            last = dict(self._last_eval)
        payload = {"object": "slo.state", "interval_s": self.interval_s}
        payload.update(last)
        if self.recorder is not None:
            payload["flight_recorder"] = self.recorder.state_payload()
        return payload
