"""Shared HTTP server base for every serving surface in the tree.

The stdlib default accept backlog (request_queue_size=5) resets
connections under reference-scale bursts — the prefix-LB benchmark runs
800–8000 concurrent streams (reference:
docs/benchmarks/prefix-aware-load-balancing.md:450-512). Admission
control belongs to the application (bounded queues + 429), never to the
kernel backlog.
"""

from __future__ import annotations

from http.server import ThreadingHTTPServer


class DeepBacklogHTTPServer(ThreadingHTTPServer):
    request_queue_size = 1024
