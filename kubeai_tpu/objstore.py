"""Zero-dependency object-store clients: GCS (JSON API) and S3 (SigV4).

The reference ships a loader container with gcloud/awscli/ossutil
(reference: components/model-loader/load.sh:20-67, Dockerfile). This
environment installs nothing, so the stores are spoken natively:

  gs://bucket/prefix   — GCS JSON API over HTTPS. Auth from the GKE
      metadata server when available, anonymous otherwise.
      STORAGE_EMULATOR_HOST / endpoint override points at the
      fake-gcs-server surface used in tests.
  s3://bucket/prefix   — S3 REST with AWS Signature V4 (hand-rolled:
      hmac+sha256 only). Credentials from AWS_ACCESS_KEY_ID/
      AWS_SECRET_ACCESS_KEY; unsigned requests when absent (test fakes,
      public buckets). AWS_ENDPOINT_URL overrides for MinIO-style fakes.
  oss://bucket/prefix  — Alibaba OSS through its S3-compatible surface:
      the S3 client with OSS_ENDPOINT (+ OSS_ACCESS_KEY_ID/SECRET).

Streaming discipline: downloads go object→file in fixed-size chunks
(never whole-object in memory), one object at a time — the weight
loader's shard-at-a-time path builds on this.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import json
import logging
import os
import urllib.parse
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

CHUNK = 1 << 20  # 1 MiB copy chunks


class ObjStoreError(RuntimeError):
    pass


def parse_url(url: str) -> tuple[str, str, str]:
    """'gs://bucket/a/b' -> ('gs', 'bucket', 'a/b')."""
    parsed = urllib.parse.urlparse(url)
    return parsed.scheme, parsed.netloc, parsed.path.lstrip("/")


def client_for(url: str):
    scheme, _, _ = parse_url(url)
    if scheme == "gs":
        return GCSClient()
    if scheme == "s3":
        return S3Client()
    if scheme == "oss":
        return S3Client(
            endpoint=os.environ.get("OSS_ENDPOINT"),
            access_key=os.environ.get("OSS_ACCESS_KEY_ID"),
            secret_key=os.environ.get("OSS_ACCESS_KEY_SECRET"),
        )
    raise ObjStoreError(f"unsupported object-store scheme {scheme!r}")


def _http(endpoint: str, default_host: str, timeout: float = 120.0):
    """HTTPConnection for an endpoint override (scheme optional) or the
    default HTTPS host. Shared by the GCS client and the Pub/Sub broker."""
    if endpoint:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        p = urllib.parse.urlparse(endpoint)
        if p.scheme == "https":
            return http.client.HTTPSConnection(p.hostname, p.port or 443, timeout=timeout)
        return http.client.HTTPConnection(p.hostname, p.port or 80, timeout=timeout)
    return http.client.HTTPSConnection(default_host, 443, timeout=timeout)


_META_LOCK = __import__("threading").Lock()
_META_TOKEN: tuple[str, float] | None = None
_META_NEGATIVE_UNTIL = [0.0]  # cache "no SA / unreachable" for 60s


def gcp_metadata_token(required: bool = False) -> str | None:
    """OAuth token from the GKE metadata server (workload identity /
    node SA), cached with 60s expiry skew. None (anonymous fallback)
    ONLY for the definitive no-credentials signals — unreachable server
    or 404 no-default-SA — and that negative result is cached for 60s so
    hot paths don't re-poll a 5s-timeout endpoint. Transient errors
    (429/5xx) raise: silently downgrading to anonymous would turn them
    into misleading permission errors downstream."""
    global _META_TOKEN
    import time

    now = time.time()
    with _META_LOCK:
        if _META_TOKEN and _META_TOKEN[1] > now + 60:
            return _META_TOKEN[0]
        if _META_NEGATIVE_UNTIL[0] > now and not required:
            return None
        try:
            conn = http.client.HTTPConnection(
                "metadata.google.internal", 80, timeout=5
            )
            conn.request(
                "GET",
                "/computeMetadata/v1/instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"},
            )
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
        except OSError as e:
            _META_NEGATIVE_UNTIL[0] = now + 60
            if required:
                raise ObjStoreError(f"metadata server unreachable: {e}")
            return None
        if resp.status == 404:  # reachable, no default service account
            _META_NEGATIVE_UNTIL[0] = now + 60
            if required:
                raise ObjStoreError("metadata server: no default service account")
            return None
        if resp.status != 200:  # transient (429/5xx): surface, don't downgrade
            raise ObjStoreError(
                f"metadata token: {resp.status} {body[:120]!r}"
            )
        data = json.loads(body)
        _META_TOKEN = (
            data["access_token"],
            now + float(data.get("expires_in", 300)),
        )
        return _META_TOKEN[0]


class GCSClient:
    """GCS JSON API: list / download (alt=media, chunked) / upload."""

    def __init__(self, endpoint: str | None = None):
        self.endpoint = endpoint or os.environ.get("STORAGE_EMULATOR_HOST")

    def _auth(self) -> dict:
        if self.endpoint:
            return {}
        token = gcp_metadata_token()
        return {"Authorization": f"Bearer {token}"} if token else {}

    def _conn(self):
        return _http(self.endpoint, "storage.googleapis.com")

    def list(self, bucket: str, prefix: str) -> list[dict]:
        """[{name, size}] under prefix (paginated)."""
        items, page = [], None
        while True:
            q = {"prefix": prefix, "maxResults": "1000"}
            if page:
                q["pageToken"] = page
            conn = self._conn()
            try:
                conn.request(
                    "GET",
                    f"/storage/v1/b/{urllib.parse.quote(bucket, safe='')}/o?"
                    + urllib.parse.urlencode(q),
                    headers=self._auth(),
                )
                resp = conn.getresponse()
                body = resp.read()
                if resp.status >= 400:
                    raise ObjStoreError(
                        f"gcs list {bucket}/{prefix}: {resp.status} {body[:200]!r}"
                    )
            finally:
                conn.close()
            out = json.loads(body)
            items += [
                {"name": o["name"], "size": int(o.get("size", 0))}
                for o in out.get("items", [])
            ]
            page = out.get("nextPageToken")
            if not page:
                return items

    def get_to_file(self, bucket: str, name: str, dest_path: str) -> None:
        conn = self._conn()
        try:
            conn.request(
                "GET",
                f"/download/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                f"/o/{urllib.parse.quote(name, safe='')}?alt=media",
                headers=self._auth(),
            )
            resp = conn.getresponse()
            if resp.status >= 400:
                raise ObjStoreError(
                    f"gcs get {bucket}/{name}: {resp.status}"
                )
            os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
            with open(dest_path, "wb") as f:
                while True:
                    chunk = resp.read(CHUNK)
                    if not chunk:
                        break
                    f.write(chunk)
        finally:
            conn.close()

    def put_from_file(self, bucket: str, name: str, src_path: str) -> None:
        size = os.path.getsize(src_path)
        conn = self._conn()
        try:
            with open(src_path, "rb") as f:
                headers = {
                    "Content-Length": str(size),
                    "Content-Type": "application/octet-stream",
                }
                headers.update(self._auth())
                conn.request(
                    "POST",
                    f"/upload/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                    f"/o?uploadType=media&name={urllib.parse.quote(name, safe='')}",
                    body=f,
                    headers=headers,
                )
                resp = conn.getresponse()
                resp.read()
                if resp.status >= 400:
                    raise ObjStoreError(
                        f"gcs put {bucket}/{name}: {resp.status}"
                    )
        finally:
            conn.close()


def sigv4_sign(
    method: str,
    path: str,
    query: str,
    extra_headers: dict[str, str],
    payload_hash: str,
    *,
    service: str,
    region: str,
    host: str,
    access_key: str,
    secret_key: str,
) -> dict:
    """AWS Signature Version 4 over (host, x-amz-date, extra_headers) —
    shared by the S3 object store and the SQS messenger driver (same
    algorithm, different service strings and signed-header sets)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    headers = {"host": host, "x-amz-date": amz_date}
    headers.update({k.lower(): v for k, v in extra_headers.items()})
    names = sorted(headers)
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in names)
    signed = ";".join(names)
    canonical = "\n".join(
        [method, path, query, canonical_headers, signed, payload_hash]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ]
    )

    def hm(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = hm(("AWS4" + secret_key).encode(), datestamp)
    k = hm(k, region)
    k = hm(k, service)
    k = hm(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = {k: v for k, v in extra_headers.items()}
    out["x-amz-date"] = amz_date
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}"
    )
    return out


class S3Client:
    """S3 REST (path-style) with optional SigV4 signing."""

    def __init__(
        self,
        endpoint: str | None = None,
        access_key: str | None = None,
        secret_key: str | None = None,
        region: str | None = None,
    ):
        self.endpoint = endpoint or os.environ.get("AWS_ENDPOINT_URL")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID")
        self.secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY")
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")

    def _host(self) -> str:
        if self.endpoint:
            return urllib.parse.urlparse(
                self.endpoint if "://" in self.endpoint
                else "http://" + self.endpoint
            ).netloc
        return f"s3.{self.region}.amazonaws.com"

    def _conn(self):
        return _http(self.endpoint, f"s3.{self.region}.amazonaws.com")

    def _sign(
        self, method: str, path: str, query: str, payload_hash: str
    ) -> dict:
        """AWS Signature Version 4 (headers-only, single-chunk)."""
        if not self.access_key or not self.secret_key:
            return {}  # unsigned: fakes/public buckets
        return sigv4_sign(
            method, path, query,
            {"x-amz-content-sha256": payload_hash},
            payload_hash,
            service="s3", region=self.region, host=self._host(),
            access_key=self.access_key, secret_key=self.secret_key,
        )

    EMPTY_SHA = hashlib.sha256(b"").hexdigest()

    def list(self, bucket: str, prefix: str) -> list[dict]:
        items, token = [], None
        while True:
            q = {"list-type": "2", "prefix": prefix, "max-keys": "1000"}
            if token:
                q["continuation-token"] = token
            # SigV4 canonicalizes with %20, not '+': quote, not quote_plus.
            query = urllib.parse.urlencode(
                sorted(q.items()), quote_via=urllib.parse.quote
            )
            path = f"/{bucket}"
            conn = self._conn()
            try:
                headers = self._sign("GET", path, query, self.EMPTY_SHA)
                conn.request("GET", f"{path}?{query}", headers=headers)
                resp = conn.getresponse()
                body = resp.read()
                if resp.status >= 400:
                    raise ObjStoreError(
                        f"s3 list {bucket}/{prefix}: {resp.status} {body[:200]!r}"
                    )
            finally:
                conn.close()
            ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
            root = ET.fromstring(body)
            # Tolerate namespaced and namespace-less XML (fakes).
            def findall(tag):
                return root.findall(f"s3:{tag}", ns) or root.findall(tag)

            for c in findall("Contents"):
                key = c.find("s3:Key", ns)
                key = key if key is not None else c.find("Key")
                size = c.find("s3:Size", ns)
                size = size if size is not None else c.find("Size")
                items.append(
                    {"name": key.text, "size": int(size.text if size is not None else 0)}
                )
            trunc = findall("IsTruncated")
            token_el = findall("NextContinuationToken")
            if trunc and trunc[0].text == "true" and token_el:
                token = token_el[0].text
            else:
                return items

    def get_to_file(self, bucket: str, name: str, dest_path: str) -> None:
        path = f"/{bucket}/{urllib.parse.quote(name)}"
        conn = self._conn()
        try:
            headers = self._sign("GET", path, "", self.EMPTY_SHA)
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
            if resp.status >= 400:
                raise ObjStoreError(f"s3 get {bucket}/{name}: {resp.status}")
            os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
            with open(dest_path, "wb") as f:
                while True:
                    chunk = resp.read(CHUNK)
                    if not chunk:
                        break
                    f.write(chunk)
        finally:
            conn.close()

    def put_from_file(self, bucket: str, name: str, src_path: str) -> None:
        path = f"/{bucket}/{urllib.parse.quote(name)}"
        # Sign with UNSIGNED-PAYLOAD so the file streams without a
        # whole-file hash pass into memory.
        conn = self._conn()
        try:
            with open(src_path, "rb") as f:
                headers = {
                    "Content-Length": str(os.path.getsize(src_path)),
                }
                headers.update(self._sign("PUT", path, "", "UNSIGNED-PAYLOAD"))
                conn.request("PUT", path, body=f, headers=headers)
                resp = conn.getresponse()
                resp.read()
                if resp.status >= 400:
                    raise ObjStoreError(
                        f"s3 put {bucket}/{name}: {resp.status}"
                    )
        finally:
            conn.close()


def download_prefix(url: str, dest_dir: str, client=None) -> list[str]:
    """Download every object under `url` into dest_dir (relative names),
    one object at a time, chunked to disk. Returns the local paths."""
    scheme, bucket, prefix = parse_url(url)
    client = client or client_for(url)
    objects = client.list(bucket, prefix)
    # Store listing is plain string-prefix matching: 'models/llama' also
    # matches 'models/llama-70b/...'. Keep only the directory itself.
    if prefix and not prefix.endswith("/"):
        objects = [
            o for o in objects
            if o["name"] == prefix or o["name"].startswith(prefix + "/")
        ]
    if not objects:
        raise ObjStoreError(f"no objects under {url}")
    out = []
    for obj in objects:
        rel = obj["name"][len(prefix):].lstrip("/") if prefix else obj["name"]
        if not rel:  # the prefix itself as an object
            rel = os.path.basename(obj["name"])
        dest = os.path.join(dest_dir, rel)
        logger.info("downloading %s/%s (%d bytes)", bucket, obj["name"], obj["size"])
        client.get_to_file(bucket, obj["name"], dest)
        out.append(dest)
    return out


def upload_dir(src_dir: str, url: str, client=None) -> list[str]:
    """Upload a directory tree under the destination prefix."""
    scheme, bucket, prefix = parse_url(url)
    client = client or client_for(url)
    uploaded = []
    for root, _, files in os.walk(src_dir):
        for fname in files:
            full = os.path.join(root, fname)
            rel = os.path.relpath(full, src_dir)
            key = f"{prefix.rstrip('/')}/{rel}" if prefix else rel
            logger.info("uploading %s -> %s/%s", rel, bucket, key)
            client.put_from_file(bucket, key, full)
            uploaded.append(key)
    return uploaded


class KVSpillStore:
    """Spill/fill store for evicted hot-prefix KV pages (the objstore leg
    of the cluster KV-sharing tier). Each entry is one serialized
    single-page `KVPageExport` blob keyed by its chain hash (hex), so a
    fill is a plain GET and needs no index.

    Two backends behind one interface:
      - in-memory LRU (url=""): the default and the test surface — spill
        stays a node-local optimization with a hard byte cap;
      - object store (gs://, s3://, oss://): pages persist as
        `<prefix>/<hash>.kvp` objects via the zero-dependency clients
        above, shared fleet-wide.

    Every method is best-effort by contract: the callers (eviction hook,
    fetch fallback) treat any failure as a miss and recompute.
    """

    def __init__(self, url: str = "", max_bytes: int = 256 << 20):
        from collections import OrderedDict

        self.url = url
        self.max_bytes = max_bytes
        self._mem: "OrderedDict[str, bytes]" = OrderedDict()
        self._mem_bytes = 0
        self.puts = 0
        self.gets = 0
        self.hits = 0

    def _key(self, hash_hex: str) -> tuple[str, str]:
        _scheme, bucket, prefix = parse_url(self.url)
        name = f"{hash_hex}.kvp"
        return bucket, f"{prefix.rstrip('/')}/{name}" if prefix else name

    def put(self, hash_hex: str, blob: bytes) -> None:
        self.puts += 1
        if not self.url:
            if len(blob) > self.max_bytes:
                return
            old = self._mem.pop(hash_hex, None)
            if old is not None:
                self._mem_bytes -= len(old)
            self._mem[hash_hex] = blob
            self._mem_bytes += len(blob)
            while self._mem_bytes > self.max_bytes and self._mem:
                _h, dropped = self._mem.popitem(last=False)
                self._mem_bytes -= len(dropped)
            return
        import tempfile

        bucket, key = self._key(hash_hex)
        client = client_for(self.url)
        with tempfile.NamedTemporaryFile(delete=False) as f:
            f.write(blob)
            tmp = f.name
        try:
            client.put_from_file(bucket, key, tmp)
        finally:
            os.unlink(tmp)

    def get(self, hash_hex: str) -> bytes | None:
        self.gets += 1
        if not self.url:
            blob = self._mem.get(hash_hex)
            if blob is not None:
                self._mem.move_to_end(hash_hex)
                self.hits += 1
            return blob
        import tempfile

        bucket, key = self._key(hash_hex)
        client = client_for(self.url)
        tmp = tempfile.mktemp()
        try:
            client.get_to_file(bucket, key, tmp)
            with open(tmp, "rb") as f:
                blob = f.read()
            self.hits += 1
            return blob
        except Exception:
            return None
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
