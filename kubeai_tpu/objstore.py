"""Zero-dependency object-store clients: GCS (JSON API) and S3 (SigV4).

The reference ships a loader container with gcloud/awscli/ossutil
(reference: components/model-loader/load.sh:20-67, Dockerfile). This
environment installs nothing, so the stores are spoken natively:

  gs://bucket/prefix   — GCS JSON API over HTTPS. Auth from the GKE
      metadata server when available, anonymous otherwise.
      STORAGE_EMULATOR_HOST / endpoint override points at the
      fake-gcs-server surface used in tests.
  s3://bucket/prefix   — S3 REST with AWS Signature V4 (hand-rolled:
      hmac+sha256 only). Credentials from AWS_ACCESS_KEY_ID/
      AWS_SECRET_ACCESS_KEY; unsigned requests when absent (test fakes,
      public buckets). AWS_ENDPOINT_URL overrides for MinIO-style fakes.
  oss://bucket/prefix  — Alibaba OSS through its S3-compatible surface:
      the S3 client with OSS_ENDPOINT (+ OSS_ACCESS_KEY_ID/SECRET).

  file://dir/prefix    — a plain directory behind the same client
      interface (PVC-mounted snapshot volumes, tests, bench runs with
      no bucket in reach).

Streaming discipline: downloads go object→file in fixed-size chunks
(never whole-object in memory), one object at a time — the weight
loader's shard-at-a-time path builds on this. Every wire operation
retries transient failures (5xx/429, connection resets, short reads)
with capped exponential backoff + jitter; an interrupted download
RESUMES from the bytes already on disk via a Range request instead of
restarting. Retries are counted in `RETRIES` and exported as
`kubeai_objstore_retries_total`.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import json
import logging
import os
import random
import time
import urllib.parse
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

CHUNK = 1 << 20  # 1 MiB copy chunks


class ObjStoreError(RuntimeError):
    pass


class TransientStoreError(ObjStoreError):
    """A store response worth retrying (5xx, 429): the bytes may well
    arrive on the next attempt. Non-transient 4xx stay plain
    `ObjStoreError` and fail immediately."""


class SnapshotMismatch(ObjStoreError):
    """A snapshot manifest whose fingerprint does not match the booting
    engine's — serving from it could silently run a stale layout, so
    callers MUST fall back to the full load path."""


# -- transient-failure retry discipline ---------------------------------------
#
# One flaky read used to fail the whole operation (a multi-GB weight
# download restarted from byte 0 on a connection reset). Every request
# now runs under `with_retries`: capped exponential backoff with full
# jitter, counted in RETRIES (scraped into kubeai_objstore_retries_total
# at collect time by the instrument bundles).

RETRIES = {"total": 0.0}  # read by metrics.registry.ObjstoreRetries

RETRY_ATTEMPTS = int(os.environ.get("KUBEAI_OBJSTORE_RETRIES", "4"))
RETRY_BASE_S = 0.2
RETRY_CAP_S = 8.0

# Module-level so tests (and latency-sensitive embedders) can replace
# the sleeper without threading a parameter through every client call.
RETRY_SLEEP = time.sleep


def _is_transient(exc: BaseException) -> bool:
    """Worth retrying: our own transient marker, connection-layer
    failures (reset/aborted/refused mid-pool, broken pipe), timeouts,
    and short reads (`IncompleteRead`, `RemoteDisconnected`)."""
    return isinstance(
        exc,
        (
            TransientStoreError,
            ConnectionError,
            TimeoutError,
            http.client.IncompleteRead,
            http.client.BadStatusLine,
        ),
    )


def with_retries(desc: str, fn, *, attempts: int | None = None,
                 sleep=None, rng=None):
    """Run `fn()` retrying transient failures up to `attempts` extra
    times with capped exponential backoff + full jitter. `fn` must be
    safe to re-run whole (each client attempt opens a fresh
    connection); download resume is handled inside `get_to_file`, not
    here."""
    attempts = RETRY_ATTEMPTS if attempts is None else attempts
    sleep = sleep if sleep is not None else RETRY_SLEEP
    rng = rng if rng is not None else random.random
    for i in range(attempts + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — filtered below
            if not _is_transient(e) or i >= attempts:
                raise
            RETRIES["total"] += 1
            delay = min(RETRY_CAP_S, RETRY_BASE_S * (2 ** i)) * (
                0.5 + rng()
            )
            logger.warning(
                "objstore %s: %s — retry %d/%d in %.2fs",
                desc, e, i + 1, attempts, delay,
            )
            sleep(delay)


def _status_error(op: str, status: int, detail: str = "") -> ObjStoreError:
    msg = f"{op}: {status}" + (f" {detail}" if detail else "")
    if status >= 500 or status == 429:
        return TransientStoreError(msg)
    return ObjStoreError(msg)


class _RangeIgnored(TransientStoreError):
    """The server answered a nonzero Range request with 200-whole-object.
    Appending that stream would duplicate the resumed prefix, so the
    download restarts from byte 0 instead."""


def _read_exact(resp, n: int, desc: str) -> bytes:
    """Read exactly n bytes from a response; a cleanly-closed short
    stream raises IncompleteRead so the retry layer re-requests."""
    buf = bytearray()
    while len(buf) < n:
        chunk = resp.read(min(CHUNK, n - len(buf)))
        if not chunk:
            raise http.client.IncompleteRead(bytes(buf), n - len(buf))
        buf += chunk
    return bytes(buf)


def _ranged_get_to_file(open_stream, desc: str, dest_path: str) -> None:
    """Streaming download with mid-stream resume: on a transient failure
    the next attempt re-requests `bytes=<bytes_on_disk>-` and APPENDS,
    instead of redownloading the whole object. A fresh call always
    truncates dest, so stale partials from a previous process never
    leak into the result. `open_stream(start)` must return a
    (response, connection) pair positioned at byte `start`."""
    os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
    state = {"offset": 0}

    def attempt():
        try:
            resp, conn = open_stream(state["offset"])
        except _RangeIgnored:
            state["offset"] = 0
            resp, conn = open_stream(0)
        try:
            # http.client's read(amt) returns b"" on a premature close
            # instead of raising, so a mid-stream cut would otherwise
            # pass for end-of-object and leave a silently truncated
            # file. Hold it to the advertised Content-Length ourselves.
            expected = resp.length
            received = 0
            with open(dest_path, "wb" if state["offset"] == 0 else "ab") as f:
                while True:
                    chunk = resp.read(CHUNK)
                    if not chunk:
                        break
                    f.write(chunk)
                    received += len(chunk)
                    state["offset"] += len(chunk)
            if expected is not None and received < expected:
                raise http.client.IncompleteRead(b"", expected - received)
        finally:
            conn.close()

    with_retries(f"get {desc}", attempt)


def parse_url(url: str) -> tuple[str, str, str]:
    """'gs://bucket/a/b' -> ('gs', 'bucket', 'a/b')."""
    parsed = urllib.parse.urlparse(url)
    return parsed.scheme, parsed.netloc, parsed.path.lstrip("/")


def client_for(url: str):
    scheme, _, _ = parse_url(url)
    if scheme == "gs":
        return GCSClient()
    if scheme == "s3":
        return S3Client()
    if scheme == "oss":
        return S3Client(
            endpoint=os.environ.get("OSS_ENDPOINT"),
            access_key=os.environ.get("OSS_ACCESS_KEY_ID"),
            secret_key=os.environ.get("OSS_ACCESS_KEY_SECRET"),
        )
    if scheme == "file":
        return LocalDirClient()
    raise ObjStoreError(f"unsupported object-store scheme {scheme!r}")


def _http(endpoint: str, default_host: str, timeout: float = 120.0):
    """HTTPConnection for an endpoint override (scheme optional) or the
    default HTTPS host. Shared by the GCS client and the Pub/Sub broker."""
    if endpoint:
        if "://" not in endpoint:
            endpoint = "http://" + endpoint
        p = urllib.parse.urlparse(endpoint)
        if p.scheme == "https":
            return http.client.HTTPSConnection(p.hostname, p.port or 443, timeout=timeout)
        return http.client.HTTPConnection(p.hostname, p.port or 80, timeout=timeout)
    return http.client.HTTPSConnection(default_host, 443, timeout=timeout)


_META_LOCK = __import__("threading").Lock()
_META_TOKEN: tuple[str, float] | None = None
_META_NEGATIVE_UNTIL = [0.0]  # cache "no SA / unreachable" for 60s


def gcp_metadata_token(required: bool = False) -> str | None:
    """OAuth token from the GKE metadata server (workload identity /
    node SA), cached with 60s expiry skew. None (anonymous fallback)
    ONLY for the definitive no-credentials signals — unreachable server
    or 404 no-default-SA — and that negative result is cached for 60s so
    hot paths don't re-poll a 5s-timeout endpoint. Transient errors
    (429/5xx) raise: silently downgrading to anonymous would turn them
    into misleading permission errors downstream."""
    global _META_TOKEN
    import time

    now = time.time()
    with _META_LOCK:
        if _META_TOKEN and _META_TOKEN[1] > now + 60:
            return _META_TOKEN[0]
        if _META_NEGATIVE_UNTIL[0] > now and not required:
            return None
        try:
            conn = http.client.HTTPConnection(
                "metadata.google.internal", 80, timeout=5
            )
            conn.request(
                "GET",
                "/computeMetadata/v1/instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"},
            )
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
        except OSError as e:
            _META_NEGATIVE_UNTIL[0] = now + 60
            if required:
                raise ObjStoreError(f"metadata server unreachable: {e}")
            return None
        if resp.status == 404:  # reachable, no default service account
            _META_NEGATIVE_UNTIL[0] = now + 60
            if required:
                raise ObjStoreError("metadata server: no default service account")
            return None
        if resp.status != 200:  # transient (429/5xx): surface, don't downgrade
            raise ObjStoreError(
                f"metadata token: {resp.status} {body[:120]!r}"
            )
        data = json.loads(body)
        _META_TOKEN = (
            data["access_token"],
            now + float(data.get("expires_in", 300)),
        )
        return _META_TOKEN[0]


class GCSClient:
    """GCS JSON API: list / download (alt=media, chunked) / upload."""

    def __init__(self, endpoint: str | None = None):
        self.endpoint = endpoint or os.environ.get("STORAGE_EMULATOR_HOST")

    def _auth(self) -> dict:
        if self.endpoint:
            return {}
        token = gcp_metadata_token()
        return {"Authorization": f"Bearer {token}"} if token else {}

    def _conn(self):
        return _http(self.endpoint, "storage.googleapis.com")

    def list(self, bucket: str, prefix: str) -> list[dict]:
        """[{name, size}] under prefix (paginated)."""
        items, page = [], None
        while True:
            q = {"prefix": prefix, "maxResults": "1000"}
            if page:
                q["pageToken"] = page

            def attempt():
                conn = self._conn()
                try:
                    conn.request(
                        "GET",
                        f"/storage/v1/b/{urllib.parse.quote(bucket, safe='')}/o?"
                        + urllib.parse.urlencode(q),
                        headers=self._auth(),
                    )
                    resp = conn.getresponse()
                    body = resp.read()
                    if resp.status >= 400:
                        raise _status_error(
                            f"gcs list {bucket}/{prefix}",
                            resp.status, repr(body[:200]),
                        )
                    return body
                finally:
                    conn.close()

            out = json.loads(
                with_retries(f"list gs://{bucket}/{prefix}", attempt)
            )
            items += [
                {"name": o["name"], "size": int(o.get("size", 0))}
                for o in out.get("items", [])
            ]
            page = out.get("nextPageToken")
            if not page:
                return items

    def _object_path(self, bucket: str, name: str) -> str:
        return (
            f"/download/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
            f"/o/{urllib.parse.quote(name, safe='')}?alt=media"
        )

    def get_to_file(self, bucket: str, name: str, dest_path: str) -> None:
        _ranged_get_to_file(
            lambda start: self._open_stream(bucket, name, start),
            f"gs://{bucket}/{name}", dest_path,
        )

    def get_range(self, bucket: str, name: str, start: int, end: int) -> bytes:
        """Inclusive byte range [start, end] of one object."""
        def attempt():
            resp, conn = self._open_stream(bucket, name, start, end)
            try:
                return _read_exact(
                    resp, end - start + 1, f"gs://{bucket}/{name}"
                )
            finally:
                conn.close()

        return with_retries(
            f"get gs://{bucket}/{name}[{start}-{end}]", attempt
        )

    def _open_stream(
        self, bucket: str, name: str, start: int = 0, end: int | None = None
    ):
        """(response, connection) streaming the object from `start`
        (to `end` inclusive when given). Returns a NON-206 response for
        start=0; a server that ignores a nonzero Range raises so the
        caller restarts from scratch instead of appending a duplicate
        prefix."""
        headers = self._auth()
        if start > 0 or end is not None:
            headers = dict(headers)
            headers["Range"] = (
                f"bytes={start}-" if end is None else f"bytes={start}-{end}"
            )
        conn = self._conn()
        try:
            conn.request("GET", self._object_path(bucket, name), headers=headers)
            resp = conn.getresponse()
        except BaseException:
            conn.close()
            raise
        if resp.status >= 400:
            conn.close()
            raise _status_error(f"gcs get {bucket}/{name}", resp.status)
        if (start > 0 or end is not None) and resp.status != 206:
            conn.close()
            raise _RangeIgnored(
                f"gcs get {bucket}/{name}: server ignored Range "
                f"(status {resp.status})"
            )
        return resp, conn

    def put_from_file(self, bucket: str, name: str, src_path: str) -> None:
        size = os.path.getsize(src_path)

        def attempt():
            conn = self._conn()
            try:
                with open(src_path, "rb") as f:
                    headers = {
                        "Content-Length": str(size),
                        "Content-Type": "application/octet-stream",
                    }
                    headers.update(self._auth())
                    conn.request(
                        "POST",
                        f"/upload/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                        f"/o?uploadType=media&name={urllib.parse.quote(name, safe='')}",
                        body=f,
                        headers=headers,
                    )
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status >= 400:
                        raise _status_error(
                            f"gcs put {bucket}/{name}", resp.status
                        )
            finally:
                conn.close()

        with_retries(f"put gs://{bucket}/{name}", attempt)


def sigv4_sign(
    method: str,
    path: str,
    query: str,
    extra_headers: dict[str, str],
    payload_hash: str,
    *,
    service: str,
    region: str,
    host: str,
    access_key: str,
    secret_key: str,
) -> dict:
    """AWS Signature Version 4 over (host, x-amz-date, extra_headers) —
    shared by the S3 object store and the SQS messenger driver (same
    algorithm, different service strings and signed-header sets)."""
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    headers = {"host": host, "x-amz-date": amz_date}
    headers.update({k.lower(): v for k, v in extra_headers.items()})
    names = sorted(headers)
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in names)
    signed = ";".join(names)
    canonical = "\n".join(
        [method, path, query, canonical_headers, signed, payload_hash]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ]
    )

    def hm(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = hm(("AWS4" + secret_key).encode(), datestamp)
    k = hm(k, region)
    k = hm(k, service)
    k = hm(k, "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = {k: v for k, v in extra_headers.items()}
    out["x-amz-date"] = amz_date
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}"
    )
    return out


class S3Client:
    """S3 REST (path-style) with optional SigV4 signing."""

    def __init__(
        self,
        endpoint: str | None = None,
        access_key: str | None = None,
        secret_key: str | None = None,
        region: str | None = None,
    ):
        self.endpoint = endpoint or os.environ.get("AWS_ENDPOINT_URL")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID")
        self.secret_key = secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY")
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")

    def _host(self) -> str:
        if self.endpoint:
            return urllib.parse.urlparse(
                self.endpoint if "://" in self.endpoint
                else "http://" + self.endpoint
            ).netloc
        return f"s3.{self.region}.amazonaws.com"

    def _conn(self):
        return _http(self.endpoint, f"s3.{self.region}.amazonaws.com")

    def _sign(
        self, method: str, path: str, query: str, payload_hash: str
    ) -> dict:
        """AWS Signature Version 4 (headers-only, single-chunk)."""
        if not self.access_key or not self.secret_key:
            return {}  # unsigned: fakes/public buckets
        return sigv4_sign(
            method, path, query,
            {"x-amz-content-sha256": payload_hash},
            payload_hash,
            service="s3", region=self.region, host=self._host(),
            access_key=self.access_key, secret_key=self.secret_key,
        )

    EMPTY_SHA = hashlib.sha256(b"").hexdigest()

    def list(self, bucket: str, prefix: str) -> list[dict]:
        items, token = [], None
        while True:
            q = {"list-type": "2", "prefix": prefix, "max-keys": "1000"}
            if token:
                q["continuation-token"] = token
            # SigV4 canonicalizes with %20, not '+': quote, not quote_plus.
            query = urllib.parse.urlencode(
                sorted(q.items()), quote_via=urllib.parse.quote
            )
            path = f"/{bucket}"

            def attempt():
                conn = self._conn()
                try:
                    headers = self._sign("GET", path, query, self.EMPTY_SHA)
                    conn.request("GET", f"{path}?{query}", headers=headers)
                    resp = conn.getresponse()
                    body = resp.read()
                    if resp.status >= 400:
                        raise _status_error(
                            f"s3 list {bucket}/{prefix}",
                            resp.status, repr(body[:200]),
                        )
                    return body
                finally:
                    conn.close()

            body = with_retries(f"list s3://{bucket}/{prefix}", attempt)
            ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
            root = ET.fromstring(body)
            # Tolerate namespaced and namespace-less XML (fakes).
            def findall(tag):
                return root.findall(f"s3:{tag}", ns) or root.findall(tag)

            for c in findall("Contents"):
                key = c.find("s3:Key", ns)
                key = key if key is not None else c.find("Key")
                size = c.find("s3:Size", ns)
                size = size if size is not None else c.find("Size")
                items.append(
                    {"name": key.text, "size": int(size.text if size is not None else 0)}
                )
            trunc = findall("IsTruncated")
            token_el = findall("NextContinuationToken")
            if trunc and trunc[0].text == "true" and token_el:
                token = token_el[0].text
            else:
                return items

    def get_to_file(self, bucket: str, name: str, dest_path: str) -> None:
        _ranged_get_to_file(
            lambda start: self._open_stream(bucket, name, start),
            f"s3://{bucket}/{name}", dest_path,
        )

    def get_range(self, bucket: str, name: str, start: int, end: int) -> bytes:
        """Inclusive byte range [start, end] of one object."""
        def attempt():
            resp, conn = self._open_stream(bucket, name, start, end)
            try:
                return _read_exact(
                    resp, end - start + 1, f"s3://{bucket}/{name}"
                )
            finally:
                conn.close()

        return with_retries(
            f"get s3://{bucket}/{name}[{start}-{end}]", attempt
        )

    def _open_stream(
        self, bucket: str, name: str, start: int = 0, end: int | None = None
    ):
        """(response, connection) streaming the object from `start` (to
        `end` inclusive when given). Range is an unsigned header — SigV4
        only commits to (host, x-amz-date, x-amz-content-sha256) here."""
        path = f"/{bucket}/{urllib.parse.quote(name)}"
        headers = dict(self._sign("GET", path, "", self.EMPTY_SHA))
        if start > 0 or end is not None:
            headers["Range"] = (
                f"bytes={start}-" if end is None else f"bytes={start}-{end}"
            )
        conn = self._conn()
        try:
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
        except BaseException:
            conn.close()
            raise
        if resp.status >= 400:
            conn.close()
            raise _status_error(f"s3 get {bucket}/{name}", resp.status)
        if (start > 0 or end is not None) and resp.status != 206:
            conn.close()
            raise _RangeIgnored(
                f"s3 get {bucket}/{name}: server ignored Range "
                f"(status {resp.status})"
            )
        return resp, conn

    def put_from_file(self, bucket: str, name: str, src_path: str) -> None:
        path = f"/{bucket}/{urllib.parse.quote(name)}"

        # Sign with UNSIGNED-PAYLOAD so the file streams without a
        # whole-file hash pass into memory.
        def attempt():
            conn = self._conn()
            try:
                with open(src_path, "rb") as f:
                    headers = {
                        "Content-Length": str(os.path.getsize(src_path)),
                    }
                    headers.update(
                        self._sign("PUT", path, "", "UNSIGNED-PAYLOAD")
                    )
                    conn.request("PUT", path, body=f, headers=headers)
                    resp = conn.getresponse()
                    resp.read()
                    if resp.status >= 400:
                        raise _status_error(
                            f"s3 put {bucket}/{name}", resp.status
                        )
            finally:
                conn.close()

        with_retries(f"put s3://{bucket}/{name}", attempt)


class LocalDirClient:
    """A plain directory behind the object-store client interface
    (file:// URLs): PVC-mounted snapshot volumes, tests, and bench runs
    with no bucket in reach. `parse_url("file:///var/snap")` yields
    bucket "" and prefix "var/snap", so names resolve from `root`
    (the filesystem root by default)."""

    def __init__(self, root: str = "/"):
        self.root = root

    def _path(self, bucket: str, name: str) -> str:
        parts = [p for p in (bucket, name) if p]
        return os.path.join(self.root, *parts) if parts else self.root

    def list(self, bucket: str, prefix: str) -> list[dict]:
        """String-prefix semantics like the real stores: a prefix naming
        a directory lists its whole tree; one naming a file lists it."""
        base = self._path(bucket, prefix)
        items = []
        if os.path.isfile(base):
            items.append({"name": prefix, "size": os.path.getsize(base)})
        if os.path.isdir(base):
            for root, _dirs, files in os.walk(base):
                for fname in files:
                    full = os.path.join(root, fname)
                    rel = os.path.relpath(full, base)
                    name = f"{prefix.rstrip('/')}/{rel}" if prefix else rel
                    items.append(
                        {"name": name, "size": os.path.getsize(full)}
                    )
        return sorted(items, key=lambda o: o["name"])

    def get_to_file(self, bucket: str, name: str, dest_path: str) -> None:
        src = self._path(bucket, name)
        if not os.path.isfile(src):
            raise ObjStoreError(f"file get {src}: not found")
        os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
        with open(src, "rb") as s, open(dest_path, "wb") as d:
            while True:
                chunk = s.read(CHUNK)
                if not chunk:
                    break
                d.write(chunk)

    def get_range(self, bucket: str, name: str, start: int, end: int) -> bytes:
        src = self._path(bucket, name)
        if not os.path.isfile(src):
            raise ObjStoreError(f"file get {src}: not found")
        with open(src, "rb") as f:
            f.seek(start)
            return f.read(end - start + 1)

    def put_from_file(self, bucket: str, name: str, src_path: str) -> None:
        dest = self._path(bucket, name)
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        tmp = dest + ".inflight"
        with open(src_path, "rb") as s, open(tmp, "wb") as d:
            while True:
                chunk = s.read(CHUNK)
                if not chunk:
                    break
                d.write(chunk)
        os.replace(tmp, dest)  # objects appear atomically, like a store


def download_prefix(url: str, dest_dir: str, client=None) -> list[str]:
    """Download every object under `url` into dest_dir (relative names),
    one object at a time, chunked to disk. Returns the local paths."""
    scheme, bucket, prefix = parse_url(url)
    client = client or client_for(url)
    objects = client.list(bucket, prefix)
    # Store listing is plain string-prefix matching: 'models/llama' also
    # matches 'models/llama-70b/...'. Keep only the directory itself.
    if prefix and not prefix.endswith("/"):
        objects = [
            o for o in objects
            if o["name"] == prefix or o["name"].startswith(prefix + "/")
        ]
    if not objects:
        raise ObjStoreError(f"no objects under {url}")
    out = []
    for obj in objects:
        rel = obj["name"][len(prefix):].lstrip("/") if prefix else obj["name"]
        if not rel:  # the prefix itself as an object
            rel = os.path.basename(obj["name"])
        dest = os.path.join(dest_dir, rel)
        logger.info("downloading %s/%s (%d bytes)", bucket, obj["name"], obj["size"])
        client.get_to_file(bucket, obj["name"], dest)
        out.append(dest)
    return out


def upload_dir(src_dir: str, url: str, client=None) -> list[str]:
    """Upload a directory tree under the destination prefix."""
    scheme, bucket, prefix = parse_url(url)
    client = client or client_for(url)
    uploaded = []
    for root, _, files in os.walk(src_dir):
        for fname in files:
            full = os.path.join(root, fname)
            rel = os.path.relpath(full, src_dir)
            key = f"{prefix.rstrip('/')}/{rel}" if prefix else rel
            logger.info("uploading %s -> %s/%s", rel, bucket, key)
            client.put_from_file(bucket, key, full)
            uploaded.append(key)
    return uploaded


def fetch_object_parallel(
    client,
    bucket: str,
    name: str,
    size: int,
    dest_path: str,
    *,
    part_bytes: int = 8 << 20,
    max_workers: int = 8,
) -> None:
    """Chunk-parallel ranged download of ONE object: the dest file is
    preallocated, then worker threads each GET an independent byte range
    (individually retried; fresh connection per request) and pwrite it
    into place. Small objects and clients without `get_range` fall back
    to the sequential streaming path."""
    if size <= part_bytes or not hasattr(client, "get_range"):
        client.get_to_file(bucket, name, dest_path)
        return
    os.makedirs(os.path.dirname(dest_path) or ".", exist_ok=True)
    with open(dest_path, "wb") as f:
        f.truncate(size)
    ranges = [
        (s, min(s + part_bytes, size) - 1) for s in range(0, size, part_bytes)
    ]
    import concurrent.futures

    fd = os.open(dest_path, os.O_WRONLY)
    try:
        def fetch(rng):
            start, end = rng
            os.pwrite(fd, client.get_range(bucket, name, start, end), start)

        with concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers
        ) as ex:
            # list() re-raises the first worker failure
            list(ex.map(fetch, ranges))
    finally:
        os.close(fd)


SNAPSHOT_VERSION = 1
SNAPSHOT_MANIFEST = "MANIFEST.json"


class SnapshotStore:
    """Engine boot snapshots: the post-conversion param tree (orbax
    checkpoint layout) plus the JAX persistent compilation cache, so a
    replica's birth costs a streamed restore instead of HF-weight
    conversion + XLA recompilation.

    Layout under `<url>/<model>/<fingerprint>/`:

      params/...      orbax checkpoint tree (one object per array file)
      xla_cache/...   JAX compilation-cache entries (may be empty on
                      platforms without persistent-cache support)
      MANIFEST.json   uploaded LAST — its presence marks the snapshot
                      complete. A crashed publisher leaves no manifest,
                      so a partial tree is never restored; the next full
                      boot simply overwrites it.

    The fingerprint folds in everything that changes the on-device
    layout or the compiled program (model id, engine config, mesh
    shape, snapshot schema version). `fetch` re-validates the manifest
    against the expected fingerprint and raises `SnapshotMismatch` on
    drift: a stale layout must NEVER be served — callers fall back to
    the full-load path and republish, self-healing the key."""

    def __init__(self, url: str, client=None):
        self.url = url.rstrip("/")
        self.client = client or client_for(self.url)
        _scheme, self.bucket, self.base_prefix = parse_url(self.url)

    @staticmethod
    def fingerprint(
        model: str,
        engine_config: dict,
        mesh_shape,
        version: int = SNAPSHOT_VERSION,
    ) -> str:
        blob = json.dumps(
            {
                "model": model,
                "engine_config": engine_config,
                "mesh_shape": list(mesh_shape),
                "snapshot_version": version,
            },
            sort_keys=True,
            default=str,
        )
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def _prefix(self, model: str, fingerprint: str) -> str:
        parts = [self.base_prefix, model.replace("/", "--"), fingerprint]
        return "/".join(p for p in parts if p)

    def manifest(self, model: str, fingerprint: str) -> dict | None:
        """The manifest iff a COMPLETE snapshot exists at this key.
        Store trouble (including exhausted retries) reads as absent:
        boot falls back to the full-load path rather than crash-looping
        on an unreachable bucket."""
        import tempfile

        key = f"{self._prefix(model, fingerprint)}/{SNAPSHOT_MANIFEST}"
        tmp = tempfile.mktemp()
        try:
            self.client.get_to_file(self.bucket, key, tmp)
            with open(tmp) as f:
                return json.load(f)
        except (ObjStoreError, json.JSONDecodeError):
            return None
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def fetch(
        self,
        model: str,
        fingerprint: str,
        dest_dir: str,
        *,
        max_workers: int = 8,
    ) -> dict | None:
        """Download the snapshot tree into dest_dir (params/ +
        xla_cache/), chunk-parallel per object. Returns the manifest,
        None when absent, or raises `SnapshotMismatch` when the manifest
        disagrees with the expected fingerprint."""
        man = self.manifest(model, fingerprint)
        if man is None:
            return None
        if man.get("fingerprint") != fingerprint:
            raise SnapshotMismatch(
                f"snapshot at {self._prefix(model, fingerprint)} carries "
                f"fingerprint {man.get('fingerprint')!r}, expected "
                f"{fingerprint!r} — falling back to full load"
            )
        prefix = self._prefix(model, fingerprint)
        for obj in self.client.list(self.bucket, prefix + "/"):
            rel = obj["name"][len(prefix):].lstrip("/")
            if not rel or rel == SNAPSHOT_MANIFEST:
                continue
            fetch_object_parallel(
                self.client,
                self.bucket,
                obj["name"],
                obj["size"],
                os.path.join(dest_dir, rel),
                max_workers=max_workers,
            )
        return man

    def publish(
        self, model: str, fingerprint: str, src_dir: str, *, meta: dict | None = None
    ) -> dict:
        """Upload a snapshot directory; MANIFEST.json goes LAST so a
        half-uploaded tree is never mistaken for a complete snapshot.
        Republishing over an existing key overwrites it (self-heal)."""
        import tempfile

        prefix = self._prefix(model, fingerprint)
        uploaded = []
        for root, _dirs, files in os.walk(src_dir):
            for fname in sorted(files):
                if fname == SNAPSHOT_MANIFEST:
                    continue
                full = os.path.join(root, fname)
                rel = os.path.relpath(full, src_dir)
                self.client.put_from_file(self.bucket, f"{prefix}/{rel}", full)
                uploaded.append(rel)
        man = {
            "fingerprint": fingerprint,
            "model": model,
            "snapshot_version": SNAPSHOT_VERSION,
            "objects": sorted(uploaded),
            **(meta or {}),
        }
        with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False
        ) as f:
            json.dump(man, f)
            tmp = f.name
        try:
            self.client.put_from_file(
                self.bucket, f"{prefix}/{SNAPSHOT_MANIFEST}", tmp
            )
        finally:
            os.unlink(tmp)
        return man


class KVSpillStore:
    """Spill/fill store for evicted hot-prefix KV pages (the objstore leg
    of the cluster KV-sharing tier). Each entry is one serialized
    single-page `KVPageExport` blob keyed by its chain hash (hex), so a
    fill is a plain GET and needs no index.

    Two backends behind one interface:
      - in-memory LRU (url=""): the default and the test surface — spill
        stays a node-local optimization with a hard byte cap;
      - object store (gs://, s3://, oss://): pages persist as
        `<prefix>/<hash>.kvp` objects via the zero-dependency clients
        above, shared fleet-wide.

    Every method is best-effort by contract: the callers (eviction hook,
    fetch fallback) treat any failure as a miss and recompute.
    """

    def __init__(self, url: str = "", max_bytes: int = 256 << 20):
        from collections import OrderedDict

        self.url = url
        self.max_bytes = max_bytes
        self._mem: "OrderedDict[str, bytes]" = OrderedDict()
        self._mem_bytes = 0
        self.puts = 0
        self.gets = 0
        self.hits = 0

    def _key(self, hash_hex: str) -> tuple[str, str]:
        _scheme, bucket, prefix = parse_url(self.url)
        name = f"{hash_hex}.kvp"
        return bucket, f"{prefix.rstrip('/')}/{name}" if prefix else name

    def put(self, hash_hex: str, blob: bytes) -> None:
        self.puts += 1
        if not self.url:
            if len(blob) > self.max_bytes:
                return
            old = self._mem.pop(hash_hex, None)
            if old is not None:
                self._mem_bytes -= len(old)
            self._mem[hash_hex] = blob
            self._mem_bytes += len(blob)
            while self._mem_bytes > self.max_bytes and self._mem:
                _h, dropped = self._mem.popitem(last=False)
                self._mem_bytes -= len(dropped)
            return
        import tempfile

        bucket, key = self._key(hash_hex)
        client = client_for(self.url)
        with tempfile.NamedTemporaryFile(delete=False) as f:
            f.write(blob)
            tmp = f.name
        try:
            client.put_from_file(bucket, key, tmp)
        finally:
            os.unlink(tmp)

    def get(self, hash_hex: str) -> bytes | None:
        self.gets += 1
        if not self.url:
            blob = self._mem.get(hash_hex)
            if blob is not None:
                self._mem.move_to_end(hash_hex)
                self.hits += 1
            return blob
        import tempfile

        bucket, key = self._key(hash_hex)
        client = client_for(self.url)
        tmp = tempfile.mktemp()
        try:
            client.get_to_file(bucket, key, tmp)
            with open(tmp, "rb") as f:
                blob = f.read()
            self.hits += 1
            return blob
        except Exception:
            return None
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
