"""Shared utilities: Kubernetes quantity/duration parsing, misc helpers."""

from kubeai_tpu.utils.units import (
    parse_duration_seconds,
    parse_quantity,
    multiply_quantity,
    format_quantity,
)
