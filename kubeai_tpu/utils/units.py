"""Kubernetes-style quantities and Go-style durations.

Shared by the config loader (interval/timeWindow durations —
reference: internal/config/system.go duration fields) and the engine
renderers (resource profile multiplication —
reference: internal/modelcontroller/model_controller.go:274-306).
"""

from __future__ import annotations

import re

_DURATION_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration_seconds(v) -> float:
    """'10s' / '3m' / '250ms' / bare numbers -> seconds."""
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip()
    for suffix in ("ms", "s", "m", "h"):
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * _DURATION_UNITS[suffix]
    return float(s)


_QTY_RE = re.compile(r"^([0-9.]+)([a-zA-Z]*)$")

# Binary and decimal suffix multipliers (memory quantities).
_QTY_SUFFIX = {
    "": 1,
    "m": 1e-3,  # milli (cpu)
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40,
}


def parse_quantity(q) -> float:
    """'4' / '500m' / '2Gi' -> float in base units."""
    m = _QTY_RE.match(str(q).strip())
    if not m:
        raise ValueError(f"bad quantity {q!r}")
    num, unit = m.groups()
    if unit not in _QTY_SUFFIX:
        raise ValueError(f"unknown quantity suffix {unit!r} in {q!r}")
    return float(num) * _QTY_SUFFIX[unit]


def format_quantity(value: float, unit: str) -> str:
    if value == int(value):
        return f"{int(value)}{unit}"
    return f"{value}{unit}"


def multiply_quantity(q, n: int) -> str:
    """Multiply a quantity string by n, preserving its suffix."""
    m = _QTY_RE.match(str(q).strip())
    if not m:
        raise ValueError(f"bad quantity {q!r}")
    num, unit = m.groups()
    return format_quantity(float(num) * n, unit)
