"""Computed, jittered ``Retry-After`` values — one helper for every
shed path.

Three layers answer 429/503 with a retry hint: the engine scheduler's
admission shed, the engine's drain refusal, and the front door's tenant
admission layer (kubeai_tpu/fleet/tenancy). All of them must obey the
same contract:

  * the hint is COMPUTED from measured state (queue drain estimate,
    remaining drain budget, bucket refill time, window reset) — never a
    magic constant;
  * it is clamped into a useful band: not 0 (a zero tells clients to
    hammer), not unbounded (an hour-long window reset should not tell a
    client to vanish for an hour — by then capacity has moved);
  * it is jittered with the proxy's factor (``base * (0.5 + 0.5*r)``,
    kubeai_tpu/routing/proxy.py) so a shed burst does not resynchronize
    into a retry burst.

``_jitter`` is module-level and monkeypatchable, the same seam the
proxy exposes — tests pin it to make every hint deterministic.
"""

from __future__ import annotations

import math
import random

MIN_RETRY_AFTER_S = 0.25
MAX_RETRY_AFTER_S = 300.0

# Jitter source (monkeypatchable in tests, like routing.proxy._jitter).
_jitter = random.random


def clamp(seconds, min_s: float = MIN_RETRY_AFTER_S,
          max_s: float = MAX_RETRY_AFTER_S) -> float:
    """Sanitize a computed wait estimate into the useful band. Garbage
    in (None, NaN, inf, negative, zero, non-numeric) floors to
    ``min_s`` — a broken estimate must degrade to "retry soon", never
    to "retry never" or "retry now"."""
    try:
        s = float(seconds)
    except (TypeError, ValueError):
        s = min_s
    if not math.isfinite(s) or s <= 0.0:
        s = min_s
    return min(max(s, min_s), max_s)


def jittered(seconds, min_s: float = MIN_RETRY_AFTER_S,
             max_s: float = MAX_RETRY_AFTER_S) -> float:
    """Clamp, then apply the proxy's jitter factor. The result stays
    within [min_s, max_s]: jitter spreads retries, it must not push the
    hint below the floor the clamp just enforced."""
    base = clamp(seconds, min_s=min_s, max_s=max_s)
    return min(max(base * (0.5 + 0.5 * _jitter()), min_s), max_s)


def format_header(seconds) -> str:
    """Render a wait as a ``Retry-After`` header value (fractional
    seconds; RFC 7231 specifies delta-seconds and every client we front
    parses floats)."""
    try:
        s = float(seconds)
    except (TypeError, ValueError):
        s = MIN_RETRY_AFTER_S
    if not math.isfinite(s) or s < 0.0:
        s = MIN_RETRY_AFTER_S
    return f"{s:.3f}"


def parse_header(value) -> float | None:
    """Parse a ``Retry-After`` header value. RFC 7231 also allows
    HTTP-dates; those (and any other non-numeric or negative value)
    return None — the caller falls back to its own backoff rather than
    sleeping until 2015."""
    if value is None:
        return None
    try:
        s = float(str(value).strip())
    except ValueError:
        return None
    if not math.isfinite(s) or s < 0.0:
        return None
    return s
