"""RFC-6902 JSON patches applied to every rendered Pod — the escape hatch
for cluster-specific pod tweaks (reference: internal/modelcontroller/patch.go:13-44,
config hook internal/config/system.go:237-241).
"""

from __future__ import annotations

import copy
from typing import Any


class PatchError(ValueError):
    pass


def apply_json_patches(patches: list[dict], obj: dict) -> dict:
    """Apply a list of RFC-6902 operations to obj (returns a new dict)."""
    out = copy.deepcopy(obj)
    for op in patches:
        _apply_one(op, out)
    return out


def _parse_path(path: str) -> list[str]:
    if path == "":
        return []
    if not path.startswith("/"):
        raise PatchError(f"path must start with '/': {path!r}")
    return [p.replace("~1", "/").replace("~0", "~") for p in path[1:].split("/")]


def _walk(obj: Any, tokens: list[str]):
    """Return the container holding the final token."""
    for t in tokens[:-1]:
        if isinstance(obj, list):
            obj = obj[int(t)]
        elif isinstance(obj, dict):
            if t not in obj:
                raise PatchError(f"path segment {t!r} not found")
            obj = obj[t]
        else:
            raise PatchError(f"cannot traverse {type(obj)} at {t!r}")
    return obj


def _apply_one(op: dict, obj: dict) -> None:
    kind = op.get("op")
    tokens = _parse_path(op.get("path", ""))
    if not tokens:
        raise PatchError("empty path not supported")
    parent = _walk(obj, tokens)
    last = tokens[-1]

    def resolve(container, token):
        if isinstance(container, list):
            idx = len(container) if token == "-" else int(token)
            return idx
        return token

    if kind == "add":
        t = resolve(parent, last)
        if isinstance(parent, list):
            parent.insert(t, copy.deepcopy(op["value"]))
        else:
            parent[t] = copy.deepcopy(op["value"])
    elif kind == "replace":
        t = resolve(parent, last)
        if isinstance(parent, list):
            parent[t] = copy.deepcopy(op["value"])
        else:
            if t not in parent:
                raise PatchError(f"replace target {t!r} missing")
            parent[t] = copy.deepcopy(op["value"])
    elif kind == "remove":
        t = resolve(parent, last)
        if isinstance(parent, list):
            del parent[t]
        else:
            if t not in parent:
                raise PatchError(f"remove target {t!r} missing")
            del parent[t]
    elif kind == "copy":
        src = _parse_path(op["from"])
        src_parent = _walk(obj, src)
        val = (
            src_parent[int(src[-1])]
            if isinstance(src_parent, list)
            else src_parent[src[-1]]
        )
        _apply_one({"op": "add", "path": op["path"], "value": val}, obj)
    elif kind == "move":
        src = _parse_path(op["from"])
        src_parent = _walk(obj, src)
        if isinstance(src_parent, list):
            val = src_parent.pop(int(src[-1]))
        else:
            val = src_parent.pop(src[-1])
        _apply_one({"op": "add", "path": op["path"], "value": val}, obj)
    elif kind == "test":
        t = resolve(parent, last)
        cur = parent[t] if not isinstance(parent, list) else parent[t]
        if cur != op.get("value"):
            raise PatchError(f"test failed at {op['path']}")
    else:
        raise PatchError(f"unknown op {kind!r}")
