"""Engine admin client: LoRA adapter load/unload over the engine's HTTP
admin API.

Generalizes the reference's vLLM-only client
(reference: internal/vllmclient/client.go:30-73) into the seam SURVEY.md §2
calls out: the same `/v1/load_lora_adapter` + `/v1/unload_lora_adapter`
contract is spoken by vLLM AND by the in-tree TPU engine
(kubeai_tpu.engine.server), so one client covers both. Error handling is
idempotency-tolerant: "already loaded" / "not found" are success when the
caller says so.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request


class EngineClientError(RuntimeError):
    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status  # HTTP status, 0 for transport errors


class EngineClient:
    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def _post(self, url: str, body: dict) -> tuple[int, str]:
        req = urllib.request.Request(
            url,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read().decode(errors="replace")
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode(errors="replace")
        except OSError as e:
            raise EngineClientError(f"POST {url}: {e}") from e

    def list_lora_adapters(self, addr: str, served_model_name: str) -> list[str]:
        """Adapters the engine actually has loaded (GET /v1/models minus
        the base model id). Lets the reconciler unload adapters whose Pod
        label is already gone — labels are removed BEFORE unload so the
        LB drains traffic first, and a 409-refused unload must still be
        retried from engine state, not label state."""
        req = urllib.request.Request(f"{addr}/v1/models")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = json.loads(resp.read().decode(errors="replace"))
        except (OSError, ValueError) as e:
            raise EngineClientError(f"GET {addr}/v1/models: {e}") from e
        return [
            m["id"] for m in body.get("data", [])
            if m.get("id") and m["id"] != served_model_name
        ]

    def load_lora_adapter(
        self,
        addr: str,
        lora_name: str,
        lora_path: str = "",
        lora_url: str = "",
        ignore_already_loaded: bool = False,
    ) -> None:
        body: dict = {"lora_name": lora_name}
        if lora_path:
            body["lora_path"] = lora_path
        if lora_url:
            body["lora_url"] = lora_url
        status, text = self._post(f"{addr}/v1/load_lora_adapter", body)
        if status == 200:
            return
        if ignore_already_loaded and "already" in text.lower():
            return
        raise EngineClientError(
            f"load adapter {lora_name} at {addr}: HTTP {status}: {text[:200]}",
            status=status,
        )

    def unload_lora_adapter(
        self, addr: str, lora_name: str, ignore_not_found: bool = False
    ) -> None:
        status, text = self._post(
            f"{addr}/v1/unload_lora_adapter", {"lora_name": lora_name}
        )
        if status == 200:
            return
        if ignore_not_found and status == 404:
            return
        if ignore_not_found and "not" in text.lower() and "found" in text.lower():
            return
        raise EngineClientError(
            f"unload adapter {lora_name} at {addr}: HTTP {status}: {text[:200]}",
            status=status,
        )
