"""Composition root: builds and runs the whole operator
(reference: internal/manager/run.go:76-399).

Wires: load balancer (Pod watcher) → Model reconciler loop → model client →
autoscaler (leader-gated) → OpenAI API server → messengers. The same
assembly runs in production and inside integration tests (the reference
starts the entire real manager in envtest — reference:
test/integration/main_test.go:132-157; here tests call Manager.start()
against a KubeStore).
"""

from __future__ import annotations

import dataclasses
import socket
import uuid

from kubeai_tpu.autoscaler import Autoscaler, LeaderElection
from kubeai_tpu.config import System
from kubeai_tpu.metrics import Metrics
from kubeai_tpu.crd.model import Model, ValidationError
from kubeai_tpu.operator.adapters import PodExec
from kubeai_tpu.operator.controller import ControllerLoop, ModelReconciler
from kubeai_tpu.operator.engine_client import EngineClient
from kubeai_tpu.operator.k8s.store import Invalid, KubeStore
from kubeai_tpu.routing.loadbalancer import LoadBalancer
from kubeai_tpu.routing.messenger import Broker, MemBroker, Messenger
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.routing.openai_server import OpenAIServer
from kubeai_tpu.routing.proxy import ModelProxy


def _model_admission(new: dict, old: dict | None) -> None:
    """CRD validation at the store boundary — admission-webhook parity."""
    try:
        model = Model.from_dict(new)
        if old is not None:
            model.validate_update(Model.from_dict(old))
        else:
            model.validate()
    except ValidationError as e:
        raise Invalid(str(e))


@dataclasses.dataclass
class Manager:
    store: KubeStore
    cfg: System
    api_host: str = "127.0.0.1"
    api_port: int = 0
    namespace: str = "default"
    identity: str = ""
    broker: Broker | None = None
    engine_client: EngineClient | None = None
    pod_exec: PodExec | None = None

    def __post_init__(self):
        self.cfg.default_and_validate()
        self.identity = self.identity or f"{socket.gethostname()}-{uuid.uuid4().hex[:6]}"
        self.store.register_validator("Model", _model_admission)

        # Per-replica instrument bundle: embedded multi-replica setups must
        # not share counters (the leader scrapes every replica and sums).
        self.metrics = Metrics()
        from kubeai_tpu.routing.health import BreakerPolicy

        res = self.cfg.resilience
        default_breaker = BreakerPolicy(
            window=res.breaker_window,
            consecutive_failures=res.breaker_consecutive_failures,
            failure_rate=res.breaker_failure_rate,
            min_samples=res.breaker_min_samples,
            open_seconds=res.breaker_open_seconds,
        )
        self.lb = LoadBalancer(
            self.store, metrics=self.metrics,
            default_breaker=default_breaker,
        )
        self.model_client = ModelClient(self.store, self.namespace)
        self.reconciler = ModelReconciler(
            self.store,
            self.cfg,
            engine_client=self.engine_client,
            pod_exec=self.pod_exec,
            metrics=self.metrics,
        )
        self.controller_loop = ControllerLoop(self.reconciler)
        self.leader = LeaderElection(
            self.store,
            self.identity,
            namespace=self.namespace,
            lease_duration=self.cfg.leader_election.lease_duration_seconds,
            retry_period=self.cfg.leader_election.retry_period_seconds,
            renew_deadline=self.cfg.leader_election.renew_deadline_seconds,
            metrics=self.metrics,
        )
        # Leadership acquisition resyncs the controller: reconciles that
        # were fenced while standby converge immediately, not at the
        # next watch event.
        self.leader.add_listener(
            lambda is_leader: self.controller_loop.resync()
            if is_leader else None
        )
        self.autoscaler = Autoscaler(
            self.store,
            self.cfg,
            self.model_client,
            self.lb,
            self.leader,
            namespace=self.namespace,
            metrics=self.metrics,
        )
        from kubeai_tpu.routing.proxy import ProxyTimeouts

        self.proxy = ModelProxy(
            self.lb, self.model_client, metrics=self.metrics,
            timeouts=ProxyTimeouts(
                connect_s=res.connect_timeout_seconds,
                response_header_s=res.response_header_timeout_seconds,
            ),
        )
        # Fleet telemetry plane (kubeai_tpu/fleet): tenant usage ledger +
        # background fleet-state aggregator. The autoscaler's per-model
        # engine reads go through the aggregator's snapshot (stale →
        # direct-scrape fallback), the front door serves /v1/fleet/* and
        # /v1/usage from them.
        from kubeai_tpu.fleet import (
            CapacityPlanner,
            DemandForecaster,
            FleetStateAggregator,
            UsageMeter,
            build_door,
        )

        self.usage = UsageMeter(
            metrics=self.metrics,
            max_tenant_series=self.cfg.tenancy.max_tenant_series,
        )
        self.fleet = FleetStateAggregator(
            lb=self.lb,
            model_client=self.model_client,
            store=self.store,
            namespace=self.namespace,
            metrics=self.metrics,
            usage=self.usage,
            interval_s=self.cfg.model_autoscaling.interval_seconds / 2.0,
            # Validated cluster identity (config `cluster:` block):
            # every snapshot is stamped so federation peers can join
            # views without guessing who they came from.
            cluster=self.cfg.cluster.name,
        )
        self.autoscaler.fleet = self.fleet
        # Actuation safety governor (kubeai_tpu/operator/governor):
        # every destructive action — pod deletion in the reconciler,
        # scale-down writes, planner preemption marks — flows through
        # it: disruption budgets, telemetry-coverage gates with static
        # stability, and leadership-lease fencing.
        from kubeai_tpu.operator.governor import ActuationGovernor

        self.governor = ActuationGovernor(
            cfg=self.cfg.governor if self.cfg.governor.enabled else None,
            fleet=self.fleet,
            leader=self.leader,
            store=self.store,
            namespace=self.namespace,
            metrics=self.metrics,
        )
        self.reconciler.governor = self.governor
        self.model_client.governor = self.governor
        # Progressive-delivery controller (kubeai_tpu/operator/rollout):
        # models with a `rollout:` block get judged canary→ramp spec
        # changes with automatic rollback; everyone else keeps the
        # classic surge plan untouched. Reads the aggregator's
        # per-version split, weights the LB's canary share, and feeds
        # the reconciler its pod caps.
        from kubeai_tpu.operator.rollout import RolloutController

        self.rollout = RolloutController(
            store=self.store,
            lb=self.lb,
            fleet=self.fleet,
            governor=self.governor,
            namespace=self.namespace,
            metrics=self.metrics,
            interval_s=self.cfg.model_autoscaling.interval_seconds / 2.0,
            enqueue=self.controller_loop.enqueue,
        )
        self.reconciler.rollout = self.rollout
        # Cluster-wide capacity planner (kubeai_tpu/fleet/planner):
        # bin-packs every model's desire onto the chip budget each tick;
        # the autoscaler applies its allocations (stale plan → direct
        # scaling), the front door serves it at /v1/fleet/plan.
        self.planner = None
        if self.cfg.capacity_planning.enabled:
            self.planner = CapacityPlanner(
                fleet=self.fleet,
                model_client=self.model_client,
                store=self.store,
                cfg=self.cfg,
                namespace=self.namespace,
                metrics=self.metrics,
                leader=self.leader,
                interval_s=(
                    self.cfg.capacity_planning.interval_seconds
                    or self.cfg.model_autoscaling.interval_seconds
                ),
                preemption_enabled=self.cfg.capacity_planning.preemption,
                governor=self.governor,
                # Predictive prewarm + cold-start-priced preemption:
                # the forecaster reads the aggregator's snapshot ring,
                # the planner orders warm replicas ahead of forecast
                # spikes (docs/concepts/cold-start.md).
                forecaster=DemandForecaster(self.fleet),
            )
            # Plan desires smooth over the SAME moving average the
            # direct scaling path uses — abundant chips must mean the
            # plan is a no-op, not a subtly different controller.
            self.planner.avg_lookup = self.autoscaler.current_average
            self.autoscaler.planner = self.planner
        # Front-door tenant admission (kubeai_tpu/fleet/tenancy): only
        # constructed when tenancy is enabled — disabled (the default)
        # leaves the serving path identical to a build without it.
        # `doorShards > 1` builds N in-process door shards sharing a
        # gossiped CRDT state plane behind a round-robin shard picker
        # (fleet/tenancy.ShardedDoor); the routing tier then reads
        # breaker verdicts and prefix holdings from the same plane.
        self.tenancy = None
        if self.cfg.tenancy.enabled:
            self.tenancy = build_door(
                self.cfg.tenancy,
                usage=self.usage,
                fleet=self.fleet,
                model_client=self.model_client,
                metrics=self.metrics,
            )
            shard_set = getattr(self.tenancy, "shard_set", None)
            if shard_set is not None:
                self.lb.set_gossip(shard_set.node(shard_set.names()[0]))
        # SLO plane (kubeai_tpu/fleet/slo) + always-on flight recorder
        # (kubeai_tpu/metrics/flightrecorder): only constructed when
        # `slo.enabled` — disabled leaves every subsystem's `recorder`
        # attribute None and the hot paths untouched.
        self.slo = None
        self.recorder = None
        if self.cfg.slo.enabled:
            from kubeai_tpu.fleet.slo import SLOEvaluator
            from kubeai_tpu.metrics.flightrecorder import FlightRecorder

            self.recorder = FlightRecorder(
                sink_dir=self.cfg.slo.incident_dir or None,
                min_trigger_interval_s=(
                    self.cfg.slo.min_incident_interval_seconds
                ),
            )
            self.slo = SLOEvaluator(
                cfg=self.cfg.slo,
                aggregator=self.fleet,
                model_client=self.model_client,
                metrics=self.metrics,
                recorder=self.recorder,
                min_telemetry_coverage=(
                    self.cfg.governor.min_telemetry_coverage
                    if self.cfg.governor.enabled else 0.0
                ),
                interval_s=self.cfg.model_autoscaling.interval_seconds,
            )
            # Burn-rate state biases both control loops; decision rings
            # land in every subsystem that makes discrete refusals.
            self.autoscaler.slo = self.slo
            self.governor.recorder = self.recorder
            self.rollout.recorder = self.recorder
            self.lb.set_recorder(self.recorder)
            if self.planner is not None:
                self.planner.slo = self.slo
                self.planner.recorder = self.recorder
            if self.tenancy is not None:
                self.tenancy.recorder = self.recorder
        # Federation plane (kubeai_tpu/federation): only constructed
        # when `federation.enabled` — single-cluster builds keep the
        # serving path identical. The aggregator joins peer fleet
        # snapshots (staleness flagged per cluster), the router spills
        # chip-exhausted models' requests to the cheapest fresh peer
        # door, the planner fails whole models over when a peer stays
        # partitioned past the window (governor-gated actuation).
        self.federation = None
        self.federation_router = None
        self.federation_planner = None
        if self.cfg.federation.enabled:
            from kubeai_tpu.federation import (
                FederationAggregator,
                FederationPlanner,
                FederationRouter,
            )

            self.federation = FederationAggregator(
                self.cfg, self.fleet, metrics=self.metrics,
            )
            self.federation_router = FederationRouter(
                self.cfg,
                planner=self.planner,
                federation=self.federation,
                metrics=self.metrics,
            )
            self.federation_planner = FederationPlanner(
                self.cfg,
                federation=self.federation,
                store=self.store,
                governor=self.governor,
                metrics=self.metrics,
                namespace=self.namespace,
            )
        self.api_server = OpenAIServer(
            self.proxy,
            self.model_client,
            host=self.api_host,
            port=self.api_port,
            metrics=self.metrics,
            fleet=self.fleet,
            usage=self.usage,
            planner=self.planner,
            governor=self.tenancy,
        )
        self.api_server.slo = self.slo
        self.api_server.federation = self.federation
        self.api_server.federation_router = self.federation_router
        self.api_server.federation_planner = self.federation_planner
        self.messengers: list[Messenger] = []
        # One broker per stream, chosen by URL scheme (gcppubsub://,
        # nats://, plain names = in-memory) — the reference registers the
        # same per-scheme driver model (reference: internal/manager/
        # run.go:47-52). An injected self.broker overrides all streams
        # (test seam, like the reference's mem:// integration wiring).
        from kubeai_tpu.routing.brokers import make_broker, scheme_of

        default_broker = self.broker  # injected test seam overrides all
        self._owned_brokers: list = []
        for stream in self.cfg.messaging.streams:
            scheme = scheme_of(stream.request_subscription)
            if self.broker is not None:
                broker = self.broker
            elif scheme == "mem":
                # One shared MemBroker across mem streams, built only when
                # a stream actually uses it.
                if default_broker is None:
                    default_broker = MemBroker()
                broker = default_broker
            else:
                broker = make_broker(stream.request_subscription)
                self._owned_brokers.append(broker)
            self.messengers.append(
                Messenger(
                    broker,
                    stream.request_subscription,
                    stream.response_topic,
                    self.lb,
                    self.model_client,
                    max_handlers=stream.max_handlers,
                    error_max_backoff=self.cfg.messaging.error_max_backoff_seconds,
                    metrics=self.metrics,
                    usage=self.usage,
                    governor=self.tenancy,
                )
            )
        self.broker = default_broker

    @property
    def api_address(self) -> str:
        return self.api_server.address

    def start(self) -> None:
        # Live OTLP trace export when OTEL_EXPORTER_OTLP_ENDPOINT is set
        # (propagation-only otherwise). The reference wires the OTel SDK
        # but leaves tracing dormant (otel.go:40-47); here it's live.
        from kubeai_tpu.metrics import tracing

        tracing.configure(service_name="kubeai-tpu-operator")
        # Restart rehydration BEFORE the first tick: last-known-good
        # replica counts come back from cluster annotations so a
        # control-plane crash never causes scale thrash or duplicate
        # repairs.
        self.governor.rehydrate()
        self.lb.start()
        self.controller_loop.start()
        self.leader.start()
        self.fleet.start()
        if self.planner is not None:
            self.planner.start()
        if self.slo is not None:
            # After the aggregator (it judges from snapshots), before
            # the autoscaler (whose first tick may read its pressure).
            self.slo.start()
        # After the aggregator too: the rollout judge reads the same
        # snapshots (per-version split).
        self.rollout.start()
        self.autoscaler.start()
        self.api_server.start()
        for m in self.messengers:
            m.start()
        # Register this replica's self pod so every LB instance discovers
        # every replica's metrics address — the leader's autoscaler must sum
        # load across ALL replicas, not just itself (reference:
        # load_balancer.go:64-83 + autoscaler.go:118-130).
        if not self.cfg.fixed_self_metric_addrs:
            self._register_self_pod()

    _self_pod_name: str = ""

    def _register_self_pod(self) -> None:
        from kubeai_tpu.routing.loadbalancer import (
            SELF_METRICS_ADDR_ANNOTATION,
            SELF_POD_LABEL,
            SELF_POD_VALUE,
        )

        self._self_pod_name = f"kubeai-{self.identity}"
        # ungoverned: the operator's own bookkeeping self-pod, not
        # serving capacity (scripts/check_actuation_paths.py)
        self.store.create(
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": self._self_pod_name,
                    "namespace": self.namespace,
                    "labels": {SELF_POD_LABEL: SELF_POD_VALUE},
                    "annotations": {
                        SELF_METRICS_ADDR_ANNOTATION: self.api_server.address
                    },
                },
                "status": {
                    "conditions": [{"type": "Ready", "status": "True"}],
                    "podIP": self.api_host,
                },
            }
        )

    def stop(self) -> None:
        if self._self_pod_name:
            try:
                # ungoverned: the operator's own bookkeeping self-pod,
                # not serving capacity (scripts/check_actuation_paths.py)
                self.store.delete("Pod", self.namespace, self._self_pod_name)
            except Exception:
                pass
        for m in self.messengers:
            m.stop()
        for b in getattr(self, "_owned_brokers", []):
            try:
                b.close()  # stop pull threads / close sockets so un-acked
                # messages redeliver to surviving replicas promptly
            except Exception:
                pass
        self.api_server.stop()
        self.autoscaler.stop()
        self.rollout.stop()
        if self.slo is not None:
            self.slo.stop()
        if self.planner is not None:
            self.planner.stop()
        self.fleet.stop()
        self.leader.stop()
        self.controller_loop.stop()
        self.lb.stop()
