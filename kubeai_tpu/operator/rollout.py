"""SLO-gated progressive rollouts with automatic rollback.

A Model spec edit changes the rendered pod hash, and the classic surge
plan (operator/pod_plan) immediately starts replacing the whole fleet
with the new hash — a bad image or flag regression reaches 100% of
traffic before anything judges it. `RolloutController` turns that spec
change into a governed, judged progression for models that opt in with
a `rollout:` block:

  canary  — the pod plan may mint at most ceil(canaryPercent% × replicas)
            new-hash pods (`calculate_pod_plan(max_new=...)`); the load
            balancer enforces the same share at ROUTING time
            (`Group.set_canary`), so even a hot canary endpoint cannot
            absorb more than its allotted traffic.
  ramp    — each `stepSeconds`, if the judge passes, the cap widens by
            one canary-sized step (governor-budgeted: a step deliberately
            replaces healthy capacity).
  complete— the cap reaches replicas, the plan drains the old hash, and
            when no old-hash pod remains the controller clears the
            canary weighting and forgets the rollout.

The judge is COMPARATIVE, not absolute: each tick it reads the fleet
aggregator's per-version split (`entry["versions"]`, the fleet keyed on
the pod-hash label) and asks whether the NEW hash is burning budget the
OLD one is not — TTFT p95 ratio over the judge window, open breakers on
new-hash endpoints, or a canary that never serves at all (crashloop).
On a failing verdict with `autoRollback`, the controller pins the
last-good hash onto the Model (`kubeai.org/rollout-pinned-hash` — every
write gated by `ActuationGovernor.allow_rollback` and pinned to this
file by scripts/check_actuation_paths.py), zeroes the canary's traffic
share, fires the flight recorder's `rollout_rollback` trigger (a
replayable incident bundle), and lets the pod plan tear the condemned
hash down. Multi-host models roll in GROUP units: one slice group per
step (`calculate_group_pod_plan(max_hash_recreates=...)`), repaired
atomically; they have no per-version telemetry split (each group hashes
differently), so they pace without the comparative judge.

Docs: docs/concepts/rollouts.md. Proven end-to-end by
benchmarks/rollout_sim.py (tier-1: tests/unit/test_rollout_sim.py).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Model
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics, flightrecorder
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.k8s.store import Conflict, NotFound

logger = logging.getLogger(__name__)

# Phase vocabulary (the kubeai_rollout_phase gauge and flight events).
PHASE_IDLE = "idle"
PHASE_CANARY = "canary"
PHASE_RAMP = "ramp"
PHASE_ROLLING_BACK = "rolling_back"
_PHASE_GAUGE = {
    PHASE_IDLE: 0, PHASE_CANARY: 1, PHASE_RAMP: 2, PHASE_ROLLING_BACK: 3,
}

# Verdict vocabulary (kubeai_rollout_verdicts_total / rollback reasons).
VERDICT_PASS = "pass"
VERDICT_TTFT = "ttft_regression"
VERDICT_BREAKERS = "breaker_trips"
VERDICT_CRASHLOOP = "crashloop"

# Judge defaults, applied when the CRD's judge fields are 0/unset.
DEFAULT_JUDGE_WINDOW_S = 30.0
DEFAULT_TTFT_P95_RATIO = 1.5
# Fewer observations than this on either side and the TTFT comparison
# abstains — a two-request canary p95 condemns nobody.
MIN_JUDGE_SAMPLES = 10.0


@dataclasses.dataclass
class _Rollout:
    """In-flight rollout state for one model."""

    new_hash: str
    old_hash: str
    replicas: int
    step_size: int
    started_at: float
    # Cumulative new-hash pod cap the plan may mint; 0 until the first
    # governed step admits the canary.
    max_new: int = 0
    steps: int = 0
    last_step_at: float = 0.0
    phase: str = PHASE_CANARY
    # Per-version cumulative TTFT-hist baselines captured at the last
    # step: the judge differences against these so each step is judged
    # on its own window, not the versions' lifetime histograms.
    baselines: dict = dataclasses.field(default_factory=dict)

    def share(self) -> float:
        """The traffic share the canary version is allowed right now."""
        if self.replicas <= 0:
            return 0.0
        return min(1.0, self.max_new / self.replicas)


class RolloutController:
    """See module docstring. Construction mirrors the other control
    loops: `store`/`lb`/`fleet`/`governor`/`recorder` injected by the
    manager (any may be None — each capability degrades independently),
    `clock` monotonic and injectable (FakeClock in the sims), `enqueue`
    an optional `(namespace, name) -> None` that requeues a Model for
    reconcile after a step changes its cap."""

    def __init__(
        self,
        store=None,
        lb=None,
        fleet=None,
        governor=None,
        recorder=None,
        namespace: str = "default",
        metrics: Metrics = DEFAULT_METRICS,
        clock=time.monotonic,
        interval_s: float = 5.0,
        enqueue=None,
    ):
        self.store = store
        self.lb = lb
        self.fleet = fleet
        self.governor = governor
        self.recorder = recorder
        self.namespace = namespace
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.enqueue = enqueue
        self._clock = clock
        self._lock = threading.Lock()
        # (ns, name) -> in-flight rollout.
        self._state: dict[tuple[str, str], _Rollout] = {}
        # (ns, name) -> condemned hash: survives the rollout state so a
        # re-rendered spec with the SAME hash cannot restart the rollout
        # the judge just killed (only a new spec hash clears it).
        self._condemned: dict[tuple[str, str], str] = {}
        # (ns, name) -> last rendered-spec hash the reconciler showed us
        # (pin hygiene needs it on the tick thread).
        self._expected: dict[tuple[str, str], str] = {}
        # (ns, name) -> clock of the last slice-group roll (group pacing).
        self._gsteps: dict[tuple[str, str], float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception("rollout tick failed")

    # -- reconciler seams (called on the controller's work thread) -------------

    def pod_cap(self, model: Model, desired_pod: dict,
                pods: list[dict]) -> int | None:
        """The `max_new` seam for `calculate_pod_plan` — and the
        controller's hash-drift sensor: every reconcile pass reports the
        rendered spec hash here, which is what starts (and completes)
        rollouts. Returns None for models without an enabled `rollout:`
        block, while a pin is steering the plan, and when no rollout is
        in flight."""
        key = (model.namespace, model.name)
        expected = k8sutils.pod_hash(desired_pod["spec"])
        ro = model.spec.rollout
        with self._lock:
            self._expected[key] = expected
            if not ro.enabled():
                self._state.pop(key, None)
                return None
            pinned = model.annotations.get(md.ROLLOUT_PINNED_HASH_ANNOTATION)
            if pinned and pinned != expected:
                # Rollback steering the plan. Remember what was
                # condemned (a restarted operator rehydrates it from
                # this very situation: pin != rendered hash means the
                # rendered hash was condemned).
                self._condemned.setdefault(key, expected)
                self._state.pop(key, None)
                return None
            condemned = self._condemned.get(key)
            if condemned == expected:
                # The judge already killed this exact hash: hold the cap
                # at zero even if the pin write was refused or lost.
                return 0
            if condemned is not None:
                # A third hash supersedes the condemned one.
                del self._condemned[key]
            old_hashes = [
                h for h in (
                    k8sutils.get_label(p, md.POD_HASH_LABEL) for p in pods
                ) if h and h != expected
            ]
            st = self._state.get(key)
            if st is not None and st.new_hash == expected:
                if not old_hashes:
                    self._complete_locked(key, model, st)
                    return None
                return st.max_new
            if st is not None:
                # Spec moved again mid-rollout: restart against the new
                # hash (the judge never vouched for the abandoned one).
                self._state.pop(key, None)
            if not old_hashes:
                return None  # fresh model / steady state: nothing to roll
            replicas = model.spec.replicas or 0
            if replicas <= 1:
                # A single replica has no stable version to compare
                # against: classic surge plan (regression-pinned by
                # tests/unit/test_rollout_sim.py).
                return None
            step = max(1, math.ceil(ro.canary_percent / 100.0 * replicas))
            old_hash = max(set(old_hashes), key=old_hashes.count)
            now = self._clock()
            self._state[key] = _Rollout(
                new_hash=expected, old_hash=old_hash, replicas=replicas,
                step_size=step, started_at=now,
            )
            logger.info(
                "rollout: model %s/%s hash %s -> %s detected (canary step "
                "%d of %d replicas)",
                model.namespace, model.name, old_hash, expected, step,
                replicas,
            )
            self._record("detected", model.name, new=expected, old=old_hash,
                         step=step)
            # Held at 0 until the first governed step (next tick) admits
            # the canary — detection itself disrupts nothing.
            return 0

    def group_cap(self, model: Model) -> int | None:
        """The `max_hash_recreates` seam for `calculate_group_pod_plan`:
        multi-host models roll ONE slice group per `stepSeconds`. None
        for models without a `rollout:` block (classic unbounded plan)."""
        ro = model.spec.rollout
        if not ro.enabled():
            return None
        with self._lock:
            last = self._gsteps.get((model.namespace, model.name))
        if last is not None and self._clock() - last < ro.step_seconds:
            return 0
        return 1

    def note_group_step(self, model: Model, groups: list[str]) -> None:
        """The group plan actually rolled `groups` for hash drift this
        pass: start the step timer and log the decision. (The teardown
        itself was governed at execution — a healthy group delete pays
        disruption budget in `PodPlan.execute`.)"""
        with self._lock:
            self._gsteps[(model.namespace, model.name)] = self._clock()
        self.metrics.rollout_steps.inc(model=model.name, step="group")
        self._record("group_roll", model.name, groups=",".join(groups))

    # -- the judged control loop ----------------------------------------------

    def tick(self) -> dict:
        """One judged pass over every in-flight rollout: refresh the
        LB's canary weighting, read the per-version evidence, roll back
        or advance. Returns {model: verdict} for observability/tests."""
        now = self._clock()
        verdicts: dict[str, str] = {}
        for model in self._models():
            key = (model.namespace, model.name)
            self._pin_hygiene(model)
            ro = model.spec.rollout
            with self._lock:
                st = self._state.get(key)
                condemned = self._condemned.get(key)
            if st is None:
                if condemned is not None and self.lb is not None:
                    # Keep routing away from the condemned hash while
                    # its pods drain.
                    self.lb.group(model.name).set_canary(condemned, 0.0)
                    self.metrics.rollout_phase.set(
                        _PHASE_GAUGE[PHASE_ROLLING_BACK], model=model.name
                    )
                elif ro.enabled():
                    self.metrics.rollout_phase.set(
                        _PHASE_GAUGE[PHASE_IDLE], model=model.name
                    )
                continue
            share = st.share()
            if self.lb is not None:
                # Routing-time enforcement: canary endpoints get at most
                # their allotted share even when they are the fastest.
                self.lb.group(model.name).set_canary(st.new_hash, share)
            self.metrics.rollout_canary_share.set(share, model=model.name)
            self.metrics.rollout_phase.set(
                _PHASE_GAUGE[st.phase], model=model.name
            )
            if st.max_new <= 0:
                # Nothing admitted yet: the first step needs no judging.
                self._advance(model, st, now)
                continue
            verdict, detail = self._judge(model, st, now)
            if verdict is None:
                continue  # evidence window still filling
            verdicts[model.name] = verdict
            self.metrics.rollout_verdicts.inc(
                model=model.name, verdict=verdict
            )
            if verdict != VERDICT_PASS:
                if ro.auto_rollback:
                    self._rollback(model, st, verdict, detail)
                else:
                    # Judged bad but rollback disabled: freeze the ramp
                    # (the cap stops rising; an operator decides).
                    self._record("frozen", model.name, verdict=verdict,
                                 detail=detail)
                continue
            if now - st.last_step_at >= ro.step_seconds:
                self._advance(model, st, now)
        return verdicts

    def _models(self) -> list[Model]:
        if self.store is None:
            return []
        out = []
        for obj in self.store.list("Model", self.namespace):
            try:
                out.append(Model.from_dict(obj))
            except Exception:
                continue  # admission-invalid stragglers judge nobody
        return out

    def _judge(self, model: Model, st: _Rollout, now: float):
        """Comparative verdict for one in-flight rollout: (verdict,
        detail), or (None, "") while evidence is still accumulating.
        Fails only on POSITIVE evidence that the new hash is worse —
        stale telemetry abstains (and the governor's coverage gate
        already refuses steps while blind)."""
        j = model.spec.rollout.judge
        window = j.window_seconds or DEFAULT_JUDGE_WINDOW_S
        if now - st.last_step_at < window:
            return None, ""
        entry = self.fleet.model_entry(model.name) if self.fleet else None
        if entry is None:
            return None, ""
        versions = entry.get("versions") or {}
        new = versions.get(st.new_hash)
        old = versions.get(st.old_hash)
        if not new or not new.get("endpoints"):
            if old and old.get("endpoints"):
                return VERDICT_CRASHLOOP, (
                    f"no serving {st.new_hash} endpoint {window:g}s after "
                    f"admitting {st.max_new}"
                )
            return None, ""  # neither version visible: abstain
        trips = int(new.get("breakers_open") or 0)
        if trips > j.max_breaker_trips:
            return VERDICT_BREAKERS, (
                f"{trips} open breaker(s) on {st.new_hash} "
                f"(allowed {j.max_breaker_trips})"
            )
        ratio = j.ttft_p95_ratio or DEFAULT_TTFT_P95_RATIO
        new_q = self._windowed_ttft(st, st.new_hash, new)
        old_q = self._windowed_ttft(st, st.old_hash, old or {})
        if (
            new_q.get("count", 0.0) >= MIN_JUDGE_SAMPLES
            and old_q.get("count", 0.0) >= MIN_JUDGE_SAMPLES
        ):
            np95, op95 = new_q.get("p95_s"), old_q.get("p95_s")
            if np95 and op95 and np95 > ratio * op95:
                return VERDICT_TTFT, (
                    f"ttft p95 {np95:g}s vs {op95:g}s "
                    f"(ratio {np95 / op95:.2f} > {ratio:g})"
                )
        return VERDICT_PASS, ""

    def _windowed_ttft(self, st: _Rollout, version: str, row: dict) -> dict:
        """TTFT quantiles for one version over the CURRENT step's window:
        the cumulative merged histogram minus the baseline captured when
        the step started (no baseline = lifetime, which for a canary IS
        its window)."""
        from kubeai_tpu.fleet.aggregator import hist_detail_quantiles

        cur = row.get("ttft_hist") or {}
        base = st.baselines.get(version) or {}
        return hist_detail_quantiles(_delta_hist(cur, base))

    # -- transitions -----------------------------------------------------------

    def _advance(self, model: Model, st: _Rollout, now: float) -> None:
        """One governed step: admit the canary, widen the ramp, or allow
        full replacement. Budgeted — a step deliberately replaces
        healthy serving capacity."""
        gov = self.governor
        if gov is not None and not gov.allow_rollout_step(model.name):
            self.metrics.rollout_denied.inc(
                model=model.name, action="rollout_step"
            )
            return  # retried next tick; the cap holds meanwhile
        st.max_new = min(st.replicas, st.max_new + st.step_size)
        st.steps += 1
        st.last_step_at = now
        st.phase = PHASE_CANARY if st.steps == 1 else PHASE_RAMP
        st.baselines = self._capture_baselines(model, st)
        kind = (
            "start" if st.steps == 1
            else "promote" if st.max_new >= st.replicas
            else "widen"
        )
        self.metrics.rollout_steps.inc(model=model.name, step=kind)
        if self.lb is not None:
            self.lb.group(model.name).set_canary(st.new_hash, st.share())
        self.metrics.rollout_canary_share.set(st.share(), model=model.name)
        logger.info(
            "rollout: model %s/%s step %d (%s) — cap %d/%d, share %.2f",
            model.namespace, model.name, st.steps, kind, st.max_new,
            st.replicas, st.share(),
        )
        self._record(kind, model.name, new=st.new_hash, max_new=st.max_new,
                     share=round(st.share(), 4))
        if self.enqueue is not None:
            self.enqueue(model.namespace, model.name)

    def _capture_baselines(self, model: Model, st: _Rollout) -> dict:
        entry = self.fleet.model_entry(model.name) if self.fleet else None
        versions = (entry or {}).get("versions") or {}
        return {
            v: dict(versions[v].get("ttft_hist") or {})
            for v in (st.new_hash, st.old_hash) if v in versions
        }

    def _complete_locked(self, key, model: Model, st: _Rollout) -> None:
        """The old hash is fully drained: the rollout is done. Called
        with the state lock held (from the reconciler seam)."""
        self._state.pop(key, None)
        if self.lb is not None:
            self.lb.group(model.name).set_canary(None)
        self.metrics.rollout_phase.set(
            _PHASE_GAUGE[PHASE_IDLE], model=model.name
        )
        self.metrics.rollout_canary_share.set(0.0, model=model.name)
        logger.info(
            "rollout: model %s/%s complete at hash %s after %d step(s)",
            model.namespace, model.name, st.new_hash, st.steps,
        )
        self._record("complete", model.name, new=st.new_hash, steps=st.steps)

    def _rollback(self, model: Model, st: _Rollout, verdict: str,
                  detail: str) -> None:
        """The judge condemned the new hash: pin the last-good one onto
        the Model (the pod plan then treats it as desired and tears the
        condemned hash down), zero the canary's traffic share, and dump
        a replayable incident bundle."""
        if not self._write_pin(model, st.old_hash):
            self.metrics.rollout_denied.inc(
                model=model.name, action="rollout_rollback"
            )
            return  # governor refused (fence/coverage); retried next tick
        key = (model.namespace, model.name)
        with self._lock:
            self._condemned[key] = st.new_hash
            self._state.pop(key, None)
        if self.lb is not None:
            self.lb.group(model.name).set_canary(st.new_hash, 0.0)
        self.metrics.rollout_rollbacks.inc(model=model.name, reason=verdict)
        self.metrics.rollout_canary_share.set(0.0, model=model.name)
        self.metrics.rollout_phase.set(
            _PHASE_GAUGE[PHASE_ROLLING_BACK], model=model.name
        )
        logger.warning(
            "rollout: ROLLING BACK model %s/%s — %s (%s); pinning %s, "
            "condemning %s",
            model.namespace, model.name, verdict, detail, st.old_hash,
            st.new_hash,
        )
        self._record("rollback", model.name, verdict=verdict, detail=detail,
                     pinned=st.old_hash, condemned=st.new_hash)
        if self.recorder is not None:
            self.recorder.trigger(
                flightrecorder.TRIGGER_ROLLBACK,
                detail=f"model {model.name}: {verdict} — {detail}",
                extra_header={"model": model.name, "verdict": verdict},
            )
        if self.enqueue is not None:
            self.enqueue(model.namespace, model.name)

    def _pin_hygiene(self, model: Model) -> None:
        """Clear a pin that no longer steers anything: the spec moved to
        a THIRD hash (a fix superseding the condemned version) or back
        to the pinned one (the pin is then redundant). The rendered hash
        comes from the reconciler seam; a model we have not seen render
        yet keeps its pin."""
        pinned = model.annotations.get(md.ROLLOUT_PINNED_HASH_ANNOTATION)
        if not pinned:
            return
        key = (model.namespace, model.name)
        with self._lock:
            expected = self._expected.get(key)
            condemned = self._condemned.get(key)
        if expected is None:
            return
        stale = expected == pinned or (
            condemned is not None and expected != condemned
        )
        if not stale:
            return
        if self._write_pin(model, None):
            with self._lock:
                self._condemned.pop(key, None)
            if self.lb is not None:
                self.lb.group(model.name).set_canary(None)
            self._record("pin_cleared", model.name, pinned=pinned,
                         expected=expected)

    def _write_pin(self, model: Model, value: str | None) -> bool:
        """EVERY write of the rollout-pin annotation lives here, behind
        `ActuationGovernor.allow_rollback` — rolling back is repair (no
        disruption budget) but stays fenced and coverage-gated, and
        scripts/check_actuation_paths.py pins the annotation write to
        this function. `value=None` clears the pin (same gate: clearing
        re-opens the path to the once-condemned hash)."""
        if self.governor is not None and not self.governor.allow_rollback(
            model.name
        ):
            return False
        if self.store is None:
            return False
        try:
            self.store.patch_merge(
                "Model", model.namespace, model.name,
                {"metadata": {"annotations": {
                    md.ROLLOUT_PINNED_HASH_ANNOTATION: value,
                }}},
            )
        except (NotFound, Conflict):
            return False
        return True

    def _record(self, decision: str, model: str, **detail) -> None:
        if self.recorder is not None:
            self.recorder.record(
                flightrecorder.ROLLOUT_DECISION, "rollout", target=model,
                decision=decision, **detail,
            )

    # -- admin surface ---------------------------------------------------------

    def state_payload(self) -> dict:
        """In-flight rollout state for debugging surfaces."""
        with self._lock:
            rollouts = {
                f"{ns}/{name}": {
                    "phase": st.phase, "new_hash": st.new_hash,
                    "old_hash": st.old_hash, "max_new": st.max_new,
                    "replicas": st.replicas, "steps": st.steps,
                    "share": round(st.share(), 4),
                }
                for (ns, name), st in self._state.items()
            }
            condemned = {
                f"{ns}/{name}": h
                for (ns, name), h in self._condemned.items()
            }
        return {"object": "rollout.state", "rollouts": rollouts,
                "condemned": condemned}


def _delta_hist(cur: dict, base: dict) -> dict:
    """Difference two cumulative `hist_detail` dicts (current minus
    baseline) into a windowed one. Counter resets (an endpoint replaced
    mid-step) clamp at the current value rather than going negative."""
    if not cur:
        return {}
    if not base:
        return cur
    base_by_le = dict(base.get("buckets") or [])
    buckets = []
    for le, c in cur.get("buckets") or []:
        buckets.append([le, max(0.0, c - base_by_le.get(le, 0.0))])
    count = max(0.0, cur.get("count", 0.0) - base.get("count", 0.0))
    total_sum = max(0.0, cur.get("sum", 0.0) - base.get("sum", 0.0))
    if count <= 0 or not buckets:
        return {}
    return {"buckets": buckets, "count": count, "sum": total_sum}
