"""Model-artifact cache subsystem (reference: internal/modelcontroller/cache.go).

Shared-filesystem PVC per cacheProfile; a loader Job downloads the model to
`/models/<name>-<uid>`; the PVC annotation `models.kubeai.org/<model>`
records which Model UID is loaded; deletion runs an eviction Job guarded by
the `kubeai.org/cache-eviction` finalizer.
"""

from __future__ import annotations

import json
import time

from kubeai_tpu.config import System
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.engines.common import ModelConfig
from kubeai_tpu.operator.k8s.store import KubeStore


class ReturnEarly(Exception):
    """Reconcile should stop and wait for the next event
    (reference: modelcontroller errReturnEarly)."""


def cache_pvc_name(model: Model, cfg: System) -> str:
    profile = model.spec.cache_profile
    cp = cfg.cache_profiles.get(profile)
    if cp and cp.shared_filesystem is not None:
        return f"shared-model-cache-{profile}"
    return f"model-cache-{model.name}"


def load_cache_job_name(model: Model) -> str:
    return f"load-cache-{model.name}"


def evict_cache_job_name(model: Model) -> str:
    return f"evict-cache-{model.name}"


def cache_dir(model: Model) -> str:
    # /models/<name>-<uid> (reference: cache.go loadCacheJobForModel).
    return f"/models/{model.name}-{model.uid}"


def _parse_pvc_model_annotation(pvc: dict, model_name: str) -> dict:
    raw = k8sutils.get_annotation(pvc, md.pvc_model_annotation(model_name))
    if not raw:
        return {"uid": "", "timestamp": 0}
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        return {"uid": "", "timestamp": 0}


def _pvc_for_model(model: Model, cfg: System) -> dict:
    cp = cfg.cache_profiles[model.spec.cache_profile]
    shared = cp.shared_filesystem or {}
    spec: dict = {
        "accessModes": ["ReadWriteMany"],
        "resources": {"requests": {"storage": shared.get("size", "100Gi")}},
    }
    if shared.get("storageClassName"):
        spec["storageClassName"] = shared["storageClassName"]
    if shared.get("persistentVolumeName"):
        spec["volumeName"] = shared["persistentVolumeName"]
    return {
        "apiVersion": "v1",
        "kind": "PersistentVolumeClaim",
        "metadata": {
            "name": cache_pvc_name(model, cfg),
            "namespace": model.namespace,
            "annotations": {},
        },
        "spec": spec,
    }


def _loader_job(model: Model, cfg: System, name: str, args: list[str]) -> dict:
    return {
        "apiVersion": "batch/v1",
        "kind": "Job",
        "metadata": {"name": name, "namespace": model.namespace},
        "spec": {
            "backoffLimit": 6,
            "template": {
                "spec": {
                    "restartPolicy": "OnFailure",
                    "containers": [
                        {
                            "name": "loader",
                            "image": cfg.model_loading_image,
                            "args": args,
                            "volumeMounts": [
                                {"name": "model-cache", "mountPath": "/models"}
                            ],
                        }
                    ],
                    "volumes": [
                        {
                            "name": "model-cache",
                            "persistentVolumeClaim": {
                                "claimName": cache_pvc_name(model, cfg)
                            },
                        }
                    ],
                }
            },
        },
    }


def reconcile_cache(
    store: KubeStore, model: Model, model_obj: dict, cfg: System, mcfg: ModelConfig
) -> bool:
    """Ensure PVC + loader Job; returns cache_loaded. Raises ReturnEarly
    while loading is in flight (reference: cache.go:30-134)."""
    pvc = store.try_get("PersistentVolumeClaim", model.namespace, cache_pvc_name(model, cfg))
    deleted = model.deletion_timestamp is not None
    if pvc is None:
        if not deleted:
            pvc = store.create(_pvc_for_model(model, cfg))
        else:
            return False

    cp = cfg.cache_profiles.get(model.spec.cache_profile)
    if cp and cp.shared_filesystem is not None:
        # Shared caches need per-model cleanup on delete → finalizer.
        if md.CACHE_EVICTION_FINALIZER not in model_obj["metadata"].setdefault(
            "finalizers", []
        ):
            model_obj["metadata"]["finalizers"].append(md.CACHE_EVICTION_FINALIZER)
            store.update(model_obj)

    job = store.try_get("Job", model.namespace, load_cache_job_name(model))
    ann = _parse_pvc_model_annotation(pvc, model.name)

    if ann["uid"] != model.uid:
        if job is None:
            job = _loader_job(
                model,
                cfg,
                load_cache_job_name(model),
                ["load", model.spec.url, cache_dir(model)],
            )
            k8sutils.set_owner_reference(model_obj, job)
            store.create(job)
            raise ReturnEarly()
        if not k8sutils.job_is_complete(job):
            raise ReturnEarly()
        pvc = store.get(
            "PersistentVolumeClaim", model.namespace, cache_pvc_name(model, cfg)
        )
        pvc["metadata"].setdefault("annotations", {})[
            md.pvc_model_annotation(model.name)
        ] = json.dumps({"uid": model.uid, "timestamp": time.time()})
        store.update(pvc)
        ann = {"uid": model.uid}

    loaded = ann["uid"] == model.uid
    if job is not None:
        # Completed: delete to avoid accumulating Jobs (reference: cache.go:126-131).
        store.delete("Job", model.namespace, load_cache_job_name(model))
    return loaded


def finalize_cache(
    store: KubeStore, model: Model, model_obj: dict, cfg: System, mcfg: ModelConfig
) -> None:
    """Eviction flow on Model delete (reference: cache.go:136-217)."""
    pvc = store.try_get(
        "PersistentVolumeClaim", model.namespace, cache_pvc_name(model, cfg)
    )
    if pvc is None or (pvc["metadata"].get("deletionTimestamp") is not None):
        _delete_cache_jobs(store, model)
        _remove_finalizer(store, model_obj)
        return

    if md.CACHE_EVICTION_FINALIZER in (model_obj["metadata"].get("finalizers") or []):
        evict = store.try_get("Job", model.namespace, evict_cache_job_name(model))
        if evict is None:
            job = _loader_job(
                model,
                cfg,
                evict_cache_job_name(model),
                ["evict", cache_dir(model)],
            )
            k8sutils.set_owner_reference(model_obj, job)
            store.create(job)
            raise ReturnEarly()
        if not k8sutils.job_is_complete(evict):
            raise ReturnEarly()
        ann_key = md.pvc_model_annotation(model.name)
        if ann_key in (pvc["metadata"].get("annotations") or {}):
            del pvc["metadata"]["annotations"][ann_key]
            store.update(pvc)
        _remove_finalizer(store, model_obj)
    _delete_cache_jobs(store, model)


def _delete_cache_jobs(store: KubeStore, model: Model) -> None:
    from kubeai_tpu.operator.k8s.store import NotFound

    for name in (load_cache_job_name(model), evict_cache_job_name(model)):
        try:
            store.delete("Job", model.namespace, name)
        except NotFound:
            pass


def _remove_finalizer(store: KubeStore, model_obj: dict) -> None:
    fins = model_obj["metadata"].get("finalizers") or []
    if md.CACHE_EVICTION_FINALIZER in fins:
        fins.remove(md.CACHE_EVICTION_FINALIZER)
        store.update(model_obj)
