"""Operator control plane: reconcilers, pod planning, engines, cache,
adapters (reference: internal/modelcontroller, internal/manager)."""
