"""Model files ConfigMap projection (reference: internal/modelcontroller/files.go).

spec.files entries are stored in a per-model ConfigMap and mounted into
server Pods via items/subPath (see engines/common.files_volume).
"""

from __future__ import annotations

from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.k8s.store import KubeStore, NotFound


def files_configmap_name(model: Model) -> str:
    return f"model-{model.name}-files"


def ensure_model_files_configmap(
    store: KubeStore, model: Model, model_obj: dict
) -> None:
    """Create/update/delete the files ConfigMap to match spec.files."""
    name = files_configmap_name(model)
    existing = store.try_get("ConfigMap", model.namespace, name)
    if not model.spec.files:
        if existing is not None:
            store.delete("ConfigMap", model.namespace, name)
        return
    data = {f"file-{i}": f.content for i, f in enumerate(model.spec.files)}
    if existing is None:
        cm = {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": model.namespace},
            "data": data,
        }
        k8sutils.set_owner_reference(model_obj, cm)
        store.create(cm)
    elif existing.get("data") != data:
        existing["data"] = data
        store.update(existing)
