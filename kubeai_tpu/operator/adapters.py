"""LoRA adapter orchestration (reference: internal/modelcontroller/adapters.go:24-118).

Per-Pod diff of `adapter.kubeai.org/<name>=hash(url)` labels against
spec.adapters:
  - missing/stale → download (exec into the loader sidecar for vLLM;
    URL-direct for the in-tree TPU engine, which fetches adapters itself),
    then the engine admin API, then set the Pod label
  - labelled-but-unspecified → unload + remove the label

The load balancer routes adapter-suffixed requests only to Pods carrying
the adapter label (reference: internal/loadbalancer/load_balancer.go:90-127).
"""

from __future__ import annotations

from typing import Protocol

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Adapter, Model, ENGINE_KUBEAI_TPU, ENGINE_VLLM
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.engine_client import EngineClient, EngineClientError
from kubeai_tpu.operator.k8s.store import KubeStore

LOADER_CONTAINER = "loader"


class ReturnEarly(Exception):
    pass


class PodExec(Protocol):
    """Exec seam (reference: pod_utils.go:13-43 SPDY exec). Tests inject a
    fake; production uses the k8s exec subresource."""

    def exec(
        self, namespace: str, pod: str, container: str, command: list[str]
    ) -> None: ...


def adapter_dir(adapter: Adapter) -> str:
    return f"/adapters/{adapter.name}"


def _pod_addr(pod: dict) -> str:
    ip = k8sutils.get_annotation(pod, md.MODEL_POD_IP_ANNOTATION) or (
        (pod.get("status") or {}).get("podIP", "")
    )
    port = k8sutils.get_annotation(pod, md.MODEL_POD_PORT_ANNOTATION) or "8000"
    return f"http://{ip}:{port}"


def _labelled_adapters(pod: dict) -> dict[str, str]:
    labels = (pod.get("metadata") or {}).get("labels") or {}
    prefix = md.ADAPTER_LABEL_DOMAIN + "/"
    return {
        k[len(prefix):]: v for k, v in labels.items() if k.startswith(prefix)
    }


def reconcile_adapters(
    store: KubeStore,
    model: Model,
    pods: list[dict],
    engine_client: EngineClient,
    pod_exec: PodExec | None = None,
) -> None:
    adapters = model.spec.adapters
    engine = model.spec.engine
    if engine not in (ENGINE_VLLM, ENGINE_KUBEAI_TPU):
        return

    for pod in pods:
        if not k8sutils.pod_is_ready(pod):
            continue
        addr = _pod_addr(pod)
        candidates = _labelled_adapters(pod)
        to_ensure: list[Adapter] = []
        for adapter in adapters:
            want_hash = k8sutils.string_hash(adapter.url)
            if candidates.get(adapter.name) == want_hash:
                candidates.pop(adapter.name, None)  # up to date
            else:
                to_ensure.append(adapter)
        ensure_names = {a.name for a in to_ensure}
        # Stale-hash adapters (URL changed) stay in `candidates` but must
        # RELOAD in place (the engine reloads when the source changes),
        # never load-then-unload.
        to_remove = [n for n in candidates if n not in ensure_names]
        pending = _pending_unloads(pod)
        # Labels are removed BEFORE unload (drain ordering below), so an
        # unload the engine refused with 409 (in-flight requests) must be
        # rediscoverable on the requeue — by then its label is gone. The
        # pending-unload annotation remembers it; the engine listing
        # reconciles annotation state against what is actually loaded.
        # Skipped entirely for adapter-free models (no per-reconcile GET).
        if pending:
            try:
                loaded = set(
                    engine_client.list_lora_adapters(addr, model.name)
                )
                spec_names = {a.name for a in adapters}
                for name in sorted(pending):
                    if name in spec_names:
                        # Re-added to the spec before the unload stuck:
                        # it is desired again, drop the tombstone.
                        _clear_pending_unload(store, pod, name)
                        continue
                    if name in to_remove:
                        continue
                    if name in loaded:
                        to_remove.append(name)
                    else:
                        _clear_pending_unload(store, pod, name)
            except EngineClientError:
                pass  # engine unreachable; retry on the next reconcile

        for adapter in to_ensure:
            reload_in_place = adapter.name in candidates
            if engine == ENGINE_VLLM:
                # Download via the loader sidecar, then point vLLM at the
                # shared emptyDir path. The fetch runs FIRST so a bad new
                # URL fails before anything is drained or unloaded — the
                # old adapter keeps serving through spec-update mistakes.
                if not k8sutils.container_is_ready(pod, LOADER_CONTAINER):
                    raise ReturnEarly()
                if pod_exec is not None:
                    pod_exec.exec(
                        pod["metadata"]["namespace"],
                        pod["metadata"]["name"],
                        LOADER_CONTAINER,
                        ["load", adapter.url, adapter_dir(adapter)],
                    )
                if reload_in_place:
                    # vLLM cannot hot-reload a loaded lora_name (duplicate
                    # load 400s "already loaded"), so a URL change must
                    # drain (label off) + unload + fresh load. A crash in
                    # this window is re-ensured by the next reconcile: the
                    # adapter stays in the spec, and the "already loaded"
                    # recovery below resolves whichever half-state the
                    # engine was left in.
                    _remove_pod_label(
                        store, pod, md.adapter_label(adapter.name)
                    )
                    engine_client.unload_lora_adapter(
                        addr, adapter.name, ignore_not_found=True
                    )
                try:
                    engine_client.load_lora_adapter(
                        addr,
                        adapter.name,
                        lora_path=adapter_dir(adapter),
                    )
                except EngineClientError as e:
                    if "already" not in str(e).lower():
                        raise
                    # "Already loaded" while the pod label is absent or
                    # stale means the engine holds weights of UNKNOWN
                    # vintage (the label hash is the only version record,
                    # and vLLM loads from the same shared dir every time —
                    # e.g. a prior reconcile crashed between label removal
                    # and unload). Swallowing it would stamp the new hash
                    # over stale weights forever; resolve by unload +
                    # fresh load of the just-fetched artifact.
                    engine_client.unload_lora_adapter(
                        addr, adapter.name, ignore_not_found=True
                    )
                    engine_client.load_lora_adapter(
                        addr,
                        adapter.name,
                        lora_path=adapter_dir(adapter),
                    )
                _update_pod_label(
                    store, pod, md.adapter_label(adapter.name),
                    k8sutils.string_hash(adapter.url),
                )
            else:
                # TPU engine fetches the adapter itself from the URL and
                # reloads in place when the source changes.
                _load_or_drain(
                    store, pod, engine_client, reload_in_place,
                    addr,
                    adapter.name,
                    k8sutils.string_hash(adapter.url),
                    lora_url=adapter.url,
                )

        for name in to_remove:
            # Tombstone FIRST (a crash after the label is gone but before
            # the annotation lands would leak the adapter in the engine
            # forever — orphan discovery is gated on the annotation), then
            # the label (the LB stops routing adapter traffic, in-flight
            # requests drain, and the engine's 409 in-use refusal resolves
            # on the backoff requeue — unload-first would livelock under
            # sustained traffic), then the unload itself.
            _add_pending_unload(store, pod, name)
            _remove_pod_label(store, pod, md.adapter_label(name))
            engine_client.unload_lora_adapter(addr, name, ignore_not_found=True)
            _clear_pending_unload(store, pod, name)


def _load_or_drain(
    store: KubeStore,
    pod: dict,
    engine_client: EngineClient,
    reload_in_place: bool,
    addr: str,
    name: str,
    url_hash: str,
    lora_url: str = "",
) -> None:
    """Load (or reload, on URL change) an in-tree-engine adapter, draining
    ON DEMAND.

    The TPU engine reloads in place when the source URL changes, so a
    URL-change reload keeps the old routing label until the engine
    actually refuses with an in-use 409 — dropping it eagerly converts a
    bad spec update (fetch/load fails with 400/transport error) into an
    indefinite routing outage while the old, still-loaded adapter would
    have kept serving fine. On a 409 we drop the label so the LB drains
    in-flight traffic and the backoff requeue retries; on any other
    failure the old label (and the serving adapter) stay put."""
    try:
        engine_client.load_lora_adapter(
            addr, name, lora_url=lora_url,
            ignore_already_loaded=not reload_in_place,
        )
    except EngineClientError as e:
        if reload_in_place and e.status == 409:
            _remove_pod_label(store, pod, md.adapter_label(name))
        raise
    _update_pod_label(store, pod, md.adapter_label(name), url_hash)


def _pending_unloads(pod: dict) -> set[str]:
    ann = ((pod.get("metadata") or {}).get("annotations") or {}).get(
        md.ADAPTER_PENDING_UNLOAD_ANNOTATION, ""
    )
    return {n for n in ann.split(",") if n}


def _set_pending_unloads(store: KubeStore, pod: dict, names: set[str]) -> None:
    fresh = store.get("Pod", pod["metadata"]["namespace"], pod["metadata"]["name"])
    anns = fresh["metadata"].setdefault("annotations", {})
    if names:
        anns[md.ADAPTER_PENDING_UNLOAD_ANNOTATION] = ",".join(sorted(names))
    else:
        anns.pop(md.ADAPTER_PENDING_UNLOAD_ANNOTATION, None)
    store.update(fresh)
    pod["metadata"].setdefault("annotations", {}).update(anns)
    if not names:
        (pod["metadata"].get("annotations") or {}).pop(
            md.ADAPTER_PENDING_UNLOAD_ANNOTATION, None
        )


def _add_pending_unload(store: KubeStore, pod: dict, name: str) -> None:
    _set_pending_unloads(store, pod, _pending_unloads(pod) | {name})


def _clear_pending_unload(store: KubeStore, pod: dict, name: str) -> None:
    _set_pending_unloads(store, pod, _pending_unloads(pod) - {name})


def _update_pod_label(store: KubeStore, pod: dict, key: str, value: str) -> None:
    fresh = store.get("Pod", pod["metadata"]["namespace"], pod["metadata"]["name"])
    fresh["metadata"].setdefault("labels", {})[key] = value
    store.update(fresh)
    pod["metadata"].setdefault("labels", {})[key] = value


def _remove_pod_label(store: KubeStore, pod: dict, key: str) -> None:
    fresh = store.get("Pod", pod["metadata"]["namespace"], pod["metadata"]["name"])
    labels = fresh["metadata"].get("labels") or {}
    labels.pop(key, None)
    store.update(fresh)
    (pod["metadata"].get("labels") or {}).pop(key, None)
