"""Actuation safety governor: the one gate every destructive
control-plane action passes through.

PRs 3/5 made the *data path* survive endpoint death; PR 7 gave the
control plane the power to shrink models and mark pods for preemptive
deletion fleet-wide. That power needs a governor: a corrupt fleet
snapshot, a split-brain second operator, or a crash-looping control
loop must never be able to mass-delete healthy serving capacity. Three
disciplines, enforced here and nowhere else:

  * **Disruption budgets.** Deleting a HEALTHY (ready, undisrupted) pod
    consumes one unit of a per-model and a cluster-wide budget over a
    sliding time window. Replacing already-broken pods is repair, not
    disruption — never budget-limited. When a budget is exhausted the
    deletion is refused (and counted in `kubeai_governor_denied_total`);
    the pod plan simply converges over later windows.
  * **Telemetry gates / static stability.** When armed
    (`governor.minTelemetryCoverage > 0` and a fleet aggregator is
    wired), scale-to-zero and planner preemption require the model's
    endpoint-telemetry coverage to meet the threshold, and while the
    fleet snapshot is absent or stale the governor holds last-known-good
    replica counts: scale-downs and budgeted deletions are refused
    outright until telemetry returns.
  * **Lease fencing.** Every actuation batch checks
    `LeaderElection.fence_valid()` first: a replica whose lease expired
    (or that never held one) raises `NotLeader` and its writes are
    dropped — dual operators cannot fight over the same pods.

The governor is also the restart-rehydration point: last-known-good
replica counts are persisted as a Model annotation and re-read by
`rehydrate()` before the operator's first tick, so a control-plane
crash never causes scale thrash.

A governor constructed with no config (`ActuationGovernor()`) is
PERMISSIVE: fence-valid, no budgets, no gates — the default for
components wired outside a `Manager` (unit tests, ad-hoc tools). The
static-analysis gate `scripts/check_actuation_paths.py` fails tier-1
when a pod-deletion call site appears outside this module.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.metrics import flightrecorder
from kubeai_tpu.metrics.registry import DEFAULT_METRICS, Metrics
from kubeai_tpu.operator.k8s.store import NotFound

logger = logging.getLogger(__name__)

# Action vocabulary (metric label values; stable strings).
ACTION_DELETE = "delete"
ACTION_GROUP_DELETE = "group_delete"
ACTION_CREATE = "create"
ACTION_REPAIR = "repair"
ACTION_MODEL_TEARDOWN = "model_teardown"
ACTION_SCALE_DOWN = "scale_down"
ACTION_SCALE_TO_ZERO = "scale_to_zero"
ACTION_PREEMPT_MARK = "preempt_mark"
ACTION_PREWARM = "prewarm"
ACTION_FEDERATION_FAILOVER = "federation_failover"
ACTION_ROLLOUT_STEP = "rollout_step"
ACTION_ROLLBACK = "rollout_rollback"

# Denial-reason vocabulary.
DENY_LEASE = "lease-invalid"
DENY_MODEL_BUDGET = "model-budget-exhausted"
DENY_CLUSTER_BUDGET = "cluster-budget-exhausted"
DENY_STALE = "telemetry-stale"
DENY_COVERAGE = "telemetry-coverage"


class NotLeader(RuntimeError):
    """Raised when an actuation batch is attempted without a valid
    leadership fence; callers requeue and retry after the next election
    round instead of writing."""


class ActuationGovernor:
    """See module docstring. `cfg` is a `config.GovernorConfig` (None =
    permissive); `fleet` a `FleetStateAggregator` (coverage source);
    `leader` a `LeaderElection` (fencing); `store` enables
    last-known-good annotation persistence; `clock` is monotonic and
    injectable (FakeClock in the chaos sim)."""

    def __init__(
        self,
        cfg=None,
        fleet=None,
        leader=None,
        store=None,
        namespace: str = "default",
        metrics: Metrics = DEFAULT_METRICS,
        clock=time.monotonic,
    ):
        self.cfg = cfg
        self.fleet = fleet
        self.leader = leader
        self.store = store
        self.namespace = namespace
        self.metrics = metrics
        self._clock = clock
        # Flight recorder (wired by the manager): every denial is a
        # discrete decision worth replaying in an incident bundle.
        self.recorder = None
        self._lock = threading.Lock()
        # Sliding window of budgeted disruptions: (clock time, model).
        self._window: deque[tuple[float, str]] = deque()
        # model -> last-known-good replica shape:
        # {"replicas": n} or {"roles": {role: n}}.
        self._lkg: dict[str, dict] = {}

    # -- state predicates ------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self.cfg is not None and bool(self.cfg.enabled)

    @property
    def armed(self) -> bool:
        """Telemetry gating active: enabled, a coverage threshold set,
        and a fleet aggregator wired to answer it."""
        return (
            self.enabled
            and self.cfg.min_telemetry_coverage > 0
            and self.fleet is not None
        )

    def fence_valid(self) -> bool:
        return self.leader is None or self.leader.fence_valid()

    def check_fence(self) -> None:
        """Raise `NotLeader` (and count the fenced batch) unless this
        replica holds a fresh leadership lease."""
        if self.fence_valid():
            return
        self.metrics.leader_fenced_writes.inc()
        raise NotLeader(
            "actuation fenced: leadership lease not held or expired"
        )

    # -- telemetry coverage ----------------------------------------------------

    def _coverage(self, model: str) -> tuple[float | None, bool]:
        """(model endpoint-telemetry coverage, snapshot_fresh). Coverage
        None when the snapshot doesn't know the model."""
        cov, fresh = self.fleet.model_coverage(model)
        if cov is not None:
            self.metrics.governor_telemetry_coverage.set(cov, model=model)
        return cov, fresh

    # -- disruption budgets ----------------------------------------------------

    def _remaining_locked(self, model: str) -> tuple[int, int]:
        now = self._clock()
        horizon = now - self.cfg.window_seconds
        while self._window and self._window[0][0] <= horizon:
            self._window.popleft()
        used_model = sum(1 for _, m in self._window if m == model)
        return (
            self.cfg.model_disruption_budget - used_model,
            self.cfg.cluster_disruption_budget - len(self._window),
        )

    def budget_remaining(self, model: str) -> tuple[int, int]:
        """(per-model, cluster-wide) disruptions still allowed in the
        current window. Unlimited (a large sentinel) when disabled."""
        if not self.enabled:
            return (1 << 30, 1 << 30)
        with self._lock:
            return self._remaining_locked(model)

    def _consume_budget(self, model: str) -> str | None:
        """Take one budgeted disruption, or return the denial reason."""
        with self._lock:
            model_rem, cluster_rem = self._remaining_locked(model)
            if model_rem <= 0:
                return DENY_MODEL_BUDGET
            if cluster_rem <= 0:
                return DENY_CLUSTER_BUDGET
            self._window.append((self._clock(), model))
            self.metrics.governor_budget_remaining.set(
                cluster_rem - 1, scope="cluster"
            )
        return None

    def _refund_budget(self, model: str) -> None:
        """Give back the most recent budget unit taken for `model` —
        the delete it paid for never reached the API server."""
        with self._lock:
            for i in range(len(self._window) - 1, -1, -1):
                if self._window[i][1] == model:
                    del self._window[i]
                    break

    def _deny(self, action: str, model: str, reason: str) -> None:
        self.metrics.governor_denied.inc(
            action=action, model=model, reason=reason
        )
        if self.recorder is not None:
            self.recorder.record(
                flightrecorder.GOVERNOR_DENY, "governor", target=model,
                action=action, reason=reason,
            )
        logger.warning(
            "governor denied %s for model %s: %s", action, model, reason
        )

    def _allow(self, action: str, model: str) -> None:
        self.metrics.governor_actions.inc(action=action, model=model)

    # -- pod actuation ---------------------------------------------------------

    def delete_pod(
        self,
        store,
        namespace: str,
        name: str,
        *,
        model: str = "",
        reason: str = "",
        budgeted: bool = True,
    ) -> bool:
        """Fence-checked, budget-limited pod deletion. `budgeted=False`
        marks a repair of an already-broken pod (never budget-limited).
        Returns True when the pod was deleted (or already gone), False
        when the governor refused."""
        self.check_fence()
        action = ACTION_DELETE if budgeted else ACTION_REPAIR
        if self.enabled and budgeted:
            if self.armed:
                _cov, fresh = self._coverage(model)
                if not fresh:
                    # Static stability: no healthy pod dies while the
                    # control plane is flying blind.
                    self.metrics.governor_static_holds.inc(model=model)
                    self._deny(action, model, DENY_STALE)
                    return False
            denied = self._consume_budget(model)
            if denied is not None:
                self._deny(action, model, denied)
                return False
        try:
            store.delete("Pod", namespace, name)
        except NotFound:
            pass
        except Exception:
            # The delete never happened (API partition, 5xx storm past
            # the client's retries): refund the budget unit, or a storm
            # of failed writes would drain the disruption window with
            # ZERO actual disruptions and stall post-chaos convergence.
            if self.enabled and budgeted:
                self._refund_budget(model)
            raise
        self._allow(action, model)
        return True

    def delete_group(
        self,
        store,
        namespace: str,
        names: list[str],
        *,
        model: str = "",
        reason: str = "",
        budgeted: bool = True,
    ) -> bool:
        """Fence-checked, budget-limited deletion of ONE slice group's
        member pods, atomically from the budget's point of view: the
        whole group consumes a single disruption-budget unit — an
        N-host replica going away is one replica's worth of disruption,
        not N pods' worth. This is the ONLY sanctioned path for
        deleting group-member pods (`scripts/check_actuation_paths.py`
        gates callers); per-pod deletes of members would tear a group
        down one host at a time and burn N budget units doing it.

        `budgeted=False` marks whole-group repair of an already-broken
        group. Returns True when the members were deleted (missing ones
        count as already gone), False when the governor refused the
        whole group — members are never partially refused."""
        self.check_fence()
        action = ACTION_GROUP_DELETE if budgeted else ACTION_REPAIR
        if self.enabled and budgeted:
            if self.armed:
                _cov, fresh = self._coverage(model)
                if not fresh:
                    self.metrics.governor_static_holds.inc(model=model)
                    self._deny(action, model, DENY_STALE)
                    return False
            denied = self._consume_budget(model)
            if denied is not None:
                self._deny(action, model, denied)
                return False
        deleted_any = False
        for name in names:
            try:
                store.delete("Pod", namespace, name)
            except NotFound:
                continue
            except Exception:
                # Refund only while the group is still intact: once one
                # member is gone the group IS disrupted — the unit was
                # genuinely spent, and the pod plan finishes the
                # teardown on a later pass.
                if self.enabled and budgeted and not deleted_any:
                    self._refund_budget(model)
                raise
            deleted_any = True
        self._allow(action, model)
        return True

    def delete_model_pods(
        self, store, namespace: str, selector: dict, *, model: str
    ) -> int:
        """Model-deletion teardown: the user asked for the model to go,
        so budgets don't apply — but the write is still fenced."""
        self.check_fence()
        n = store.delete_all_of("Pod", namespace, selector)
        self._allow(ACTION_MODEL_TEARDOWN, model)
        return n

    def create_pod(self, store, pod: dict, *, model: str = "") -> dict:
        """Pod creation is fenced (a non-leader must not race the leader
        to create replicas) but never budgeted."""
        self.check_fence()
        created = store.create(pod)
        self._allow(ACTION_CREATE, model)
        return created

    # -- scaling ---------------------------------------------------------------

    def govern_scale(
        self, model: str, current: int, target: int
    ) -> tuple[int, str | None]:
        """Gate one replica-count change about to be written to the
        Model spec. Scale-ups and no-ops pass through; scale-downs are
        fenced, held at last-known-good while telemetry is stale, and
        refused the final step to zero when coverage is below the
        threshold. Returns (allowed_target, denial_reason|None)."""
        if target >= current or not self.enabled:
            return target, None
        action = ACTION_SCALE_TO_ZERO if target == 0 else ACTION_SCALE_DOWN
        if not self.fence_valid():
            self.metrics.leader_fenced_writes.inc()
            self._deny(action, model, DENY_LEASE)
            return current, DENY_LEASE
        if self.armed:
            cov, fresh = self._coverage(model)
            if not fresh:
                held = self._lkg_replicas(model)
                hold_at = max(current, held) if held is not None else current
                self.metrics.governor_static_holds.inc(model=model)
                self._deny(action, model, DENY_STALE)
                return hold_at, DENY_STALE
            if (
                target == 0
                and cov is not None
                and cov < self.cfg.min_telemetry_coverage
            ):
                self._deny(action, model, DENY_COVERAGE)
                # Shrinking is fine; disappearing is not: clamp to one.
                return 1, DENY_COVERAGE
        self._allow(action, model)
        return target, None

    def allow_preemption(self, model: str) -> bool:
        """Whether the capacity planner may mark this model's pods as
        preemption victims right now (fence + coverage gate)."""
        if not self.fence_valid():
            self.metrics.leader_fenced_writes.inc()
            self._deny(ACTION_PREEMPT_MARK, model, DENY_LEASE)
            return False
        if not self.armed:
            return True
        cov, fresh = self._coverage(model)
        if not fresh:
            self._deny(ACTION_PREEMPT_MARK, model, DENY_STALE)
            return False
        if cov is not None and cov < self.cfg.min_telemetry_coverage:
            self._deny(ACTION_PREEMPT_MARK, model, DENY_COVERAGE)
            return False
        self._allow(ACTION_PREEMPT_MARK, model)
        return True

    def allow_prewarm(self, model: str) -> bool:
        """Whether the capacity planner may order predictive prewarm
        replicas for this model right now. Prewarm only ADDS capacity,
        so budgets don't apply — but the order is still fenced (a
        non-leader's plan must not create pods) and refused while fleet
        telemetry is stale: a blind forecaster extrapolating from a dead
        snapshot ring must not spend chips. Denials land in
        kubeai_prewarm_denied_total."""
        if not self.fence_valid():
            self.metrics.leader_fenced_writes.inc()
            self.metrics.prewarm_denied.inc(model=model)
            self._deny(ACTION_PREWARM, model, DENY_LEASE)
            return False
        if self.armed:
            _cov, fresh = self._coverage(model)
            if not fresh:
                self.metrics.prewarm_denied.inc(model=model)
                self._deny(ACTION_PREWARM, model, DENY_STALE)
                return False
        self._allow(ACTION_PREWARM, model)
        return True

    def allow_federation_failover(self, model: str) -> bool:
        """Whether the federation planner may fail this model over to
        (or back from) another cluster right now. A failover rewrites
        where a whole model serves, so it is fenced (a non-leader must
        not rehome models) and refused while LOCAL fleet telemetry is
        stale: a cluster that cannot see its own fleet must not judge a
        peer's partition. Budgets don't apply — failover adds capacity
        elsewhere rather than destroying it here."""
        if not self.fence_valid():
            self.metrics.leader_fenced_writes.inc()
            self._deny(ACTION_FEDERATION_FAILOVER, model, DENY_LEASE)
            return False
        if self.armed:
            _cov, fresh = self._coverage(model)
            if not fresh:
                self._deny(ACTION_FEDERATION_FAILOVER, model, DENY_STALE)
                return False
        self._allow(ACTION_FEDERATION_FAILOVER, model)
        return True

    def allow_rollout_step(self, model: str) -> bool:
        """Whether the rollout controller may advance a rollout one step
        (canary admission, ramp widening, promotion) right now. A step
        deliberately replaces healthy serving capacity, so it is
        BUDGETED like any other disruption — one unit per step — on top
        of being fenced and refused while fleet telemetry is stale or
        below coverage: a judge that cannot see both versions must not
        promote either."""
        if not self.fence_valid():
            self.metrics.leader_fenced_writes.inc()
            self._deny(ACTION_ROLLOUT_STEP, model, DENY_LEASE)
            return False
        if self.armed:
            cov, fresh = self._coverage(model)
            if not fresh:
                self._deny(ACTION_ROLLOUT_STEP, model, DENY_STALE)
                return False
            if cov is not None and cov < self.cfg.min_telemetry_coverage:
                self._deny(ACTION_ROLLOUT_STEP, model, DENY_COVERAGE)
                return False
        if self.enabled:
            denied = self._consume_budget(model)
            if denied is not None:
                self._deny(ACTION_ROLLOUT_STEP, model, denied)
                return False
        self._allow(ACTION_ROLLOUT_STEP, model)
        return True

    def allow_rollback(self, model: str) -> bool:
        """Whether the rollout controller may pin the last-good hash and
        tear the condemned version down right now. Rolling back REPAIRS
        a fleet the judge already found burning budget, so disruption
        budgets don't apply (a budget-starved rollback would leave the
        bad version serving) — but the pin write is still fenced (a
        non-leader must not rewrite rollout state) and refused while
        telemetry is stale or below coverage: condemning a version takes
        evidence, and a blind judge has none."""
        if not self.fence_valid():
            self.metrics.leader_fenced_writes.inc()
            self._deny(ACTION_ROLLBACK, model, DENY_LEASE)
            return False
        if self.armed:
            cov, fresh = self._coverage(model)
            if not fresh:
                self._deny(ACTION_ROLLBACK, model, DENY_STALE)
                return False
            if cov is not None and cov < self.cfg.min_telemetry_coverage:
                self._deny(ACTION_ROLLBACK, model, DENY_COVERAGE)
                return False
        self._allow(ACTION_ROLLBACK, model)
        return True

    # -- last-known-good persistence / restart rehydration ---------------------

    def _lkg_replicas(self, model: str) -> int | None:
        entry = self._lkg.get(model)
        if not entry:
            return None
        if "replicas" in entry:
            return int(entry["replicas"])
        roles = entry.get("roles") or {}
        return sum(int(v) for v in roles.values()) if roles else None

    def note_applied(
        self,
        model: str,
        replicas: int | None = None,
        roles: dict[str, int] | None = None,
    ) -> None:
        """Record a replica count that was applied under healthy
        conditions — the static-stability floor a restarted operator
        rehydrates. Persisted as a Model annotation (best-effort) so it
        survives a control-plane crash."""
        if not self.enabled:
            return
        if self.armed:
            _cov, fresh = self._coverage(model)
            if not fresh:
                return  # never learn a "good" count from blind ticks
        entry: dict = {}
        if replicas is not None:
            entry["replicas"] = int(replicas)
        if roles:
            # Merge per-role updates (scale_role writes one role at a
            # time) so one role's apply never forgets the other's.
            prev_roles = (self._lkg.get(model) or {}).get("roles") or {}
            entry["roles"] = {
                **prev_roles, **{r: int(n) for r, n in roles.items()},
            }
        if not entry or self._lkg.get(model) == entry:
            return
        self._lkg[model] = entry
        if self.store is None:
            return
        try:
            self.store.patch_merge(
                "Model",
                self.namespace,
                model,
                {
                    "metadata": {
                        "annotations": {
                            md.LAST_KNOWN_GOOD_ANNOTATION: json.dumps(
                                entry, sort_keys=True
                            )
                        }
                    }
                },
            )
        except Exception as e:  # noqa: BLE001 — persistence is best-effort
            logger.debug("lkg annotation write failed for %s: %s", model, e)

    def rehydrate(self) -> int:
        """Re-read last-known-good annotations from every Model before
        the first tick — the restarted operator's memory of what a
        healthy fleet looked like. Returns the number of models
        rehydrated."""
        if self.store is None:
            return 0
        n = 0
        try:
            models = self.store.list("Model", self.namespace)
        except Exception as e:  # noqa: BLE001 — rehydration is best-effort
            logger.warning("governor rehydration list failed: %s", e)
            return 0
        for obj in models:
            meta = obj.get("metadata") or {}
            raw = (meta.get("annotations") or {}).get(
                md.LAST_KNOWN_GOOD_ANNOTATION
            )
            if not raw:
                continue
            try:
                entry = json.loads(raw)
            except (TypeError, json.JSONDecodeError):
                continue
            if isinstance(entry, dict) and entry:
                self._lkg[meta.get("name", "")] = entry
                n += 1
        return n


# Permissive instance for components wired without a Manager: every call
# site still ROUTES through the governor (the static gate requires it),
# it just never refuses.
PERMISSIVE = ActuationGovernor()
