"""The Model reconciler — the heart of the operator
(reference: internal/modelcontroller/model_controller.go:70-198).

Reconcile pass:
  files ConfigMap → self feature-labels → autoscaling replica bounds →
  model config resolution → [deletion: delete Pods + finalize cache] →
  [cacheProfile: reconcile cache, early-return while loading] →
  list Pods → status.replicas → pod plan (surge rollout) → adapters.

Runs against the KubeStore interface; a watch-driven `ControllerLoop`
(bottom) plays the controller-runtime role — Model events and events from
owned Pods/Jobs/PVCs enqueue the owning Model
(reference: model_controller.go:201-209 Owns(...)).
"""

from __future__ import annotations

import json
import logging
import queue
import random
import threading
import time
import traceback

from kubeai_tpu.config import System
from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Model, disagg_role_replicas
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.operator import adapters as adapters_mod
from kubeai_tpu.operator import cache as cache_mod
from kubeai_tpu.operator import files as files_mod
from kubeai_tpu.operator import governor as governor_mod
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator import slicegroup
from kubeai_tpu.operator.governor import NotLeader
from kubeai_tpu.operator.engine_client import EngineClient
from kubeai_tpu.operator.engines import render_pod, resolve_model_config
from kubeai_tpu.operator.k8s.store import Conflict, KubeStore, NotFound
from kubeai_tpu.operator.patch import apply_json_patches
from kubeai_tpu.operator.pod_plan import calculate_pod_plan

logger = logging.getLogger(__name__)

# Requeue-backoff jitter source (monkeypatchable in tests): N models
# failing on the same cause must not requeue in lockstep.
_jitter = random.random

# Model.status.conditions vocabulary — stable strings tests and docs
# (docs/concepts/resilience.md) rely on.
COND_READY = "Ready"
COND_PROGRESSING = "Progressing"
COND_DEGRADED = "Degraded"
REASON_ALL_READY = "AllReplicasReady"
REASON_NOT_READY = "ReplicasNotReady"
REASON_SCALED_TO_ZERO = "ScaledToZero"
REASON_WAITING = "WaitingForReplicas"
REASON_REPAIRING = "ReplacingFailedPods"
REASON_STABLE = "Stable"
REASON_HEALTHY = "Healthy"


class ModelReconciler:
    def __init__(
        self,
        store: KubeStore,
        cfg: System,
        engine_client: EngineClient | None = None,
        pod_exec: adapters_mod.PodExec | None = None,
        metrics: Metrics = DEFAULT_METRICS,
        clock=time.monotonic,
        wall=time.time,
        governor: governor_mod.ActuationGovernor | None = None,
        rollout=None,
    ):
        self.store = store
        self.cfg = cfg
        self.engine_client = engine_client or EngineClient()
        self.pod_exec = pod_exec
        self.metrics = metrics
        # Every destructive action this reconciler takes flows through
        # the governor (fencing + disruption budgets); the permissive
        # default keeps directly-constructed reconcilers ungoverned.
        self.governor = governor or governor_mod.PERMISSIVE
        # Progressive-rollout controller (operator/rollout.RolloutController,
        # wired by the manager): supplies the canary pod cap for models
        # with a `rollout:` block. None leaves every plan identical to
        # the classic surge rollout.
        self.rollout = rollout
        # Two clocks, both injectable: `clock` (monotonic) spaces repair
        # backoff; `wall` compares against pod creationTimestamps (the
        # store stamps wall time) for the stuck-Pending deadline.
        self._clock = clock
        self._wall = wall
        # (ns, name) -> (consecutive repair passes, last repair at
        # `clock` time): the per-model delete-and-replace backoff state.
        self._repair_state: dict[tuple[str, str], tuple[int, float]] = {}

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, namespace: str, name: str) -> None:
        try:
            model_obj = self.store.get("Model", namespace, name)
        except NotFound:
            return
        model = Model.from_dict(model_obj)

        try:
            self._reconcile(model, model_obj)
        except (cache_mod.ReturnEarly, adapters_mod.ReturnEarly):
            return
        except Conflict:
            # Stale snapshot — the next watch event re-enqueues us.
            return

    def _reconcile(self, model: Model, model_obj: dict) -> None:
        files_mod.ensure_model_files_configmap(self.store, model, model_obj)

        if self._apply_self_labels(model_obj) | self._apply_replica_bounds(
            model_obj
        ):
            model_obj = self.store.update(model_obj)
            model = Model.from_dict(model_obj)

        mcfg = resolve_model_config(model, self.cfg)
        if model.spec.cache_profile:
            mcfg.cache_dir = cache_mod.cache_dir(model)

        # Deletion path (reference: model_controller.go:112-133).
        if model.deletion_timestamp is not None:
            self.governor.delete_model_pods(
                self.store,
                model.namespace,
                {md.POD_MODEL_LABEL: model.name},
                model=model.name,
            )
            if mcfg.num_hosts > 1:
                from kubeai_tpu.operator.engines.kubeai_tpu_engine import (
                    hosts_service_name,
                )

                try:
                    self.store.delete(
                        "Service", model.namespace, hosts_service_name(model)
                    )
                except NotFound:
                    pass
            if model.spec.cache_profile:
                cache_mod.finalize_cache(
                    self.store, model, model_obj, self.cfg, mcfg
                )
            return

        if model.spec.cache_profile:
            loaded = cache_mod.reconcile_cache(
                self.store, model, model_obj, self.cfg, mcfg
            )
            self._patch_status(model, cache_loaded=loaded)
            if not loaded:
                return

        pods = self.store.list(
            "Pod", model.namespace, {md.POD_MODEL_LABEL: model.name}
        )
        # Self-healing pass: classify preempted / crash-looping /
        # stuck-Pending pods, delete-and-replace them (per-model backoff),
        # and surface the result through status.conditions. Multi-host
        # models repair in GROUP units: one broken member poisons its
        # whole slice group.
        if mcfg.num_hosts > 1:
            pods, degraded, repaired = self._group_health_pass(model, pods)
        else:
            pods, degraded, repaired = self._pod_health_pass(model, pods)
        n_all, ready = self._replica_counts(pods, mcfg)
        self._patch_status(
            model,
            replicas_all=n_all,
            replicas_ready=ready,
            conditions=self._conditions(
                model, mcfg, ready, degraded, repaired
            ),
        )

        if model.spec.disaggregation.enabled and mcfg.num_hosts <= 1:
            plan = self._plan_disagg(model, mcfg, pods)
        elif mcfg.num_hosts > 1:
            plan = self._plan_multihost(model, model_obj, mcfg, pods)
        else:
            desired_pod = render_pod(model, self.cfg, mcfg, "x")
            self._apply_model_annotations(model, desired_pod)
            if self.cfg.model_server_pods.json_patches:
                desired_pod = apply_json_patches(
                    self.cfg.model_server_pods.json_patches, desired_pod
                )
            plan = calculate_pod_plan(
                pods, model, desired_pod, self.cfg.model_rollouts.surge,
                **self._rollout_kwargs(model, desired_pod, pods),
            )
        if plan.contains_actions():
            plan.execute(self.store, model_obj, governor=self.governor)
            if plan.churned_not_ready:
                # The plan delete-and-replaced not-ready out-of-date
                # pods: extend the model's repair-backoff streak so a
                # rollout whose pods never go Ready retries on the same
                # exponential cadence as any other repair loop.
                self._note_plan_churn(model)
            pods = self.store.list(
                "Pod", model.namespace, {md.POD_MODEL_LABEL: model.name}
            )
            n_all, ready = self._replica_counts(pods, mcfg)
            self._patch_status(
                model,
                replicas_all=n_all,
                replicas_ready=ready,
                conditions=self._conditions(
                    model, mcfg, ready, degraded, repaired
                ),
            )
            return  # adapter pass runs on the next event, against fresh pods

        adapters_mod.reconcile_adapters(
            self.store, model, plan.to_remain, self.engine_client, self.pod_exec
        )

    # -- self-healing pod health pass ------------------------------------------

    def _pod_health_pass(
        self, model: Model, pods: list[dict]
    ) -> tuple[list[dict], list[tuple[str, str]], bool]:
        """Classify every pod (k8sutils.classify_pod_failure) and
        delete-and-replace the broken ones: deleting here shrinks the
        list the pod plan sees, so the SAME reconcile pass renders the
        replacements — a preempted spot replica is back under one pass,
        not one watch-event round trip per pod.

        Repeated repairs back off exponentially per model (base × 2^n,
        capped): a spec that kills every pod it renders must not thrash
        the cluster. Within backoff the broken pods are left in place
        (still reported Degraded) so the plan does not double-replace.

        Returns (surviving pods, [(pod name, reason)...], repaired?)."""
        r = self.cfg.resilience
        key = (model.namespace, model.name)
        now = self._clock()
        broken: list[tuple[dict, str]] = []
        healthy: list[dict] = []
        for p in pods:
            reason = k8sutils.classify_pod_failure(
                p,
                now=self._wall(),
                pending_deadline_s=r.pod_pending_deadline_seconds,
                restart_threshold=r.pod_restart_threshold,
            )
            if reason is None:
                healthy.append(p)
            else:
                broken.append((p, reason))
        if not broken:
            st = self._repair_state.get(key)
            if st and now - st[1] > r.repair_backoff_max_seconds:
                # Quiet past the max backoff: the failure streak is over.
                self._repair_state.pop(key, None)
                self._persist_repair_state(model, None)
            return pods, [], False
        degraded = [(p["metadata"]["name"], reason) for p, reason in broken]
        count, last = (
            self._repair_state.get(key)
            or self._rehydrate_repair_state(model)
        )
        backoff = min(
            r.repair_backoff_max_seconds,
            r.repair_backoff_base_seconds * (2.0 ** min(count, 10)),
        )
        if count and now - last < backoff:
            # Remember the rehydrated streak so a restart mid-backoff
            # keeps honoring it instead of re-reading each pass.
            self._repair_state[key] = (count, last)
            return pods, degraded, False
        for p, reason in broken:
            name = p["metadata"]["name"]
            # Repair of an already-broken pod: fenced but never
            # budget-limited (the governor counts it as `repair`).
            self.governor.delete_pod(
                self.store, model.namespace, name,
                model=model.name, reason=reason, budgeted=False,
            )
            self.metrics.controller_pod_replacements.inc(
                model=model.name, reason=reason
            )
            logger.warning(
                "pod-health: replacing pod %s/%s (%s) for model %s "
                "(repair streak %d)",
                model.namespace, name, reason, model.name, count + 1,
            )
        self._repair_state[key] = (count + 1, now)
        self._persist_repair_state(model, count + 1)
        return healthy, degraded, True

    def _group_health_pass(
        self, model: Model, pods: list[dict]
    ) -> tuple[list[dict], list[tuple[str, str]], bool]:
        """Whole-group self-healing for multi-host replicas. One broken
        member poisons its entire slice group — lockstep multihost
        cannot survive a single host restarting with a fresh address —
        so repair tears down EVERY member of an afflicted group through
        the governor's atomic group-delete (one fenced action, never
        budget-limited: the group is already broken) and lets the group
        plan recreate the full group. The per-model exponential repair
        backoff is shared with the single-host pass.

        Returns (surviving pods, [(pod name, reason)...], repaired?)."""
        r = self.cfg.resilience
        key = (model.namespace, model.name)
        now = self._clock()
        groups = slicegroup.group_pods(pods)
        singles = slicegroup.ungrouped_pods(pods)
        broken_by_group: dict[int, list[tuple[str, str]]] = {}
        for g, members in groups.items():
            for p in members:
                reason = k8sutils.classify_pod_failure(
                    p,
                    now=self._wall(),
                    pending_deadline_s=r.pod_pending_deadline_seconds,
                    restart_threshold=r.pod_restart_threshold,
                )
                if reason is not None:
                    broken_by_group.setdefault(g, []).append(
                        (p["metadata"]["name"], reason)
                    )
        if not broken_by_group:
            st = self._repair_state.get(key)
            if st and now - st[1] > r.repair_backoff_max_seconds:
                self._repair_state.pop(key, None)
                self._persist_repair_state(model, None)
            return pods, [], False
        degraded = [
            nr for _, pairs in sorted(broken_by_group.items()) for nr in pairs
        ]
        count, last = (
            self._repair_state.get(key)
            or self._rehydrate_repair_state(model)
        )
        backoff = min(
            r.repair_backoff_max_seconds,
            r.repair_backoff_base_seconds * (2.0 ** min(count, 10)),
        )
        if count and now - last < backoff:
            self._repair_state[key] = (count, last)
            return pods, degraded, False
        repaired_groups: set[int] = set()
        for g, name_reasons in sorted(broken_by_group.items()):
            members = groups[g]
            names = [p["metadata"]["name"] for p in members]
            first_name, first_reason = name_reasons[0]
            self.governor.delete_group(
                self.store, model.namespace, names,
                model=model.name, reason=first_reason, budgeted=False,
            )
            self.metrics.slicegroup_repairs.inc(
                model=model.name, reason=first_reason
            )
            # EVERY member is replaced, not just the broken ones: a
            # healthy host torn down in the cascade is charged to the
            # group's triggering reason.
            broken_reasons = dict(name_reasons)
            for name in names:
                self.metrics.controller_pod_replacements.inc(
                    model=model.name,
                    reason=broken_reasons.get(name, first_reason),
                )
            logger.warning(
                "group-health: replacing slice group g%d (%d hosts) of "
                "model %s — member %s %s (repair streak %d)",
                g, len(members), model.name, first_name, first_reason,
                count + 1,
            )
            repaired_groups.add(g)
        self._repair_state[key] = (count + 1, now)
        self._persist_repair_state(model, count + 1)
        surviving = singles + [
            p
            for g, members in sorted(groups.items())
            if g not in repaired_groups
            for p in members
        ]
        return surviving, degraded, True

    def _rehydrate_repair_state(self, model: Model) -> tuple[int, float]:
        """A restarted operator must not forget an in-flight repair
        backoff (it would instantly issue duplicate repairs): the streak
        is persisted as a Model annotation in wall time and mapped back
        onto this process's monotonic clock here."""
        raw = model.annotations.get(md.REPAIR_STATE_ANNOTATION)
        if not raw:
            return (0, 0.0)
        try:
            entry = json.loads(raw)
            count = int(entry["count"])
            last_wall = float(entry["last"])
        except (TypeError, KeyError, ValueError, json.JSONDecodeError):
            return (0, 0.0)
        elapsed = max(0.0, self._wall() - last_wall)
        return (count, self._clock() - elapsed)

    def _persist_repair_state(self, model: Model, count: int | None) -> None:
        """Write (or clear, count=None) the repair-streak annotation.
        Best-effort: a failed write only costs restart continuity."""
        value = (
            None if count is None
            else json.dumps({"count": count, "last": self._wall()})
        )
        if value is None and md.REPAIR_STATE_ANNOTATION not in model.annotations:
            return
        try:
            self.store.patch_merge(
                "Model", model.namespace, model.name,
                {"metadata": {"annotations": {
                    md.REPAIR_STATE_ANNOTATION: value,
                }}},
            )
        except (NotFound, Conflict):
            pass

    # -- progressive-rollout seams ---------------------------------------------

    def _rollout_kwargs(
        self, model: Model, desired_pod: dict, pods: list[dict]
    ) -> dict:
        """Keyword seams for `calculate_pod_plan`. The pinned hash comes
        straight off the Model annotation — a rollback written by a
        previous leader keeps steering the plan even when no rollout
        controller is wired here — the canary cap comes from the rollout
        controller, and churn pacing rides the model's repair-backoff
        streak either way."""
        kw: dict = {}
        pinned = model.annotations.get(md.ROLLOUT_PINNED_HASH_ANNOTATION)
        if pinned:
            kw["pinned_hash"] = pinned
        if self.rollout is not None:
            # Always consulted — pod_cap doubles as the controller's
            # hash-drift sensor — but it returns None (no cap) while a
            # pin is steering the plan or no rollout is in flight.
            cap = self.rollout.pod_cap(model, desired_pod, pods)
            if cap is not None:
                kw["max_new"] = cap
        budget = self._churn_pacing(model)
        if budget is not None:
            kw["recreate_budget"] = budget
        return kw

    def _churn_pacing(self, model: Model) -> int | None:
        """`recreate_budget` for the pod plan: 0 while the model's
        repair-backoff window is open (not-ready out-of-date pods wait
        out the same backoff the health pass honors), None otherwise
        (the plan's own max(1, surge) per-pass default)."""
        st = self._repair_state.get((model.namespace, model.name))
        if not st:
            return None
        count, last = st
        r = self.cfg.resilience
        backoff = min(
            r.repair_backoff_max_seconds,
            r.repair_backoff_base_seconds * (2.0 ** min(count, 10)),
        )
        if count and self._clock() - last < backoff:
            return 0
        return None

    def _note_plan_churn(self, model: Model) -> None:
        """Count a plan pass that churned not-ready out-of-date pods as
        one repair round: shares the exponential backoff streak with the
        pod-health pass."""
        key = (model.namespace, model.name)
        count, _last = (
            self._repair_state.get(key)
            or self._rehydrate_repair_state(model)
        )
        self._repair_state[key] = (count + 1, self._clock())
        self._persist_repair_state(model, count + 1)

    def _conditions(
        self,
        model: Model,
        mcfg,
        ready: int,
        degraded: list[tuple[str, str]],
        repaired: bool,
    ) -> list[dict]:
        """Ready / Progressing / Degraded with stable reasons (module
        constants). `degraded` is the pod-health pass's classification
        list; `repaired` marks that replacements were issued this pass."""
        if model.spec.disaggregation.enabled and mcfg.num_hosts <= 1:
            desired = sum(
                disagg_role_replicas(model, role) for role in md.DISAGG_ROLES
            )
        else:
            desired = model.spec.replicas or 0
        conds = []
        if desired == 0:
            conds.append(_cond(COND_READY, False, REASON_SCALED_TO_ZERO,
                               "0 replicas desired"))
        elif ready >= desired:
            conds.append(_cond(COND_READY, True, REASON_ALL_READY,
                               f"{ready}/{desired} replicas ready"))
        else:
            conds.append(_cond(COND_READY, False, REASON_NOT_READY,
                               f"{ready}/{desired} replicas ready"))
        if repaired:
            conds.append(_cond(
                COND_PROGRESSING, True, REASON_REPAIRING,
                "replacing failed pods: " + _degraded_msg(degraded),
            ))
        elif ready < desired:
            conds.append(_cond(COND_PROGRESSING, True, REASON_WAITING,
                               f"{ready}/{desired} replicas ready"))
        else:
            conds.append(_cond(COND_PROGRESSING, False, REASON_STABLE,
                               "replica set stable"))
        if degraded:
            conds.append(_cond(
                COND_DEGRADED, True, degraded[0][1], _degraded_msg(degraded),
            ))
        else:
            conds.append(_cond(COND_DEGRADED, False, REASON_HEALTHY,
                               "all pods healthy"))
        return conds

    # -- helpers --------------------------------------------------------------

    @staticmethod
    def _replica_counts(pods: list[dict], mcfg) -> tuple[int, int]:
        """status.replicas in REPLICA units. Multi-host: a replica exists
        when its pod group is complete and is ready only when EVERY host
        is ready (the mesh needs all of them)."""
        if mcfg.num_hosts <= 1:
            ready = sum(1 for p in pods if k8sutils.pod_is_ready(p))
            return len(pods), ready
        groups: dict[str, list[dict]] = {}
        for p in pods:
            g = k8sutils.get_label(p, md.POD_GROUP_LABEL)
            groups.setdefault(g or "?", []).append(p)
        complete = [
            ps for ps in groups.values() if len(ps) >= mcfg.num_hosts
        ]
        ready = sum(
            1
            for ps in complete
            if all(k8sutils.pod_is_ready(p) for p in ps)
        )
        return len(complete), ready

    def _plan_multihost(self, model, model_obj, mcfg, pods):
        """Multi-host replicas: ensure the headless Service, render pod
        GROUPS (one Pod per host), and diff by fixed name (no reference
        analog — one-Pod-per-replica there; see engines/kubeai_tpu_engine
        multi-host section)."""
        from kubeai_tpu.operator.engines.kubeai_tpu_engine import (
            kubeai_tpu_host_pods,
            multihost_service,
        )
        from kubeai_tpu.operator.pod_plan import calculate_group_pod_plan

        svc = multihost_service(model)
        try:
            self.store.get("Service", model.namespace, svc["metadata"]["name"])
        except NotFound:
            k8sutils.set_owner_reference(model_obj, svc)
            try:
                self.store.create(svc)
            except Conflict:
                pass

        def render_group(g: int) -> list[dict]:
            rendered = []
            for pod in kubeai_tpu_host_pods(model, self.cfg, mcfg, g):
                self._apply_model_annotations(model, pod)
                if self.cfg.model_server_pods.json_patches:
                    pod = apply_json_patches(
                        self.cfg.model_server_pods.json_patches, pod
                    )
                rendered.append(pod)
            return rendered

        cap = None
        if self.rollout is not None:
            # Canary pacing in GROUP units: at most `cap` groups that
            # are stale only by hash drift roll per step; broken groups
            # always repair atomically regardless.
            cap = self.rollout.group_cap(model)
        plan = calculate_group_pod_plan(
            pods, model, render_group, mcfg.num_hosts,
            max_hash_recreates=cap,
        )
        if self.rollout is not None and plan.rolled_stale_groups:
            self.rollout.note_group_step(model, plan.rolled_stale_groups)
        return plan

    def _plan_disagg(self, model, mcfg, pods):
        """Disaggregated prefill/decode: render one desired pod PER ROLE
        (role label + --role flag) and diff each role's pod set against
        its own replica count — the autoscaler's per-role annotation,
        clamped to the CRD bounds. spec.replicas stays the unified knob
        and is ignored here; stray unified/unknown-role pods (a model
        that just flipped disaggregation on) are deleted."""
        import copy as _copy

        from kubeai_tpu.operator.engines.kubeai_tpu_engine import (
            kubeai_tpu_pod,
        )
        from kubeai_tpu.operator.pod_plan import PodPlan, calculate_pod_plan

        by_role: dict[str, list[dict]] = {}
        strays: list[dict] = []
        for p in pods:
            role = k8sutils.get_label(p, md.POD_ROLE_LABEL)
            if role in md.DISAGG_ROLES:
                by_role.setdefault(role, []).append(p)
            else:
                strays.append(p)

        to_create: list[dict] = []
        to_delete: list[dict] = list(strays)
        to_remain: list[dict] = []
        churned = 0
        details = [
            f"deleting roleless pod {p['metadata']['name']}" for p in strays
        ]
        for role in md.DISAGG_ROLES:
            desired_pod = kubeai_tpu_pod(model, self.cfg, mcfg, "x", role=role)
            self._apply_model_annotations(model, desired_pod)
            if self.cfg.model_server_pods.json_patches:
                desired_pod = apply_json_patches(
                    self.cfg.model_server_pods.json_patches, desired_pod
                )
            # calculate_pod_plan reads spec.replicas: hand it a copy of
            # the model with the ROLE's replica count in that seat.
            role_model = _copy.deepcopy(model)
            role_model.spec.replicas = disagg_role_replicas(model, role)
            # Disaggregated roles don't canary (each role renders its
            # own hash, so there is no single version to judge), but
            # churn pacing still applies.
            plan = calculate_pod_plan(
                by_role.get(role, []), role_model, desired_pod,
                self.cfg.model_rollouts.surge,
                recreate_budget=self._churn_pacing(model),
            )
            to_create += plan.to_create
            to_delete += plan.to_delete
            to_remain += plan.to_remain
            churned += plan.churned_not_ready
            details += [f"{role}: {d}" for d in plan.details]
        return PodPlan(
            model=model,
            to_create=to_create,
            to_delete=to_delete,
            to_remain=to_remain,
            details=details,
            churned_not_ready=churned,
        )

    def _apply_self_labels(self, model_obj: dict) -> bool:
        """Feature labels on the Model itself
        (reference: model_controller.go:374-407)."""
        labels = model_obj["metadata"].setdefault("labels", {})
        features = set((model_obj.get("spec") or {}).get("features") or [])
        changed = False
        prefix = md.MODEL_FEATURE_LABEL_DOMAIN + "/"
        for key in list(labels):
            if key.startswith(prefix) and key[len(prefix):] not in features:
                del labels[key]
                changed = True
        for f in features:
            if labels.get(prefix + f) != "true":
                labels[prefix + f] = "true"
                changed = True
        return changed

    def _apply_replica_bounds(self, model_obj: dict) -> bool:
        """Clamp spec.replicas to [minReplicas, maxReplicas]
        (reference: model_controller.go:357-372)."""
        spec = model_obj.setdefault("spec", {})
        mn = int(spec.get("minReplicas", 0) or 0)
        mx = spec.get("maxReplicas")
        replicas = spec.get("replicas")
        if replicas is None or replicas < mn:
            # ungoverned: clamp UP to the CRD minReplicas floor — never
            # shrinks capacity (scripts/check_actuation_paths.py)
            spec["replicas"] = mn
            return True
        if mx is not None and replicas > mx:
            # ungoverned: clamp to the user's own CRD maxReplicas bound
            spec["replicas"] = mx
            return True
        return False

    def _apply_model_annotations(self, model: Model, pod: dict) -> None:
        """Copy address-override annotations when enabled — the integration-
        test seam for fake backends (reference: model_controller.go:228-248,
        test/integration/utils_test.go:150-159)."""
        if not self.cfg.allow_pod_address_override:
            return
        for key in (md.MODEL_POD_IP_ANNOTATION, md.MODEL_POD_PORT_ANNOTATION):
            if key in model.annotations:
                pod["metadata"].setdefault("annotations", {})[key] = (
                    model.annotations[key]
                )

    def _patch_status(self, model: Model, **kwargs) -> None:
        patch: dict = {"status": {}}
        if "replicas_all" in kwargs or "replicas_ready" in kwargs:
            patch["status"]["replicas"] = {}
            if "replicas_all" in kwargs:
                patch["status"]["replicas"]["all"] = kwargs["replicas_all"]
            if "replicas_ready" in kwargs:
                patch["status"]["replicas"]["ready"] = kwargs["replicas_ready"]
        if "cache_loaded" in kwargs:
            patch["status"]["cache"] = {"loaded": kwargs["cache_loaded"]}
        if "conditions" in kwargs:
            # Replaced wholesale (list merge would interleave stale
            # entries); no timestamps — deterministic content only.
            patch["status"]["conditions"] = kwargs["conditions"]
        try:
            self.store.patch_merge("Model", model.namespace, model.name, patch)
        except NotFound:
            pass


def _cond(type_: str, status: bool, reason: str, message: str) -> dict:
    return {
        "type": type_,
        "status": "True" if status else "False",
        "reason": reason,
        "message": message,
    }


def _degraded_msg(degraded: list[tuple[str, str]]) -> str:
    return "; ".join(f"{name}: {reason}" for name, reason in degraded)


class ControllerLoop:
    """Watch-driven reconcile loop (controller-runtime equivalent)."""

    WATCHED_KINDS = ("Model", "Pod", "Job", "PersistentVolumeClaim")

    def __init__(self, reconciler: ModelReconciler):
        self.reconciler = reconciler
        self.store = reconciler.store
        self._events = self.store.watch(self.WATCHED_KINDS)
        self._queue: "queue.Queue[tuple[str, str] | None]" = queue.Queue()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # (ns, name) -> consecutive reconcile failures (backoff exponent).
        self._failures: dict[tuple[str, str], int] = {}

    def start(self) -> None:
        self._threads = [
            threading.Thread(target=self._watch_loop, daemon=True),
            threading.Thread(target=self._work_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()
        # Initial sync: reconcile everything already in the store.
        for obj in self.store.list("Model"):
            self._enqueue_obj(obj)

    def stop(self) -> None:
        self._stop.set()
        self._events.put(None)
        self._queue.put(None)
        for t in self._threads:
            t.join(timeout=5)

    def resync(self) -> None:
        """Re-enqueue every live Model — called on leadership
        acquisition so work that was fenced while not leader converges
        immediately instead of waiting for the next watch event."""
        try:
            for obj in self.store.list("Model"):
                self._enqueue_obj(obj)
        except Exception:
            logger.warning("leader resync failed", exc_info=True)

    def enqueue(self, namespace: str, name: str) -> None:
        """Ask for a reconcile of one Model outside the watch stream —
        the rollout controller calls this after advancing a step (the
        raised canary cap would otherwise wait for the next event)."""
        self._queue.put((namespace, name))

    def _enqueue_obj(self, obj: dict) -> None:
        kind = obj.get("kind")
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace", "default")
        if kind == "Model":
            self._queue.put((ns, meta.get("name", "")))
            return
        # Owned objects map back to their Model via the `model` label or
        # owner references.
        model_name = ((meta.get("labels") or {}).get(md.POD_MODEL_LABEL))
        if model_name:
            self._queue.put((ns, model_name))
            return
        for ref in meta.get("ownerReferences") or []:
            if ref.get("kind") == "Model":
                self._queue.put((ns, ref.get("name", "")))

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            item = self._events.get()
            if item is None:
                return
            _event, obj = item
            if _event == "RELIST":
                # Watch gap (410 Gone relist): deletions in the gap left
                # no event — re-enqueue every live Model so reconciles
                # converge from the fresh snapshot.
                try:
                    for m in self.store.list("Model"):
                        meta = m.get("metadata") or {}
                        self._queue.put(
                            (meta.get("namespace", "default"),
                             meta.get("name", ""))
                        )
                except Exception:
                    logger.warning("relist resync failed", exc_info=True)
                continue
            self._enqueue_obj(obj)

    def _work_loop(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            if item is None:
                return
            ns, name = item
            # Coalesce duplicate keys waiting in the queue.
            pending = []
            try:
                while True:
                    nxt = self._queue.get_nowait()
                    if nxt is None:
                        return
                    if nxt != (ns, name):
                        pending.append(nxt)
            except queue.Empty:
                pass
            for p in pending:
                self._queue.put(p)
            try:
                self.reconciler.reconcile(ns, name)
                if self._failures.pop((ns, name), None) is not None:
                    self._metrics.controller_consecutive_failures.set(
                        0, model=name
                    )
            except NotLeader:
                # Not an error: this replica keeps its caches warm but
                # never actuates. The work requeues with backoff; the
                # leadership-acquisition resync converges it promptly.
                self._requeue_after_backoff(ns, name, count_failure=False)
            except Exception:
                logger.error(
                    "reconcile %s/%s failed:\n%s", ns, name, traceback.format_exc()
                )
                self._requeue_after_backoff(ns, name)

    @property
    def _metrics(self) -> Metrics:
        return getattr(self.reconciler, "metrics", DEFAULT_METRICS)

    def _backoff_delay(self, n: int) -> float:
        """Exponential backoff for the n-th consecutive failure, JITTERED
        over [0.5, 1.0]× — N models failing on the same cause (a bad
        image tag, a quota hit) would otherwise requeue in lockstep and
        hammer the apiserver/engines in synchronized waves."""
        base = min(30.0, 0.5 * (2.0 ** min(n, 10)))
        return base * (0.5 + 0.5 * _jitter())

    def _requeue_after_backoff(
        self, ns: str, name: str, count_failure: bool = True
    ) -> None:
        """Failed reconciles retry with exponential backoff instead of
        waiting for the next watch event (which may never come — e.g. an
        engine 409 while adapter requests drain). Parity with
        controller-runtime's requeue-on-error semantics (the reference's
        Reconcile returns err → backoff requeue). `count_failure=False`
        requeues without growing the failure streak (fenced non-leader
        reconciles are healthy, not failing)."""
        n = self._failures.get((ns, name), 0)
        if count_failure:
            # Cap the stored count: 2.0**1024 raises OverflowError, which
            # would escape the worker's except handler and kill the
            # reconcile loop.
            self._failures[(ns, name)] = min(n + 1, 16)
            self._metrics.controller_consecutive_failures.set(
                self._failures[(ns, name)], model=name
            )
        # Fenced requeues pace at a fixed modest delay (the n=2 rung)
        # rather than the hot first-failure rung: a standby replica
        # re-checks leadership every couple of seconds per model.
        delay = self._backoff_delay(n if count_failure else max(n, 2))

        def _put():
            if not self._stop.is_set():
                self._queue.put((ns, name))

        t = threading.Timer(delay, _put)
        t.daemon = True
        t.start()
