"""Pod renderer for the in-tree TPU engine (the reference has no analog —
its TPU path launches stock vLLM-TPU images, reference:
charts/kubeai/values.yaml:48 + values-gke.yaml:18-41; here the engine is
kubeai_tpu.engine.server running on the slice).

TPU-specific rendering:
  - `google.com/tpu` requests/limits from the resource profile
  - ICI topology flows to the engine via TPU_TOPOLOGY env (mesh shape)
  - generous startup probe budget for sharded weight loading (the
    reference gives vLLM 3h — reference: engine_vllm.go:101-107)
"""

from __future__ import annotations

from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator.engines.common import (
    ModelConfig,
    base_pod,
    files_volume,
    model_env,
    source_env_and_volumes,
)

PORT = 8000


def kubeai_tpu_pod(
    model: Model, cfg: System, mcfg: ModelConfig, suffix: str,
    role: str = "",
) -> dict:
    """`role` renders one pod of a disaggregated group: the engine gets
    `--role prefill|decode` (+ transfer limits from the CRD block) and
    the pod carries the model-role label the LB's per-role endpoint
    groups key on. "" renders the classic unified replica."""
    pod = base_pod(model, cfg, mcfg, suffix)
    env, volumes, mounts = source_env_and_volumes(model, cfg, mcfg)
    fvols, fmounts = files_volume(model, f"model-{model.name}-files")
    volumes += fvols
    mounts += fmounts

    args = [
        "--model-url", model.spec.url,
        "--served-model-name", model.name,
        "--port", str(PORT),
    ]
    if mcfg.tpu_topology:
        args += ["--tpu-topology", mcfg.tpu_topology]
    if mcfg.cache_dir:
        args += ["--model-dir", mcfg.cache_dir]
    # Speculative decoding from first-class spec fields (CRD validates
    # draftUrl implies speculativeTokens >= 1 and the KubeAITPU engine).
    if model.spec.speculative_tokens > 0:
        args += ["--speculate", str(model.spec.speculative_tokens)]
    if model.spec.draft_url:
        args += ["--draft-url", model.spec.draft_url]
    # Graceful drain: CRD drainTimeoutSeconds, defaulted from the system
    # config resilience block. The same number drives the engine's
    # --drain-timeout, the preStop drain trigger, and (plus slack for
    # the final flush) terminationGracePeriodSeconds — so kubelet's KILL
    # can never race the in-flight completions the engine is waiting on.
    drain_timeout = int(
        model.spec.drain_timeout_seconds
        or cfg.resilience.drain_timeout_seconds
    )
    args += ["--drain-timeout", str(drain_timeout)]
    # Step watchdog: a hung device step flips /health and exits nonzero
    # so kubelet restarts the pod long before the router's circuit
    # breaker could accumulate response-header timeouts.
    args += [
        "--watchdog-timeout",
        f"{cfg.resilience.watchdog_timeout_seconds:g}",
    ]
    # SLO scheduling policy from the CRD scheduling: block (validated to
    # the engine's priority classes at admission).
    sched = model.spec.scheduling
    if sched.default_priority:
        args += ["--default-priority", sched.default_priority]
    if sched.max_deadline_ms:
        args += ["--max-deadline-ms", str(sched.max_deadline_ms)]
    if sched.queue_shares:
        args += [
            "--queue-shares",
            ",".join(
                f"{cls}={share:g}"
                for cls, share in sorted(sched.queue_shares.items())
            ),
        ]
    # Disaggregated serving role (CRD disaggregation: block): the engine
    # flag plus the pod label the LB's role groups key on.
    if role:
        from kubeai_tpu.crd import metadata as md

        args += ["--role", role]
        dis = model.spec.disaggregation
        if dis.max_transfer_mb:
            args += ["--max-transfer-mb", str(dis.max_transfer_mb)]
        if dis.transfer_timeout_seconds:
            args += [
                "--transfer-timeout", f"{dis.transfer_timeout_seconds:g}",
            ]
        pod["metadata"]["labels"][md.POD_ROLE_LABEL] = role
    # Cluster KV sharing (CRD kvSharing: block): the engine publishes
    # held page-hash chains, serves peer page exports, and pulls
    # common-prefix pages from the proxy-suggested X-KV-Source peer.
    # --kv-sharing implies --prefix-cache engine-side.
    kvs = model.spec.kv_sharing
    if kvs.enabled:
        args += ["--kv-sharing"]
        if kvs.fetch_timeout_seconds:
            args += ["--kv-fetch-timeout", f"{kvs.fetch_timeout_seconds:g}"]
        if kvs.max_transfer_mb:
            args += ["--max-transfer-mb", str(kvs.max_transfer_mb)]
        if kvs.spill_url:
            args += ["--kv-spill-url", kvs.spill_url]
    # KV-cache storage dtype (CRD kvCache: block): int8 halves resident
    # KV bytes (~2x slot capacity at equal HBM) and every KV transfer.
    if model.spec.kv_cache.enabled():
        args += ["--kv-dtype", model.spec.kv_cache.dtype]
    # Overlapped step pipeline (CRD engineStep: block): dispatch chunk
    # N+1 before reaping chunk N so host work hides behind device
    # compute. Unset = engine default (auto: on where the topology
    # allows, synchronous for lockstep multihost / pipeline parallelism).
    if model.spec.engine_step.enabled():
        args += ["--step-overlap", model.spec.engine_step.overlap]
    # Engine snapshot/restore (CRD coldStart: block): boot restores the
    # post-conversion param tree + compilation cache from the snapshot
    # store instead of re-running HF conversion and XLA compilation.
    cold = model.spec.cold_start
    if cold.enabled:
        args += ["--snapshot-url", cold.snapshot_url]
        if not cold.publish:
            args += ["--snapshot-no-publish"]
    # Adapters are NOT baked into the spec: they hot-swap through the
    # /v1/load_lora_adapter admin API (see operator/adapters.py), so adapter
    # changes never trigger a pod rollout.
    args += list(model.spec.args)

    env.append({"name": "TPU_TOPOLOGY", "value": mcfg.tpu_topology or "1x1"})
    env.append({"name": "TPU_CHIPS", "value": str(mcfg.tpu_chips or 1)})
    env += model_env(model)

    container = {
        "name": "server",
        "image": mcfg.image,
        "args": args,
        "env": env,
        "ports": [{"containerPort": PORT, "name": "http"}],
        "resources": {"requests": mcfg.requests, "limits": mcfg.limits},
        "volumeMounts": mounts,
        # Sharded weight streaming into slice HBM can take a long time on
        # first boot (no cache); same 3h ceiling the reference grants vLLM.
        # Snapshot-restore boots skip conversion and most compilation, so
        # the budget tightens to 30min: a replica stuck that long is
        # broken and should be restarted, not waited on for 3h. (The
        # first full-load boot of a model still fits — publish happens
        # after Ready, and the fallback path only re-runs conversion.)
        "startupProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
            "failureThreshold": 180 if cold.enabled else 1080,
        },
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 30,
            "failureThreshold": 3,
        },
        # preStop fires BEFORE kubelet sends SIGTERM: the drain endpoint
        # flips /health to 503 (LB ejection) and stops admission while
        # routing still points here — no request lands on a dying Pod.
        # (kubelet's httpGet hook can only GET; the server accepts GET
        # /v1/drain for exactly this.)
        "lifecycle": {
            "preStop": {
                "httpGet": {"path": "/v1/drain", "port": PORT},
            },
        },
    }
    if cfg.model_server_pods.container_security_context:
        container["securityContext"] = cfg.model_server_pods.container_security_context
    if model.spec.env_from:
        container["envFrom"] = list(model.spec.env_from)

    pod["spec"]["containers"] = [container]
    pod["spec"]["volumes"] = volumes
    # Drain budget + 15s slack for the terminated-straggler flush and
    # process teardown; kubelet's default 30s would KILL mid-drain for
    # any model configured above it.
    pod["spec"]["terminationGracePeriodSeconds"] = drain_timeout + 15
    pod["metadata"]["annotations"]["model-pod-port"] = str(PORT)
    return pod


# ---- multi-host replicas -----------------------------------------------------
#
# A v5e slice larger than 8 chips spans hosts; every host runs the same
# engine process and jax.distributed joins them into one mesh over DCN
# (engine flags --dcn-coordinator/--process-id/--num-processes,
# kubeai_tpu/engine/server.py). The operator's unit becomes a POD GROUP:
# one Pod per host with a stable hostname under a headless Service, host
# 0 as coordinator and the only HTTP-serving endpoint. No reference
# analog (strict one-Pod-per-replica, pod_plan.go:28-156).

DCN_PORT = 8476


def _dns_label(s: str) -> str:
    """Model names are DNS SUBDOMAINS (dots allowed, e.g.
    llama-3.1-8b...), but Service names and Pod hostnames are DNS
    LABELS. Sanitize dots to dashes WITH a short hash of the original —
    plain replacement would collide "llama-3.1" with "llama-3-1" in the
    same namespace."""
    if "." not in s:
        return s
    import hashlib

    digest = hashlib.sha256(s.encode()).hexdigest()[:6]
    return f"{s.replace('.', '-')}-{digest}"


def hosts_service_name(model: Model) -> str:
    return f"model-{_dns_label(model.name)}-hosts"


def multihost_service(model: Model) -> dict:
    """Headless Service giving host Pods stable DNS for the coordinator."""
    from kubeai_tpu.crd import metadata as md

    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": hosts_service_name(model),
            "namespace": model.namespace,
            "labels": {md.POD_MODEL_LABEL: model.name},
        },
        "spec": {
            "clusterIP": "None",
            # Pods can only become ready AFTER jax.distributed joins all
            # hosts, and hosts join by resolving each other's per-pod DNS
            # — which must therefore be published for NOT-ready Pods, or
            # the group deadlocks at startup (the StatefulSet peer-
            # discovery pattern).
            "publishNotReadyAddresses": True,
            "selector": {md.POD_MODEL_LABEL: model.name},
            "ports": [{"name": "dcn", "port": DCN_PORT}],
        },
    }


def kubeai_tpu_host_pods(
    model: Model, cfg: System, mcfg: ModelConfig, group: int
) -> list[dict]:
    """Render one replica group: num_hosts Pods with fixed names (stable
    hostnames are part of the coordinator address, so generateName-style
    random suffixes can't be used)."""
    from kubeai_tpu.crd import metadata as md

    svc = hosts_service_name(model)
    label_name = _dns_label(model.name)
    coord_host = f"model-{label_name}-g{group}-h0"
    coordinator = f"{coord_host}.{svc}.{model.namespace}.svc:{DCN_PORT}"
    pods = []
    for h in range(mcfg.num_hosts):
        pod = kubeai_tpu_pod(model, cfg, mcfg, f"g{group}-h{h}")
        spec = pod["spec"]
        spec["hostname"] = f"model-{label_name}-g{group}-h{h}"
        spec["subdomain"] = svc
        c = spec["containers"][0]
        c["args"] += [
            "--dcn-coordinator", coordinator,
            "--process-id", str(h),
            "--num-processes", str(mcfg.num_hosts),
        ]
        c["env"] += [
            {"name": "TPU_COORDINATOR", "value": coordinator},
            {"name": "TPU_PROCESS_ID", "value": str(h)},
            {"name": "TPU_PROCESS_COUNT", "value": str(mcfg.num_hosts)},
            {
                "name": "TPU_WORKER_HOSTNAMES",
                "value": ",".join(
                    f"model-{label_name}-g{group}-h{i}.{svc}"
                    for i in range(mcfg.num_hosts)
                ),
            },
        ]
        if model.spec.sharding.mesh:
            # Logical mesh axis sizes (data/fsdp/tp) for the engine's
            # SpecLayout; rendered in a stable axis order so the pod
            # hash doesn't churn on dict ordering.
            c["env"].append({
                "name": "TPU_MESH",
                "value": ",".join(
                    f"{axis}={model.spec.sharding.mesh[axis]}"
                    for axis in ("data", "fsdp", "tp")
                    if axis in model.spec.sharding.mesh
                ),
            })
        labels = pod["metadata"]["labels"]
        labels[md.POD_GROUP_LABEL] = str(group)
        labels[md.POD_HOST_LABEL] = str(h)
        labels[md.POD_GROUP_SIZE_LABEL] = str(mcfg.num_hosts)
        if h > 0:
            # Workers join the mesh but never serve HTTP: the LB must not
            # route to them.
            pod["metadata"]["annotations"][
                md.MODEL_POD_SERVING_ANNOTATION
            ] = "false"
        pods.append(pod)
    return pods
