"""Pod renderer for the in-tree TPU engine (the reference has no analog —
its TPU path launches stock vLLM-TPU images, reference:
charts/kubeai/values.yaml:48 + values-gke.yaml:18-41; here the engine is
kubeai_tpu.engine.server running on the slice).

TPU-specific rendering:
  - `google.com/tpu` requests/limits from the resource profile
  - ICI topology flows to the engine via TPU_TOPOLOGY env (mesh shape)
  - generous startup probe budget for sharded weight loading (the
    reference gives vLLM 3h — reference: engine_vllm.go:101-107)
"""

from __future__ import annotations

from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator.engines.common import (
    ModelConfig,
    base_pod,
    files_volume,
    model_env,
    source_env_and_volumes,
)

PORT = 8000


def kubeai_tpu_pod(model: Model, cfg: System, mcfg: ModelConfig, suffix: str) -> dict:
    pod = base_pod(model, cfg, mcfg, suffix)
    env, volumes, mounts = source_env_and_volumes(model, cfg, mcfg)
    fvols, fmounts = files_volume(model, f"model-{model.name}-files")
    volumes += fvols
    mounts += fmounts

    args = [
        "--model-url", model.spec.url,
        "--served-model-name", model.name,
        "--port", str(PORT),
    ]
    if mcfg.tpu_topology:
        args += ["--tpu-topology", mcfg.tpu_topology]
    if mcfg.cache_dir:
        args += ["--model-dir", mcfg.cache_dir]
    # Adapters are NOT baked into the spec: they hot-swap through the
    # /v1/load_lora_adapter admin API (see operator/adapters.py), so adapter
    # changes never trigger a pod rollout.
    args += list(model.spec.args)

    env.append({"name": "TPU_TOPOLOGY", "value": mcfg.tpu_topology or "1x1"})
    env.append({"name": "TPU_CHIPS", "value": str(mcfg.tpu_chips or 1)})
    env += model_env(model)

    container = {
        "name": "server",
        "image": mcfg.image,
        "args": args,
        "env": env,
        "ports": [{"containerPort": PORT, "name": "http"}],
        "resources": {"requests": mcfg.requests, "limits": mcfg.limits},
        "volumeMounts": mounts,
        # Sharded weight streaming into slice HBM can take a long time on
        # first boot (no cache); same 3h ceiling the reference grants vLLM.
        "startupProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
            "failureThreshold": 1080,
        },
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 30,
            "failureThreshold": 3,
        },
    }
    if cfg.model_server_pods.container_security_context:
        container["securityContext"] = cfg.model_server_pods.container_security_context
    if model.spec.env_from:
        container["envFrom"] = list(model.spec.env_from)

    pod["spec"]["containers"] = [container]
    pod["spec"]["volumes"] = volumes
    pod["metadata"]["annotations"]["model-pod-port"] = str(PORT)
    return pod
