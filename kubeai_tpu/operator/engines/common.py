"""Model-config resolution and source handling shared by engine renderers.

Mirrors:
  - profile multiplication + image lookup
    (reference: internal/modelcontroller/model_controller.go:257-355)
  - model source URL parsing with per-scheme Pod additions
    (reference: internal/modelcontroller/model_source.go:82-271)
"""

from __future__ import annotations

import dataclasses
from urllib.parse import parse_qs, urlparse

from kubeai_tpu.config import System, ResourceProfile
from kubeai_tpu.crd.model import Model
from kubeai_tpu.utils.units import multiply_quantity


class ResolutionError(ValueError):
    pass


@dataclasses.dataclass
class ModelSource:
    """Parsed spec.url (reference: internal/modelcontroller/model_source.go:231-271)."""

    scheme: str
    ref: str  # repo id / bucket path / pvc path / ollama model
    params: dict[str, str]

    @property
    def pull_policy(self) -> str:  # ollama ?pull=
        return self.params.get("pull", "")

    @property
    def insecure(self) -> bool:
        return self.params.get("insecure", "") in ("true", "1")

    @property
    def named_model(self) -> str | None:  # ?model= override
        return self.params.get("model")


def parse_model_source(url: str) -> ModelSource:
    parsed = urlparse(url)
    if not parsed.scheme:
        raise ResolutionError(f"model url {url!r} missing scheme")
    ref = (parsed.netloc + parsed.path).strip("/")
    params = {k: v[-1] for k, v in parse_qs(parsed.query).items()}
    return ModelSource(scheme=parsed.scheme, ref=ref, params=params)


@dataclasses.dataclass
class ModelConfig:
    """Everything a renderer needs (resolved profile × count + image + source)."""

    image: str
    requests: dict[str, str]
    limits: dict[str, str]
    node_selector: dict[str, str]
    affinity: dict | None
    tolerations: list[dict]
    scheduler_name: str
    runtime_class_name: str
    profile_name: str
    profile_count: int
    source: ModelSource
    # Scale: replica bounds after autoscaling clamping
    cache_dir: str = ""  # set when cacheProfile in play
    num_hosts: int = 1  # Pods per replica (multi-host TPU slices)

    @property
    def tpu_topology(self) -> str | None:
        from kubeai_tpu.config.system import TPU_TOPOLOGY_SELECTOR

        return self.node_selector.get(TPU_TOPOLOGY_SELECTOR)

    @property
    def tpu_chips(self) -> int:
        v = self.limits.get("google.com/tpu") or self.requests.get("google.com/tpu")
        return int(v) if v else 0


def resolve_model_config(model: Model, cfg: System) -> ModelConfig:
    """Profile lookup+multiplication and engine-image selection
    (reference: internal/modelcontroller/model_controller.go:257-355)."""
    profile_name, count = "", 1
    if model.spec.resource_profile:
        name, _, cnt = model.spec.resource_profile.partition(":")
        profile_name, count = name, int(cnt or "1")
    profile = ResourceProfile()
    if profile_name:
        if profile_name not in cfg.resource_profiles:
            raise ResolutionError(
                f"resourceProfile {profile_name!r} not found in system config"
            )
        profile = cfg.resource_profiles[profile_name]

    requests = {k: multiply_quantity(v, count) for k, v in profile.requests.items()}
    limits = {k: multiply_quantity(v, count) for k, v in profile.limits.items()}

    image = model.spec.image
    if not image:
        images = cfg.model_servers.get(model.spec.engine)
        if not images:
            raise ResolutionError(f"no images configured for engine {model.spec.engine}")
        image_name = profile.image_name or "default"
        image = images.get(image_name) or images["default"]

    # spec.sharding overrides the profile's group shape: an explicit
    # hosts-per-replica wins over profile.numHosts, and an explicit ICI
    # topology wins over the profile's topology node selector.
    node_selector = dict(profile.node_selector)
    num_hosts = profile.num_hosts
    if model.spec.sharding.enabled():
        from kubeai_tpu.config.system import TPU_TOPOLOGY_SELECTOR

        if model.spec.sharding.hosts:
            num_hosts = model.spec.sharding.hosts
        if model.spec.sharding.topology:
            node_selector[TPU_TOPOLOGY_SELECTOR] = model.spec.sharding.topology

    return ModelConfig(
        image=image,
        requests=requests,
        limits=limits,
        node_selector=node_selector,
        affinity=profile.affinity,
        tolerations=list(profile.tolerations),
        scheduler_name=profile.scheduler_name,
        runtime_class_name=profile.runtime_class_name,
        profile_name=profile_name,
        profile_count=count,
        source=parse_model_source(model.spec.url),
        num_hosts=num_hosts,
    )


# -- shared pod scaffolding ---------------------------------------------------


def base_pod(model: Model, cfg: System, mcfg: ModelConfig, suffix: str) -> dict:
    """Common Pod scaffold all renderers extend."""
    from kubeai_tpu.crd import metadata as md

    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"model-{model.name}-{suffix}",
            "namespace": model.namespace,
            "labels": {
                md.POD_MODEL_LABEL: model.name,
            },
            "annotations": {},
        },
        "spec": {
            "containers": [],
            "restartPolicy": "Always",
            "nodeSelector": dict(mcfg.node_selector),
            "tolerations": list(mcfg.tolerations),
        },
    }
    spec = pod["spec"]
    if mcfg.affinity:
        spec["affinity"] = mcfg.affinity
    if mcfg.scheduler_name:
        spec["schedulerName"] = mcfg.scheduler_name
    if mcfg.runtime_class_name:
        spec["runtimeClassName"] = mcfg.runtime_class_name
    if model.spec.priority_class_name:
        spec["priorityClassName"] = model.spec.priority_class_name
    if cfg.model_server_pods.service_account_name:
        spec["serviceAccountName"] = cfg.model_server_pods.service_account_name
    if cfg.model_server_pods.security_context:
        spec["securityContext"] = cfg.model_server_pods.security_context
    if cfg.model_server_pods.image_pull_secrets:
        spec["imagePullSecrets"] = [
            {"name": n} for n in cfg.model_server_pods.image_pull_secrets
        ]
    return pod


def source_env_and_volumes(model: Model, cfg: System, mcfg: ModelConfig):
    """Per-scheme env/volumes/mounts (reference: model_source.go:82-227)."""
    env: list[dict] = []
    volumes: list[dict] = []
    mounts: list[dict] = []
    src = mcfg.source
    if src.scheme == "hf":
        env.append(
            {
                "name": "HF_TOKEN",
                "valueFrom": {
                    "secretKeyRef": {
                        "name": cfg.secret_names.get("huggingface", "kubeai-huggingface"),
                        "key": "token",
                        "optional": True,
                    }
                },
            }
        )
    elif src.scheme == "s3":
        env.extend(
            [
                {
                    "name": n,
                    "valueFrom": {
                        "secretKeyRef": {
                            "name": cfg.secret_names.get("aws", "kubeai-aws"),
                            "key": k,
                            "optional": True,
                        }
                    },
                }
                for n, k in (
                    ("AWS_ACCESS_KEY_ID", "accessKeyID"),
                    ("AWS_SECRET_ACCESS_KEY", "secretAccessKey"),
                )
            ]
        )
    elif src.scheme == "gs":
        env.append(
            {
                "name": "GOOGLE_APPLICATION_CREDENTIALS",
                "value": "/secrets/gcp/credentials.json",
            }
        )
        volumes.append(
            {
                "name": "gcp-credentials",
                "secret": {
                    "secretName": cfg.secret_names.get("gcp", "kubeai-gcp"),
                    "optional": True,
                },
            }
        )
        mounts.append(
            {"name": "gcp-credentials", "mountPath": "/secrets/gcp", "readOnly": True}
        )
    elif src.scheme == "oss":
        env.extend(
            [
                {
                    "name": n,
                    "valueFrom": {
                        "secretKeyRef": {
                            "name": cfg.secret_names.get("alibaba", "kubeai-alibaba"),
                            "key": k,
                            "optional": True,
                        }
                    },
                }
                for n, k in (
                    ("OSS_ACCESS_KEY_ID", "accessKeyID"),
                    ("OSS_ACCESS_KEY_SECRET", "accessKeySecret"),
                )
            ]
        )
    elif src.scheme == "pvc":
        pvc_name = src.ref.split("/", 1)[0]
        volumes.append(
            {
                "name": "model-pvc",
                "persistentVolumeClaim": {"claimName": pvc_name, "readOnly": True},
            }
        )
        mounts.append({"name": "model-pvc", "mountPath": "/model", "readOnly": True})
    return env, volumes, mounts


def model_env(model: Model) -> list[dict]:
    out = [{"name": k, "value": v} for k, v in sorted(model.spec.env.items())]
    return out


def files_volume(model: Model, files_configmap_name: str):
    """Project spec.files via ConfigMap items
    (reference: internal/modelcontroller/files.go)."""
    if not model.spec.files:
        return [], []
    items = []
    mounts = []
    for i, f in enumerate(model.spec.files):
        key = f"file-{i}"
        items.append({"key": key, "path": f.path.lstrip("/")})
        mounts.append(
            {
                "name": "model-files",
                "mountPath": f.path,
                "subPath": f.path.lstrip("/"),
                "readOnly": True,
            }
        )
    volumes = [
        {
            "name": "model-files",
            "configMap": {"name": files_configmap_name, "items": items},
        }
    ]
    return volumes, mounts
