"""Ollama Pod renderer (reference: internal/modelcontroller/engine_ollama.go:13-213).

The startup probe runs a shell script that pulls (or copies from PVC),
renames via `ollama cp` so the served name matches the Model name, and
warm-ups with `ollama run` — so Ready == actually serving, which the
blocking load balancer relies on.
"""

from __future__ import annotations

import shlex

from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator.engines.common import (
    ModelConfig,
    base_pod,
    files_volume,
    model_env,
    source_env_and_volumes,
)

PORT = 8000


def ollama_pod(model: Model, cfg: System, mcfg: ModelConfig, suffix: str) -> dict:
    pod = base_pod(model, cfg, mcfg, suffix)
    env, volumes, mounts = source_env_and_volumes(model, cfg, mcfg)
    fvols, fmounts = files_volume(model, f"model-{model.name}-files")
    volumes += fvols
    mounts += fmounts

    src = mcfg.source
    is_pvc = src.scheme == "pvc"
    ollama_ref = src.named_model or src.ref if not is_pvc else (
        src.named_model or model.name
    )

    # Startup script (reference: engine_ollama.go:173-213): pull/copy, then
    # rename to the Model name, then a warm-up generation.
    steps = []
    if is_pvc:
        steps.append("true")  # models are preloaded under OLLAMA_MODELS
    else:
        pull = src.pull_policy or "missing"
        if pull == "always":
            steps.append(f"ollama pull {shlex.quote(ollama_ref)}")
        elif pull == "never":
            steps.append("true")
        else:
            steps.append(
                f"ollama list | grep -q {shlex.quote(ollama_ref)} || "
                f"ollama pull {shlex.quote(ollama_ref)}"
            )
    if ollama_ref != model.name:
        steps.append(
            f"ollama cp {shlex.quote(ollama_ref)} {shlex.quote(model.name)}"
        )
    steps.append(f"ollama run {shlex.quote(model.name)} hi")
    script = " && ".join(steps)

    env.append({"name": "OLLAMA_HOST", "value": f"0.0.0.0:{PORT}"})
    # Never evict loaded models (reference: engine_ollama.go KEEP_ALIVE).
    env.append({"name": "OLLAMA_KEEP_ALIVE", "value": "999999h"})
    if is_pvc:
        path = "/model" + ("/" + src.ref.split("/", 1)[1] if "/" in src.ref else "")
        env.append({"name": "OLLAMA_MODELS", "value": path})
    if src.insecure:
        env.append({"name": "OLLAMA_INSECURE", "value": "true"})
    env += model_env(model)

    container = {
        "name": "server",
        "image": mcfg.image,
        "env": env,
        "ports": [{"containerPort": PORT, "name": "http"}],
        "resources": {"requests": mcfg.requests, "limits": mcfg.limits},
        "volumeMounts": mounts,
        "startupProbe": {
            "exec": {"command": ["bash", "-c", script]},
            "periodSeconds": 10,
            "failureThreshold": 180,
            "timeoutSeconds": 600,
        },
        "readinessProbe": {
            "httpGet": {"path": "/", "port": PORT},
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/", "port": PORT},
            "periodSeconds": 30,
            "failureThreshold": 3,
        },
    }
    if cfg.model_server_pods.container_security_context:
        container["securityContext"] = cfg.model_server_pods.container_security_context
    if model.spec.env_from:
        container["envFrom"] = list(model.spec.env_from)

    pod["spec"]["containers"] = [container]
    pod["spec"]["volumes"] = volumes
    pod["metadata"]["annotations"]["model-pod-port"] = str(PORT)
    return pod
