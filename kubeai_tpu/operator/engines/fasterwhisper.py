"""FasterWhisper Pod renderer (reference: internal/modelcontroller/engine_fasterwhisper.go).

Env-configured engine for the SpeechToText feature.
"""

from __future__ import annotations

from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator.engines.common import (
    ModelConfig,
    base_pod,
    files_volume,
    model_env,
    source_env_and_volumes,
)

PORT = 8000


def fasterwhisper_pod(model: Model, cfg: System, mcfg: ModelConfig, suffix: str) -> dict:
    pod = base_pod(model, cfg, mcfg, suffix)
    env, volumes, mounts = source_env_and_volumes(model, cfg, mcfg)
    fvols, fmounts = files_volume(model, f"model-{model.name}-files")
    volumes += fvols
    mounts += fmounts

    src = mcfg.source
    model_id = "/model" if src.scheme == "pvc" else src.ref
    env.append({"name": "WHISPER__MODEL", "value": model_id})
    env.append({"name": "WHISPER__PORT", "value": str(PORT)})
    env.append({"name": "ENABLE_UI", "value": "false"})
    env += model_env(model)

    container = {
        "name": "server",
        "image": mcfg.image,
        "args": list(model.spec.args),
        "env": env,
        "ports": [{"containerPort": PORT, "name": "http"}],
        "resources": {"requests": mcfg.requests, "limits": mcfg.limits},
        "volumeMounts": mounts,
        "startupProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
            "failureThreshold": 360,
        },
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 30,
            "failureThreshold": 3,
        },
    }
    if cfg.model_server_pods.container_security_context:
        container["securityContext"] = cfg.model_server_pods.container_security_context
    if model.spec.env_from:
        container["envFrom"] = list(model.spec.env_from)

    pod["spec"]["containers"] = [container]
    pod["spec"]["volumes"] = volumes
    pod["metadata"]["annotations"]["model-pod-port"] = str(PORT)
    return pod
