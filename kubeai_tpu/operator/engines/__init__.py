"""Engine Pod renderers (reference: internal/modelcontroller/engine_*.go).

One renderer per engine type; each returns a Pod manifest dict for a Model
replica. The KubeAITPU renderer is the TPU-native path (in-tree JAX engine
server, `google.com/tpu` resources, ICI topology from the resource profile);
OLlama/VLLM/FasterWhisper/Infinity keep capability parity with the
reference's external-engine orchestration.
"""

from kubeai_tpu.operator.engines.common import ModelConfig, resolve_model_config
from kubeai_tpu.operator.engines.kubeai_tpu_engine import kubeai_tpu_pod
from kubeai_tpu.operator.engines.ollama import ollama_pod
from kubeai_tpu.operator.engines.vllm import vllm_pod
from kubeai_tpu.operator.engines.fasterwhisper import fasterwhisper_pod
from kubeai_tpu.operator.engines.infinity import infinity_pod

RENDERERS = {
    "KubeAITPU": kubeai_tpu_pod,
    "OLlama": ollama_pod,
    "VLLM": vllm_pod,
    "FasterWhisper": fasterwhisper_pod,
    "Infinity": infinity_pod,
}


def render_pod(model, cfg, mcfg, index_suffix: str) -> dict:
    return RENDERERS[model.spec.engine](model, cfg, mcfg, index_suffix)
