"""vLLM Pod renderer (reference: internal/modelcontroller/engine_vllm.go:12-167).

Kept for capability parity — users migrating from the reference can keep
GPU Models running unchanged while TPU Models use the in-tree engine.
"""

from __future__ import annotations

from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator.engines.common import (
    ModelConfig,
    base_pod,
    files_volume,
    model_env,
    source_env_and_volumes,
)

PORT = 8000


def vllm_pod(model: Model, cfg: System, mcfg: ModelConfig, suffix: str) -> dict:
    pod = base_pod(model, cfg, mcfg, suffix)
    env, volumes, mounts = source_env_and_volumes(model, cfg, mcfg)
    fvols, fmounts = files_volume(model, f"model-{model.name}-files")
    volumes += fvols
    mounts += fmounts

    src = mcfg.source
    if src.scheme == "pvc":
        model_arg = "/model" + (
            "/" + src.ref.split("/", 1)[1] if "/" in src.ref else ""
        )
    elif src.scheme == "hf":
        model_arg = src.ref
    elif src.scheme in ("s3", "gs", "oss"):
        # runai-streamer loads object storage directly
        # (reference: engine_vllm.go s3 handling).
        model_arg = f"{src.scheme}://{src.ref}"
    else:
        model_arg = src.ref
    if mcfg.cache_dir:
        model_arg = mcfg.cache_dir

    args = ["--model=" + model_arg, f"--served-model-name={model.name}", f"--port={PORT}"]
    if src.scheme in ("s3", "gs", "oss"):
        args.append("--load-format=runai_streamer")
    if model.spec.adapters:
        args.append("--enable-lora")
    args += list(model.spec.args)

    env += model_env(model)
    if model.spec.adapters:
        env.append({"name": "VLLM_ALLOW_RUNTIME_LORA_UPDATING", "value": "True"})

    # /dev/shm for torch inter-process comms (reference: engine_vllm.go).
    volumes.append({"name": "dshm", "emptyDir": {"medium": "Memory"}})
    mounts.append({"name": "dshm", "mountPath": "/dev/shm"})

    container = {
        "name": "server",
        "image": mcfg.image,
        "args": args,
        "env": env,
        "ports": [{"containerPort": PORT, "name": "http"}],
        "resources": {"requests": mcfg.requests, "limits": mcfg.limits},
        "volumeMounts": mounts,
        # 3h startup budget for big-weight loads (reference: engine_vllm.go:101-107).
        "startupProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
            "failureThreshold": 1080,
        },
        "readinessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 10,
        },
        "livenessProbe": {
            "httpGet": {"path": "/health", "port": PORT},
            "periodSeconds": 30,
            "failureThreshold": 3,
        },
    }
    if cfg.model_server_pods.container_security_context:
        container["securityContext"] = cfg.model_server_pods.container_security_context
    if model.spec.env_from:
        container["envFrom"] = list(model.spec.env_from)

    # Adapter loader sidecar (exec target for adapter downloads,
    # reference: adapters.go:203-217).
    if model.spec.adapters:
        pod["spec"]["initContainers"] = [
            {
                "name": "loader",
                "image": cfg.model_loading_image,
                "command": ["sleep", "infinity"],
                "restartPolicy": "Always",  # sidecar
                "volumeMounts": [
                    {"name": "adapters", "mountPath": "/adapters"}
                ],
            }
        ]
        volumes.append({"name": "adapters", "emptyDir": {}})
        mounts.append({"name": "adapters", "mountPath": "/adapters"})

    pod["spec"]["containers"] = [container]
    pod["spec"]["volumes"] = volumes
    pod["metadata"]["annotations"]["model-pod-port"] = str(PORT)
    return pod
