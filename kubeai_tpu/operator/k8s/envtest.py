"""Conformance-grade in-process kube-apiserver for integration tests.

The reference's e2e tier runs a REAL apiserver (kind) and curls through
it (reference: test/e2e/run.sh:24-105), so server-side behavior —
OpenAPI structural validation, CEL admission rules, resourceVersion
semantics, watch resume, 410 Gone — is exercised, not assumed. This
module is the envtest analog for environments without cluster binaries:
an HTTP server that

  - loads the ACTUAL CRD manifest (deploy/crd-model.yaml) and enforces
    its openAPIV3Schema on writes: types, required, pattern, enum,
    defaults, and every `x-kubernetes-validations` CEL rule (a built-in
    evaluator covers the CEL subset CRDs use: has()/size(),
    startsWith, exists/filter macros, logical/comparison operators,
    oldSelf transition rules). Rejections are Status objects with the
    rule's message — admission errors come FROM THE SERVER, never from
    in-process client code;
  - maintains a global resourceVersion: lists carry the collection rv,
    updates with a stale object rv return 409 Conflict, watches resume
    from `resourceVersion=` by replaying history, and a compacted
    history returns 410 Gone (clients must relist — rest.py's watch
    loop does);
  - streams watches as chunked JSON lines and can close connections
    every N events to exercise client reconnect/resume.

It speaks exactly the API subset RestKubeClient uses (KIND_ROUTES), so
the full operator manager runs against it unmodified.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

# ---- mini-CEL ------------------------------------------------------------
#
# Expression subset used by CRD validation rules. Evaluation follows
# CEL's error-absorbing logical operators: `true || error` is true,
# `false && error` is false.


class CelError(Exception):
    pass


_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>\d+)|(?P<str>'[^']*')|(?P<ident>[A-Za-z_][A-Za-z0-9_]*)"
    r"|(?P<op>\|\||&&|==|!=|<=|>=|[!<>().,]))"
)


def _tokenize(src: str) -> list[str]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m or m.end() == pos:
            if src[pos:].strip():
                raise CelError(f"cannot tokenize {src[pos:]!r}")
            break
        out.append(m.group().strip())
        pos = m.end()
    return out


class _Parser:
    """Pratt parser producing a closure tree: each node is
    fn(env) -> value, env = {'self': ..., 'oldSelf': ..., lambda vars}."""

    def __init__(self, tokens: list[str]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> str | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str:
        tok = self.peek()
        if tok is None:
            raise CelError("unexpected end of expression")
        self.i += 1
        return tok

    def expect(self, tok: str) -> None:
        got = self.next()
        if got != tok:
            raise CelError(f"expected {tok!r}, got {got!r}")

    def parse(self):
        node = self.parse_or()
        if self.peek() is not None:
            raise CelError(f"trailing tokens at {self.peek()!r}")
        return node

    def parse_or(self):
        node = self.parse_and()
        while self.peek() == "||":
            self.next()
            rhs = self.parse_and()
            node = _logical_or(node, rhs)
        return node

    def parse_and(self):
        node = self.parse_cmp()
        while self.peek() == "&&":
            self.next()
            rhs = self.parse_cmp()
            node = _logical_and(node, rhs)
        return node

    def parse_cmp(self):
        node = self.parse_unary()
        if self.peek() in ("==", "!=", "<=", ">=", "<", ">"):
            op = self.next()
            rhs = self.parse_unary()
            node = _compare(op, node, rhs)
        return node

    def parse_unary(self):
        if self.peek() == "!":
            self.next()
            inner = self.parse_unary()
            return lambda env: not _truthy(inner(env))
        return self.parse_postfix()

    def parse_postfix(self):
        node = self.parse_primary()
        while self.peek() == ".":
            self.next()
            name = self.next()
            if self.peek() == "(":  # method / macro
                self.next()
                node = self.parse_call(node, name)
            else:
                node = _field(node, name)
        return node

    def parse_call(self, recv, name: str):
        if name in ("exists", "filter"):
            var = self.next()
            self.expect(",")
            body = self.parse_or()
            self.expect(")")
            return _macro(name, recv, var, body)
        args = []
        if self.peek() != ")":
            args.append(self.parse_or())
            while self.peek() == ",":
                self.next()
                args.append(self.parse_or())
        self.expect(")")
        return _method(name, recv, args)

    def parse_primary(self):
        tok = self.next()
        if tok == "(":
            node = self.parse_or()
            self.expect(")")
            return node
        if tok.startswith("'"):
            s = tok[1:-1]
            return lambda env: s
        if tok.isdigit():
            n = int(tok)
            return lambda env: n
        if tok in ("true", "false"):
            b = tok == "true"
            return lambda env: b
        if tok == "has":
            self.expect("(")
            # has() takes a field-access chain; the LAST access is the
            # existence test, the prefix must resolve.
            inner = self.parse_or()
            self.expect(")")
            if not isinstance(inner, _FieldAccess):
                raise CelError("has() requires a field selection")
            return inner.as_has()
        if tok == "size":
            self.expect("(")
            inner = self.parse_or()
            self.expect(")")
            return lambda env: _size(inner(env))
        name = tok
        return _Var(name)


class _Var:
    def __init__(self, name: str):
        self.name = name

    def __call__(self, env):
        if self.name not in env:
            raise CelError(f"unknown identifier {self.name!r}")
        return env[self.name]


class _FieldAccess:
    def __init__(self, recv, name: str):
        self.recv = recv
        self.name = name

    def __call__(self, env):
        obj = self.recv(env)
        if not isinstance(obj, dict) or self.name not in obj:
            raise CelError(f"no such field {self.name!r}")
        return obj[self.name]

    def as_has(self):
        recv, name = self.recv, self.name

        def fn(env):
            obj = recv(env)
            return isinstance(obj, dict) and name in obj

        return fn


def _field(recv, name: str):
    return _FieldAccess(recv, name)


def _truthy(v) -> bool:
    if not isinstance(v, bool):
        raise CelError(f"expected bool, got {type(v).__name__}")
    return v


def _logical_or(lhs, rhs):
    def fn(env):
        # CEL absorbs errors: true || error == true (either side).
        try:
            if _truthy(lhs(env)):
                return True
            left_err = None
        except CelError as e:
            left_err = e
        if _truthy(rhs(env)):
            return True
        if left_err is not None:
            raise left_err
        return False

    return fn


def _logical_and(lhs, rhs):
    def fn(env):
        try:
            if not _truthy(lhs(env)):
                return False
            left_err = None
        except CelError as e:
            left_err = e
        if not _truthy(rhs(env)):
            return False
        if left_err is not None:
            raise left_err
        return True

    return fn


def _compare(op: str, lhs, rhs):
    def fn(env):
        a, b = lhs(env), rhs(env)
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if type(a) is not type(b):
            raise CelError(f"cannot order {a!r} and {b!r}")
        if op == "<=":
            return a <= b
        if op == ">=":
            return a >= b
        if op == "<":
            return a < b
        return a > b

    return fn


def _method(name: str, recv, args):
    def fn(env):
        obj = recv(env)
        vals = [a(env) for a in args]
        if name == "startsWith":
            if not isinstance(obj, str):
                raise CelError("startsWith on non-string")
            return obj.startswith(vals[0])
        if name == "endsWith":
            if not isinstance(obj, str):
                raise CelError("endsWith on non-string")
            return obj.endswith(vals[0])
        if name == "contains":
            return vals[0] in obj
        if name == "size":
            return _size(obj)
        if name == "matches":
            return re.search(vals[0], obj) is not None
        raise CelError(f"unsupported method {name!r}")

    return fn


def _macro(name: str, recv, var: str, body):
    def fn(env):
        seq = recv(env)
        if not isinstance(seq, list):
            raise CelError(f"{name}() on non-list")
        if name == "exists":
            return any(
                _truthy(body({**env, var: item})) for item in seq
            )
        return [item for item in seq if _truthy(body({**env, var: item}))]

    return fn


def _size(v):
    if isinstance(v, (str, list, dict)):
        return len(v)
    raise CelError(f"size() of {type(v).__name__}")


def compile_cel(expr: str):
    """Compile a CRD validation rule to fn(self, oldSelf=None) -> bool."""
    node = _Parser(_tokenize(expr)).parse()

    def fn(self_val, old_self=None):
        env = {"self": self_val}
        if old_self is not None:
            env["oldSelf"] = old_self
        return _truthy(node(env))

    return fn


# ---- structural schema ------------------------------------------------------


class ValidationFailure(Exception):
    def __init__(self, path: str, message: str):
        super().__init__(f"{path}: {message}")
        self.path = path
        self.message = message


class Schema:
    """One openAPIV3Schema node: type/required/pattern/enum/properties/
    items/defaults + compiled x-kubernetes-validations."""

    def __init__(self, spec: dict):
        self.spec = spec
        self.type = spec.get("type")
        self.required = spec.get("required", [])
        self.pattern = re.compile(spec["pattern"]) if "pattern" in spec else None
        self.enum = spec.get("enum")
        self.default = spec.get("default")
        self.properties = {
            k: Schema(v) for k, v in spec.get("properties", {}).items()
        }
        self.items = Schema(spec["items"]) if "items" in spec else None
        addl = spec.get("additionalProperties")
        self.additional = Schema(addl) if isinstance(addl, dict) else None
        self.rules = [
            (compile_cel(r["rule"]), r.get("message", r["rule"]),
             "oldSelf" in r["rule"])
            for r in spec.get("x-kubernetes-validations", [])
        ]

    def apply_defaults(self, value):
        if self.type == "object" and isinstance(value, dict):
            for name, sub in self.properties.items():
                if name not in value and sub.default is not None:
                    value[name] = json.loads(json.dumps(sub.default))
                if name in value:
                    sub.apply_defaults(value[name])
        elif self.type == "array" and isinstance(value, list) and self.items:
            for item in value:
                self.items.apply_defaults(item)
        return value

    def validate(self, value, old=None, path: str = "") -> None:
        self._check_type(value, path)
        for fn, message, needs_old in self.rules:
            if needs_old and old is None:
                continue  # transition rules only apply to updates
            try:
                ok = fn(value, old)
            except CelError as e:
                raise ValidationFailure(path or ".", f"rule error: {e}")
            if not ok:
                raise ValidationFailure(path or ".", message)
        if self.type == "object" and isinstance(value, dict):
            for req in self.required:
                if req not in value:
                    raise ValidationFailure(
                        f"{path}.{req}", "required field is missing"
                    )
            for name, sub in self.properties.items():
                if name in value:
                    sub.validate(
                        value[name],
                        (old or {}).get(name) if isinstance(old, dict) else None,
                        f"{path}.{name}",
                    )
            if self.additional is not None:
                for name, v in value.items():
                    if name not in self.properties:
                        self.additional.validate(v, None, f"{path}.{name}")
        elif self.type == "array" and isinstance(value, list) and self.items:
            for i, item in enumerate(value):
                self.items.validate(item, None, f"{path}[{i}]")
        if self.pattern and isinstance(value, str):
            if not self.pattern.search(value):
                raise ValidationFailure(
                    path, f"does not match pattern {self.pattern.pattern!r}"
                )
        if self.enum is not None and value not in self.enum:
            raise ValidationFailure(path, f"not one of {self.enum}")

    def _check_type(self, value, path: str) -> None:
        expect = self.type
        if expect is None:
            return
        ok = {
            "object": lambda v: isinstance(v, dict),
            "array": lambda v: isinstance(v, list),
            "string": lambda v: isinstance(v, str),
            "integer": lambda v: isinstance(v, int) and not isinstance(v, bool),
            "number": lambda v: isinstance(v, (int, float))
            and not isinstance(v, bool),
            "boolean": lambda v: isinstance(v, bool),
        }[expect](value)
        if not ok:
            raise ValidationFailure(
                path, f"expected {expect}, got {type(value).__name__}"
            )


def load_crd_schema(crd_path: str) -> Schema:
    """Parse deploy/crd-model.yaml (stdlib YAML subset parser from the
    config package) and compile its v1 openAPIV3Schema."""
    from kubeai_tpu.config.system import _parse_config_text

    with open(crd_path) as f:
        crd = _parse_config_text(f.read())
    for version in crd["spec"]["versions"]:
        if version.get("storage") or version.get("served"):
            return Schema(version["schema"]["openAPIV3Schema"])
    raise ValueError("no served version in CRD")


# ---- the API server ----------------------------------------------------------

_PLURALS = {
    "pods": "Pod",
    "configmaps": "ConfigMap",
    "persistentvolumeclaims": "PersistentVolumeClaim",
    "services": "Service",
    "nodes": "Node",
    "jobs": "Job",
    "leases": "Lease",
    "models": "Model",
}


class FakeKubeApiServer:
    """See module docstring. `crd_path` enables server-side Model
    admission; `watch_close_every` closes each watch connection after N
    events (clients must resume); `compact()` discards watch history so
    stale resumes get 410 Gone; `fault_plan` (a
    kubeai_tpu.testing.faults.ApiFaultPlan) injects deterministic
    server-side faults — 429 storms with Retry-After, 409 conflict
    storms, 5xx, dropped connections, pre-response stalls — per
    (method, resource, watch?) request schedule, so client retry paths
    are chaos-tested over real HTTP."""

    def __init__(
        self,
        crd_path: str | None = None,
        watch_close_every: int = 0,
        fault_plan=None,
        fault_sleep=None,
    ):
        self.fault_plan = fault_plan
        self._fault_sleep = fault_sleep  # injectable stall clock
        self.lock = threading.RLock()
        self.objects: dict[tuple[str, str, str], dict] = {}
        self.rv = 0
        # Watch history: list of (rv, kind_plural, event_type, object).
        self.history: list[tuple[int, str, str, dict]] = []
        self.history_start = 0  # rvs <= this are compacted away
        self.watch_gen = 0  # bumped by compact(): open streams close
        self.watch_close_every = watch_close_every
        self.model_schema = load_crd_schema(crd_path) if crd_path else None
        self.requests: list[str] = []
        self._new_event = threading.Condition(self.lock)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                outer._handle(self, "GET")

            def do_POST(self):
                outer._handle(self, "POST")

            def do_PUT(self):
                outer._handle(self, "PUT")

            def do_PATCH(self):
                outer._handle(self, "PATCH")

            def do_DELETE(self):
                outer._handle(self, "DELETE")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self._stop = threading.Event()
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def close(self) -> None:
        self._stop.set()
        with self._new_event:
            self._new_event.notify_all()
        self.httpd.shutdown()
        self.httpd.server_close()

    def compact(self) -> None:
        """Discard watch history (etcd compaction) and close open watch
        streams: every client resume from a pre-compaction rv then gets
        410 Gone DETERMINISTICALLY (the rv bump guarantees any rv a
        client saw before this call is now too old)."""
        with self._new_event:
            self.rv += 1
            self.history_start = self.rv
            self.history.clear()
            self.watch_gen += 1
            self._new_event.notify_all()

    # -- request handling -------------------------------------------------------

    @staticmethod
    def _status(code: int, reason: str, message: str) -> dict:
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "reason": reason,
            "code": code,
            "message": message,
        }

    def _send(
        self, handler, code: int, payload: dict,
        headers: dict | None = None,
    ) -> None:
        body = json.dumps(payload).encode()
        handler.send_response(code)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            handler.send_header(k, str(v))
        handler.end_headers()
        try:
            handler.wfile.write(body)
        except OSError:
            pass

    @staticmethod
    def _parse_path(path: str):
        parsed = urllib.parse.urlparse(path)
        segs = [s for s in parsed.path.split("/") if s]
        q = {k: v[-1] for k, v in urllib.parse.parse_qs(parsed.query).items()}
        ns = name = None
        if "namespaces" in segs:
            i = segs.index("namespaces")
            ns = segs[i + 1]
            plural = segs[i + 2]
            name = segs[i + 3] if len(segs) > i + 3 else None
        else:
            plural = segs[-1]
        return plural, ns, name, q

    def _handle(self, handler, method: str) -> None:
        try:
            plural, ns, name, q = self._parse_path(handler.path)
        except (ValueError, IndexError):
            self._send(handler, 404, self._status(404, "NotFound", "bad path"))
            return
        self.requests.append(f"{method} {handler.path}")
        if self.fault_plan is not None and not self._apply_fault(
            handler, method, plural, q
        ):
            return
        if plural not in _PLURALS:
            self._send(
                handler, 404,
                self._status(404, "NotFound", f"unknown resource {plural}"),
            )
            return
        n = int(handler.headers.get("Content-Length") or 0)
        body = None
        if n:
            try:
                body = json.loads(handler.rfile.read(n))
            except json.JSONDecodeError:
                self._send(
                    handler, 400,
                    self._status(400, "BadRequest", "invalid JSON"),
                )
                return
        try:
            if method == "GET" and q.get("watch") == "true":
                self._watch(handler, plural, q)
            elif method == "GET" and name:
                self._get(handler, plural, ns, name)
            elif method == "GET":
                self._list(handler, plural, ns, q)
            elif method == "POST":
                self._create(handler, plural, ns, body)
            elif method == "PUT":
                self._update(handler, plural, ns, name, body)
            elif method == "PATCH":
                self._patch(handler, plural, ns, name, body)
            elif method == "DELETE":
                self._delete(handler, plural, ns, name)
        except BrokenPipeError:
            pass

    def _apply_fault(self, handler, method: str, plural: str, q) -> bool:
        """Consult the fault plan for this request. Returns True when
        handling should proceed normally (possibly after a stall),
        False when the fault already answered (or dropped) the
        request."""
        from kubeai_tpu.testing import faults as faults_mod

        fault = self.fault_plan.on_request(
            method, plural, q.get("watch") == "true"
        )
        if fault is None:
            return True
        if fault.kind == faults_mod.API_FAULT_DROP:
            try:
                handler.connection.close()
            except OSError:
                pass
            return False
        if fault.kind == faults_mod.API_FAULT_STALL:
            (self._fault_sleep or time.sleep)(fault.stall_s)
            return True
        self._send(
            handler,
            fault.status,
            self._status(fault.status, fault.reason, fault.message),
            headers=fault.headers,
        )
        return False

    # -- CRUD ---------------------------------------------------------------

    def _admit(self, plural: str, obj: dict, old: dict | None) -> str | None:
        """Server-side admission; returns an error message or None."""
        if plural != "models" or self.model_schema is None:
            return None
        try:
            self.model_schema.apply_defaults(obj)
            self.model_schema.validate(obj, old)
        except ValidationFailure as e:
            return str(e)
        return None

    def _record(self, plural: str, ev: str, obj: dict) -> None:
        self.history.append((self.rv, plural, ev, json.loads(json.dumps(obj))))
        if len(self.history) > 4096:
            self.history_start = self.history[1024][0]
            del self.history[:1024]
        self._new_event.notify_all()

    def _create(self, handler, plural, ns, obj) -> None:
        import uuid

        with self.lock:
            meta = obj.setdefault("metadata", {})
            meta.setdefault("namespace", ns or "default")
            if not meta.get("name"):
                if meta.get("generateName"):
                    meta["name"] = (
                        meta["generateName"] + uuid.uuid4().hex[:6]
                    )
                else:
                    self._send(
                        handler, 422,
                        self._status(
                            422, "Invalid", "metadata.name is required"
                        ),
                    )
                    return
            key = (plural, meta["namespace"], meta["name"])
            if key in self.objects:
                self._send(
                    handler, 409,
                    self._status(
                        409, "AlreadyExists", f"{meta.get('name')} exists"
                    ),
                )
                return
            err = self._admit(plural, obj, None)
            if err is not None:
                self._send(handler, 422, self._status(422, "Invalid", err))
                return
            self.rv += 1
            meta["resourceVersion"] = str(self.rv)
            # `or`, not setdefault: client-built objects often carry an
            # EMPTY uid field, and GC matches strictly by uid.
            meta["uid"] = meta.get("uid") or f"uid-{self.rv}"
            self.objects[key] = obj
            self._record(plural, "ADDED", obj)
        self._send(handler, 201, obj)

    def _get(self, handler, plural, ns, name) -> None:
        with self.lock:
            obj = self.objects.get((plural, ns or "default", name))
        if obj is None:
            self._send(
                handler, 404, self._status(404, "NotFound", f"{name} not found")
            )
            return
        self._send(handler, 200, obj)

    @staticmethod
    def _matches(obj: dict, selector: str) -> bool:
        labels = (obj.get("metadata") or {}).get("labels") or {}
        for part in selector.split(","):
            if not part:
                continue
            k, _, v = part.partition("=")
            if labels.get(k) != v:
                return False
        return True

    def _list(self, handler, plural, ns, q) -> None:
        selector = q.get("labelSelector", "")
        with self.lock:
            items = [
                o for (p, n, _), o in sorted(self.objects.items())
                if p == plural and (ns is None or n == ns)
                and (not selector or self._matches(o, selector))
            ]
            rv = str(self.rv)
        self._send(
            handler, 200,
            {
                "kind": f"{_PLURALS[plural]}List",
                "metadata": {"resourceVersion": rv},
                "items": items,
            },
        )

    def _update(self, handler, plural, ns, name, obj) -> None:
        with self.lock:
            key = (plural, ns or "default", name)
            old = self.objects.get(key)
            if old is None:
                self._send(
                    handler, 404,
                    self._status(404, "NotFound", f"{name} not found"),
                )
                return
            sent_rv = (obj.get("metadata") or {}).get("resourceVersion")
            if sent_rv and sent_rv != old["metadata"]["resourceVersion"]:
                self._send(
                    handler, 409,
                    self._status(
                        409, "Conflict",
                        f"the object has been modified (rv {sent_rv} != "
                        f"{old['metadata']['resourceVersion']})",
                    ),
                )
                return
            err = self._admit(plural, obj, old)
            if err is not None:
                self._send(handler, 422, self._status(422, "Invalid", err))
                return
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            obj["metadata"]["uid"] = (
                obj["metadata"].get("uid")
                or old["metadata"].get("uid")
                or f"uid-{self.rv}"
            )
            self.objects[key] = obj
            self._record(plural, "MODIFIED", obj)
        self._send(handler, 200, obj)

    def _patch(self, handler, plural, ns, name, patch) -> None:
        with self.lock:
            key = (plural, ns or "default", name)
            old = self.objects.get(key)
            if old is None:
                self._send(
                    handler, 404,
                    self._status(404, "NotFound", f"{name} not found"),
                )
                return

            def merge(dst, src):
                for k, v in src.items():
                    if v is None:
                        dst.pop(k, None)
                    elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                        merge(dst[k], v)
                    else:
                        dst[k] = v

            obj = json.loads(json.dumps(old))
            merge(obj, patch or {})
            err = self._admit(plural, obj, old)
            if err is not None:
                self._send(handler, 422, self._status(422, "Invalid", err))
                return
            self.rv += 1
            obj["metadata"]["resourceVersion"] = str(self.rv)
            self.objects[key] = obj
            self._record(plural, "MODIFIED", obj)
        self._send(handler, 200, obj)

    def _delete(self, handler, plural, ns, name) -> None:
        with self.lock:
            key = (plural, ns or "default", name)
            obj = self.objects.pop(key, None)
            if obj is None:
                self._send(
                    handler, 404,
                    self._status(404, "NotFound", f"{name} not found"),
                )
                return
            self.rv += 1
            self._record(plural, "DELETED", obj)
            self._gc_locked(obj["metadata"])
        self._send(handler, 200, self._status(200, "Success", "deleted"))

    def _gc_locked(self, owner_meta: dict) -> None:
        """Cascade-delete dependents by ownerReference — the cluster
        garbage collector's job, which a conformance server must do or
        controller-owned Pods leak on Model deletion. Strictly
        uid-matched, like the real GC."""
        uid = owner_meta.get("uid")
        if not uid:
            return
        victims = [
            key for key, o in self.objects.items()
            if any(
                ref.get("uid") == uid
                for ref in (
                    (o.get("metadata") or {}).get("ownerReferences") or []
                )
            )
        ]
        for plural_v, ns_v, name_v in victims:
            obj = self.objects.pop((plural_v, ns_v, name_v), None)
            if obj is not None:
                self.rv += 1
                self._record(plural_v, "DELETED", obj)
                self._gc_locked(obj["metadata"])

    # -- watch --------------------------------------------------------------

    def _watch(self, handler, plural, q) -> None:
        """Chunked watch stream. resourceVersion semantics:
        absent/'' = events from NOW; rv = replay history AFTER rv, 410
        Gone if that part of history was compacted."""
        rv_param = q.get("resourceVersion", "")
        with self.lock:
            if rv_param:
                since = int(rv_param)
                if since < self.history_start:
                    self._send(
                        handler, 410,
                        self._status(
                            410, "Expired",
                            f"too old resource version: {since} "
                            f"({self.history_start})",
                        ),
                    )
                    return
            else:
                since = self.rv
        handler.send_response(200)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()

        with self.lock:
            gen = self.watch_gen
        sent = 0
        while not self._stop.is_set():
            with self._new_event:
                if self.watch_gen != gen:
                    break  # compaction: force the client to reconnect
                batch = [
                    (rv, ev, obj)
                    for rv, p, ev, obj in self.history
                    if p == plural and rv > since
                ]
                if not batch:
                    self._new_event.wait(timeout=0.5)
                    continue
            for rv, ev, obj in batch:
                line = json.dumps({"type": ev, "object": obj}).encode() + b"\n"
                try:
                    handler.wfile.write(
                        f"{len(line):x}\r\n".encode() + line + b"\r\n"
                    )
                    handler.wfile.flush()
                except OSError:
                    return
                since = rv
                sent += 1
                if self.watch_close_every and sent >= self.watch_close_every:
                    try:
                        handler.wfile.write(b"0\r\n\r\n")
                    except OSError:
                        pass
                    return
        try:
            handler.wfile.write(b"0\r\n\r\n")
        except OSError:
            pass
