"""In-memory Kubernetes API store with watches, finalizers and optimistic
concurrency — the control plane's test substrate AND the single client
interface the operator codes against.

Semantics mirrored from the real API server (and exercised the way the
reference exercises envtest — reference: test/integration/utils_test.go):
  - resourceVersion optimistic concurrency on update (Conflict on mismatch)
  - delete with finalizers sets deletionTimestamp; the object is removed
    only when the last finalizer is cleared by an update
  - label-selector list filtering
  - watch events (ADDED/MODIFIED/DELETED) fan out to subscriber queues
"""

from __future__ import annotations

import copy
import queue
import threading
import time
import uuid
from typing import Callable, Iterable


class NotFound(KeyError):
    pass


class Conflict(RuntimeError):
    pass


class Invalid(ValueError):
    pass


def _key(kind: str, namespace: str, name: str) -> tuple:
    return (kind, namespace, name)


def meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


def match_labels(obj: dict, selector: dict[str, str] | None) -> bool:
    if not selector:
        return True
    labels = meta(obj).get("labels") or {}
    return all(labels.get(k) == v for k, v in selector.items())


class KubeStore:
    """Thread-safe in-memory object store keyed by (kind, namespace, name)."""

    def __init__(self, namegen: Callable[[], str] | None = None):
        self._lock = threading.RLock()
        self._objects: dict[tuple, dict] = {}
        self._rv = 0
        self._watchers: list[tuple[tuple[str, ...] | None, queue.Queue]] = []
        # admission validators: kind -> callable(new_obj, old_obj|None)
        self._validators: dict[str, Callable[[dict, dict | None], None]] = {}
        # generateName suffix source. The default mirrors the real API
        # server (random); deterministic sims inject a counter so pod
        # names — and everything that sorts by them — replay identically.
        self._namegen = namegen or (lambda: uuid.uuid4().hex[:6])

    # -- admission -----------------------------------------------------------

    def register_validator(
        self, kind: str, fn: Callable[[dict, dict | None], None]
    ) -> None:
        self._validators[kind] = fn

    # -- watch ---------------------------------------------------------------

    def watch(self, kinds: Iterable[str] | None = None) -> queue.Queue:
        """Subscribe to events: queue yields (event_type, obj_copy)."""
        q: queue.Queue = queue.Queue()
        with self._lock:
            self._watchers.append((tuple(kinds) if kinds else None, q))
        return q

    def _emit(self, event: str, obj: dict) -> None:
        kind = obj.get("kind", "")
        for kinds, q in list(self._watchers):
            if kinds is None or kind in kinds:
                q.put((event, copy.deepcopy(obj)))

    # -- CRUD ----------------------------------------------------------------

    def _next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    def create(self, obj: dict) -> dict:
        with self._lock:
            kind = obj.get("kind") or ""
            m = meta(obj)
            ns = m.setdefault("namespace", "default")
            name = m.get("name")
            if not name:
                if m.get("generateName"):
                    name = m["generateName"] + self._namegen()
                    m["name"] = name
                else:
                    raise Invalid("metadata.name required")
            k = _key(kind, ns, name)
            if k in self._objects:
                raise Conflict(f"{kind} {ns}/{name} already exists")
            if kind in self._validators:
                self._validators[kind](obj, None)
            m["uid"] = m.get("uid") or str(uuid.uuid4())
            m["resourceVersion"] = self._next_rv()
            m.setdefault("creationTimestamp", time.time())
            m.setdefault("generation", 1)
            stored = copy.deepcopy(obj)
            self._objects[k] = stored
            self._emit("ADDED", stored)
            return copy.deepcopy(stored)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            return copy.deepcopy(self._objects[k])

    def try_get(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        with self._lock:
            out = []
            for (k_kind, k_ns, _), obj in self._objects.items():
                if k_kind != kind:
                    continue
                if namespace is not None and k_ns != namespace:
                    continue
                if not match_labels(obj, label_selector):
                    continue
                out.append(copy.deepcopy(obj))
            out.sort(key=lambda o: meta(o).get("name", ""))
            return out

    def update(self, obj: dict) -> dict:
        """Full update with optimistic concurrency; spec change bumps
        generation; clearing the last finalizer of a deleting object
        removes it."""
        with self._lock:
            kind = obj.get("kind") or ""
            m = meta(obj)
            k = _key(kind, m.get("namespace", "default"), m.get("name"))
            if k not in self._objects:
                raise NotFound(f"{kind} {k[1]}/{k[2]}")
            current = self._objects[k]
            cur_m = meta(current)
            if str(m.get("resourceVersion")) != str(cur_m.get("resourceVersion")):
                raise Conflict(
                    f"{kind} {k[1]}/{k[2]}: resourceVersion conflict"
                )
            if kind in self._validators:
                self._validators[kind](obj, current)
            if obj.get("spec") != current.get("spec"):
                m["generation"] = int(cur_m.get("generation", 1)) + 1
            # immutable server-set fields
            m["uid"] = cur_m.get("uid")
            m["creationTimestamp"] = cur_m.get("creationTimestamp")
            if cur_m.get("deletionTimestamp"):
                m["deletionTimestamp"] = cur_m["deletionTimestamp"]
            m["resourceVersion"] = self._next_rv()
            stored = copy.deepcopy(obj)
            if m.get("deletionTimestamp") and not m.get("finalizers"):
                del self._objects[k]
                self._emit("DELETED", stored)
            else:
                self._objects[k] = stored
                self._emit("MODIFIED", stored)
            return copy.deepcopy(stored)

    def patch_merge(
        self, kind: str, namespace: str, name: str, patch: dict
    ) -> dict:
        """Strategic-merge-ish patch (dict deep merge; None deletes keys).
        Retries are unnecessary: server-side under one lock."""
        with self._lock:
            obj = self.get(kind, namespace, name)
            _deep_merge(obj, patch)
            return self.update(obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        """Delete; honors finalizers like the real API server."""
        with self._lock:
            k = _key(kind, namespace, name)
            if k not in self._objects:
                raise NotFound(f"{kind} {namespace}/{name}")
            obj = self._objects[k]
            m = meta(obj)
            if m.get("finalizers"):
                if not m.get("deletionTimestamp"):
                    m["deletionTimestamp"] = time.time()
                    m["resourceVersion"] = self._next_rv()
                    self._emit("MODIFIED", obj)
                return
            del self._objects[k]
            self._emit("DELETED", obj)
            self._collect_garbage_locked(m)

    def _collect_garbage_locked(self, owner_meta: dict) -> None:
        """Cascade-delete dependents whose ownerReference matches the
        deleted object (the real cluster's garbage collector; the
        reference relies on it for Pod cleanup via controller refs).
        Strictly uid-matched, like the real GC — name fallbacks would
        cascade on unrelated same-named objects."""
        uid = owner_meta.get("uid")
        if not uid:
            return
        victims = [
            key for key, o in self._objects.items()
            if any(
                ref.get("uid") == uid
                for ref in (meta(o).get("ownerReferences") or [])
            )
        ]
        for kind_v, ns_v, name_v in victims:
            try:
                self.delete(kind_v, ns_v, name_v)
            except NotFound:
                pass

    def delete_all_of(
        self,
        kind: str,
        namespace: str,
        label_selector: dict[str, str] | None = None,
    ) -> int:
        with self._lock:
            victims = self.list(kind, namespace, label_selector)
            for v in victims:
                try:
                    self.delete(kind, namespace, meta(v)["name"])
                except NotFound:
                    pass
            return len(victims)


def _deep_merge(dst: dict, patch: dict) -> None:
    for k, v in patch.items():
        if v is None:
            dst.pop(k, None)
        elif isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = copy.deepcopy(v)
