"""Kubernetes API access layer.

Two implementations of one interface:
  - `KubeStore`: in-memory API server (the test strategy's envtest
    equivalent — reference: test/integration/main_test.go:83-89 runs a real
    apiserver with no kubelet; here the store IS the apiserver).
  - `RestKubeClient` (kubeai_tpu.operator.k8s.rest): stdlib-HTTP client for
    a real cluster (in-cluster service account auth).

Objects are plain dicts in manifest shape — same contract as the wire.
"""

from kubeai_tpu.operator.k8s.store import KubeStore, Conflict, NotFound
