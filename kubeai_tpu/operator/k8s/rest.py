"""Real-cluster Kubernetes client speaking the same interface as KubeStore.

stdlib-only (urllib over the in-cluster API endpoint with the mounted
service-account token). Maps the store interface onto REST verbs:

  get/list/create/update/patch_merge/delete/delete_all_of + watch

Watches use the streaming watch API (chunked JSON lines). Objects are the
same manifest dicts KubeStore holds, so every controller-path component
(reconciler, LB, autoscaler) runs unmodified against a live cluster.
"""

from __future__ import annotations

import json
import logging
import queue
import random
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Iterable

from kubeai_tpu.metrics.registry import DEFAULT_METRICS, Metrics
from kubeai_tpu.operator.k8s.store import Conflict, Invalid, NotFound

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# Backoff jitter source (monkeypatchable in tests, like
# ControllerLoop._jitter): N clients retrying the same API-server brownout
# must not hammer it in lockstep waves.
_jitter = random.random

# kind -> (api_prefix, plural, namespaced)
KIND_ROUTES = {
    "Pod": ("/api/v1", "pods", True),
    "ConfigMap": ("/api/v1", "configmaps", True),
    "PersistentVolumeClaim": ("/api/v1", "persistentvolumeclaims", True),
    "Service": ("/api/v1", "services", True),
    "Node": ("/api/v1", "nodes", False),
    "Job": ("/apis/batch/v1", "jobs", True),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases", True),
    "Model": ("/apis/kubeai.org/v1", "models", True),
}


class RestKubeClient:
    def __init__(
        self,
        base_url: str,
        token: str,
        ca_file: str | None = None,
        max_attempts: int = 5,
        backoff_base: float = 0.2,
        backoff_max: float = 5.0,
        metrics: Metrics = DEFAULT_METRICS,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        # Transient-failure retry policy: 429 (honoring Retry-After),
        # 5xx, and connection errors (non-POST only — a connect error
        # mid-POST may have been processed) retry up to `max_attempts`
        # with capped exponential backoff + jitter.
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.metrics = metrics
        if ca_file:
            self._ctx = ssl.create_default_context(cafile=ca_file)
        else:
            self._ctx = ssl.create_default_context()
        self._watchers: list[tuple[tuple[str, ...] | None, queue.Queue]] = []
        self._watch_threads: list[threading.Thread] = []
        self._stop = threading.Event()

    def _sleep(self, seconds: float) -> None:
        """Interruptible backoff sleep (fake-timer tests override)."""
        self._stop.wait(seconds)

    def _backoff_delay(self, attempt: int) -> float:
        """Jittered capped exponential delay before retry `attempt`
        (1-based): min(max, base·2^(n-1)) × [0.5, 1.0)."""
        base = min(self.backoff_max, self.backoff_base * (2.0 ** (attempt - 1)))
        return base * (0.5 + 0.5 * _jitter())

    @staticmethod
    def in_cluster() -> "RestKubeClient":
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        import os

        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return RestKubeClient(
            f"https://{host}:{port}", token, ca_file=f"{SA_DIR}/ca.crt"
        )

    # -- plumbing -------------------------------------------------------------

    def _route(self, kind: str, namespace: str | None) -> str:
        if kind not in KIND_ROUTES:
            raise Invalid(f"unsupported kind {kind!r}")
        prefix, plural, namespaced = KIND_ROUTES[kind]
        if namespaced and namespace:
            return f"{prefix}/namespaces/{namespace}/{plural}"
        return f"{prefix}/{plural}"

    def _req(
        self, method: str, path: str, body: dict | None = None,
        content_type: str = "application/json",
    ) -> dict:
        """One API request with transient-failure retries. Terminal
        statuses map to the store's exception vocabulary immediately
        (404→NotFound, 409→Conflict, 400/422→Invalid); 429 retries after
        the server's Retry-After (capped), 5xx and connection errors
        retry on the capped exponential backoff. POSTs never retry
        connection errors — the server may have processed the create."""
        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        last_exc: Exception | None = None
        for attempt in range(1, self.max_attempts + 1):
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Authorization", f"Bearer {self.token}")
            req.add_header("Accept", "application/json")
            if data is not None:
                req.add_header("Content-Type", content_type)
            try:
                with urllib.request.urlopen(
                    req, context=self._ctx, timeout=30
                ) as r:
                    return json.loads(r.read() or b"{}")
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")[:500]
                if e.code == 404:
                    raise NotFound(detail)
                if e.code == 409:
                    raise Conflict(detail)
                if e.code in (400, 422):
                    raise Invalid(detail)
                last_exc = e
                if e.code == 429:
                    reason = "429"
                    delay = self._retry_after_delay(e, attempt)
                elif 500 <= e.code < 600:
                    reason = "5xx"
                    delay = self._backoff_delay(attempt)
                else:
                    raise
            except (TimeoutError, OSError) as e:
                # urllib.error.URLError subclasses OSError; both mean the
                # request may never have reached the server.
                if method == "POST":
                    raise
                last_exc = e
                reason = "connection"
                delay = self._backoff_delay(attempt)
            if attempt >= self.max_attempts or self._stop.is_set():
                break
            self.metrics.kubeclient_retries.inc(verb=method, reason=reason)
            logger.debug(
                "kube API %s %s attempt %d failed (%s), retrying in %.3fs",
                method, path, attempt, reason, delay,
            )
            self._sleep(delay)
        self.metrics.kubeclient_retry_exhausted.inc(verb=method)
        raise last_exc  # type: ignore[misc]

    def _retry_after_delay(self, e, attempt: int) -> float:
        """429 delay: the server's Retry-After when present (capped at
        the backoff ceiling), else the normal backoff schedule."""
        ra = e.headers.get("Retry-After") if e.headers is not None else None
        try:
            if ra is not None:
                return min(max(0.0, float(ra)), self.backoff_max)
        except (TypeError, ValueError):
            pass
        return self._backoff_delay(attempt)

    # -- store interface ------------------------------------------------------

    def register_validator(self, kind: str, fn) -> None:
        pass  # validation is the real API server's / webhook's job

    def create(self, obj: dict) -> dict:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default")
        return self._req("POST", self._route(obj["kind"], ns), obj)

    def get(self, kind: str, namespace: str, name: str) -> dict:
        return self._req("GET", f"{self._route(kind, namespace)}/{name}")

    def try_get(self, kind: str, namespace: str, name: str) -> dict | None:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def list(
        self,
        kind: str,
        namespace: str | None = None,
        label_selector: dict[str, str] | None = None,
    ) -> list[dict]:
        path = self._route(kind, namespace)
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            path += "?" + urllib.parse.urlencode({"labelSelector": sel})
        out = self._req("GET", path)
        items = out.get("items", [])
        for it in items:
            it.setdefault("kind", kind)
        return items

    def update(self, obj: dict) -> dict:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "default")
        return self._req(
            "PUT", f"{self._route(obj['kind'], ns)}/{meta['name']}", obj
        )

    def patch_merge(self, kind: str, namespace: str, name: str, patch: dict) -> dict:
        """Merge patch with bounded conflict retry: a 409 (server-side
        write race — conflict storms in chaos tests) re-reads the object
        (fresh rv/existence) and reapplies the same merge patch, since a
        merge patch carries no resourceVersion of its own."""
        path = f"{self._route(kind, namespace)}/{name}"
        last: Conflict | None = None
        for attempt in range(1, self.max_attempts + 1):
            try:
                return self._req(
                    "PATCH", path, patch,
                    content_type="application/merge-patch+json",
                )
            except Conflict as e:
                last = e
                if attempt >= self.max_attempts or self._stop.is_set():
                    break
                self.metrics.kubeclient_retries.inc(
                    verb="PATCH", reason="conflict"
                )
                # Fresh GET: surfaces NotFound if the object vanished
                # mid-storm and lets the server settle the racing write.
                self.get(kind, namespace, name)
                self._sleep(self._backoff_delay(attempt))
        self.metrics.kubeclient_retry_exhausted.inc(verb="PATCH")
        raise last  # type: ignore[misc]

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._req("DELETE", f"{self._route(kind, namespace)}/{name}")

    def delete_all_of(
        self, kind: str, namespace: str,
        label_selector: dict[str, str] | None = None,
    ) -> int:
        victims = self.list(kind, namespace, label_selector)
        for v in victims:
            try:
                self.delete(kind, namespace, v["metadata"]["name"])
            except NotFound:
                pass
        return len(victims)

    # -- watch ----------------------------------------------------------------

    def watch(self, kinds: Iterable[str] | None = None) -> queue.Queue:
        q: queue.Queue = queue.Queue()
        kinds_t = tuple(kinds) if kinds else tuple(KIND_ROUTES)
        self._watchers.append((kinds_t, q))
        for kind in kinds_t:
            t = threading.Thread(
                target=self._watch_loop, args=(kind, q), daemon=True
            )
            t.start()
            self._watch_threads.append(t)
        return q

    def _watch_loop(self, kind: str, q: queue.Queue) -> None:
        # Reflector bootstrap: LIST first, then watch from the list's
        # resourceVersion (controller-runtime's ListWatch semantics).
        # Starting at rv="" would mean "from now" — objects created after
        # watch() returned but before the HTTP stream established were
        # silently missed (the round-4 m0-lost-ADDED bug). The snapshot
        # arrives as a RELIST sentinel + synthetic MODIFIEDs, the same
        # shape consumers already resync on after a 410.
        rv = self._relist_into(kind, q)
        failures = 0  # consecutive broken connections → backoff exponent
        while not self._stop.is_set():
            path = self._route(kind, None) + "?watch=true"
            if rv:
                path += f"&resourceVersion={rv}"
            url = self.base_url + path
            req = urllib.request.Request(url)
            req.add_header("Authorization", f"Bearer {self.token}")
            try:
                with urllib.request.urlopen(
                    req, context=self._ctx, timeout=300
                ) as r:
                    for line in r:
                        if self._stop.is_set():
                            return
                        try:
                            ev = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        # A live event stream means the server is healthy:
                        # the next reconnect starts the schedule over.
                        failures = 0
                        obj = ev.get("object") or {}
                        obj.setdefault("kind", kind)
                        rv = (obj.get("metadata") or {}).get(
                            "resourceVersion", rv
                        )
                        q.put((ev.get("type", "MODIFIED"), obj))
            except urllib.error.HTTPError as e:
                e.read()
                if e.code == 410:
                    # 410 Gone: our resourceVersion was compacted away.
                    # Retrying with the stale rv would 410 forever; the
                    # protocol answer is RELIST. A "RELIST" sentinel goes
                    # first — creations/updates in the gap are subsumed
                    # by the snapshot's synthetic MODIFIEDs, but
                    # DELETIONS leave no object to emit, so consumers
                    # must full-resync on the sentinel. The relist
                    # retries with backoff until it succeeds: resuming
                    # "from now" after a failed relist would silently
                    # drop the gap.
                    rv = self._relist_into(kind, q)
                else:
                    failures = self._watch_wait(kind, failures)
            except OSError:
                failures = self._watch_wait(kind, failures)

    def _watch_wait(self, kind: str, failures: int) -> int:
        """Capped exponential backoff + jitter between watch reconnects
        (the fixed 2 s sleep made every client re-dial a browned-out
        API server in lockstep). Returns the grown failure count."""
        failures = min(failures + 1, 16)
        self.metrics.kubeclient_watch_reconnects.inc(kind=kind)
        delay = min(30.0, 0.5 * (2.0 ** (failures - 1)))
        self._sleep(delay * (0.5 + 0.5 * _jitter()))
        return failures

    def _relist_into(self, kind: str, q: queue.Queue) -> str:
        failures = 0
        while not self._stop.is_set():
            try:
                out = self._req("GET", self._route(kind, None))
                break
            except (OSError, NotFound):
                failures = self._watch_wait(kind, failures)
        else:
            return ""
        q.put(("RELIST", {"kind": kind, "metadata": {}}))
        for it in out.get("items", []):
            it.setdefault("kind", kind)
            q.put(("MODIFIED", it))
        return (out.get("metadata") or {}).get("resourceVersion", "")
