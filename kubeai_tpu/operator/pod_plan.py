"""Pod planning: desired-vs-observed diff with surge-based rollouts.

Behavioral parity with the reference's planner
(reference: internal/modelcontroller/pod_plan.go:28-156):
  - rollout detection via the pod-hash label of the rendered spec
  - +surge desired replicas while any out-of-date Pod exists
  - out-of-date Pods that are NOT ready are recreated immediately;
    ready out-of-date Pods are recreated only when all Pods are ready
    (one per reconcile), and the surge Pod is not recreated at the end
  - deletion priority: not-ready → unscheduled → old-hash → youngest
    (reference: pod_plan.go:215-243)
  - delete before create, to avoid unnecessary node scale-ups
"""

from __future__ import annotations

import copy
import dataclasses

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator import k8sutils, slicegroup
from kubeai_tpu.operator.k8s.store import KubeStore, NotFound, Conflict


@dataclasses.dataclass
class PodPlan:
    model: Model
    to_create: list[dict]
    to_delete: list[dict]
    to_remain: list[dict]
    details: list[str]
    # Multi-host: the member pods of each slice group being deleted,
    # one inner list per group, ordered broken-groups-first. Members
    # also appear flattened in `to_delete` (so inspection and counting
    # stay uniform); `execute()` routes each inner list through the
    # governor's atomic group-delete — one disruption-budget unit per
    # group — and skips those members in the per-pod loop. Empty for
    # single-host plans, keeping them identical to the pre-group world.
    to_delete_groups: list[list[dict]] = dataclasses.field(
        default_factory=list
    )
    # Not-ready out-of-date pods this plan delete-and-replaced: the
    # controller counts such a pass toward the model's repair-backoff
    # streak (a rollout whose pods never go Ready must retry on the
    # same exponential cadence as any other repair loop).
    churned_not_ready: int = 0
    # Multi-host: group indices torn down purely for hash drift this
    # pass (the canary-paced kind; broken-group repairs not included).
    rolled_stale_groups: list[str] = dataclasses.field(default_factory=list)

    def contains_actions(self) -> bool:
        return bool(self.to_create or self.to_delete)

    def execute(self, store: KubeStore, model_obj: dict, governor=None) -> bool:
        """Apply the plan through the actuation governor (lease fencing
        + disruption budgets for healthy pods). Returns True if anything
        changed. A budget-refused deletion simply waits for a later
        window; the fence raising `NotLeader` aborts the whole batch."""
        from kubeai_tpu.operator import governor as governor_mod

        gov = governor if governor is not None else governor_mod.PERMISSIVE
        # The batch is fenced as a unit: an expired leader writes nothing.
        gov.check_fence()
        changed = False
        model_name = self.model.name
        # Delete before create (reference: pod_plan.go:179). Slice
        # groups go first, atomically: the whole group is one replica,
        # so it pays ONE budget unit — and only when every member was
        # healthy (a group with any broken member is already disrupted;
        # replacing it is repair).
        grouped: set[str] = set()
        for members in self.to_delete_groups:
            if not members:
                continue
            budgeted = all(
                k8sutils.pod_is_ready(p)
                and k8sutils.pod_disruption_reason(p) is None
                for p in members
            )
            names = [p["metadata"]["name"] for p in members]
            grouped.update(names)
            if gov.delete_group(
                store,
                members[0]["metadata"]["namespace"],
                names,
                model=model_name,
                budgeted=budgeted,
            ):
                changed = True
        for pod in self.to_delete:
            if pod["metadata"]["name"] in grouped:
                continue
            # Deleting a pod that is already broken (not ready, or
            # disrupted) is repair; only healthy serving capacity
            # consumes disruption budget.
            budgeted = (
                k8sutils.pod_is_ready(pod)
                and k8sutils.pod_disruption_reason(pod) is None
            )
            if gov.delete_pod(
                store,
                pod["metadata"]["namespace"],
                pod["metadata"]["name"],
                model=model_name,
                budgeted=budgeted,
            ):
                changed = True
        for pod in self.to_create:
            pod = copy.deepcopy(pod)
            k8sutils.set_owner_reference(model_obj, pod)
            try:
                gov.create_pod(store, pod, model=model_name)
            except Conflict:
                pass
            changed = True
        return changed


def sort_pods_by_deletion_order(pods: list[dict], expected_hash: str) -> list[dict]:
    """Lower index = deleted first (reference: pod_plan.go:215-243)."""

    def key(pod: dict):
        return (
            # Capacity-planner preemption victims first: when the fleet
            # planner shrinks this model to free chips for a higher
            # scheduling class, the pods that die must be exactly its
            # picks, not whichever pod the generic ordering reaches.
            # With no plan present every pod lacks the annotation and
            # the ordering below is unchanged.
            not k8sutils.get_annotation(pod, md.PLANNER_PREEMPT_ANNOTATION),
            # Disrupted pods (spot preemption / eviction / Failed) next:
            # they serve nothing and their node may already be gone.
            k8sutils.pod_disruption_reason(pod) is None,
            k8sutils.pod_is_ready(pod),  # not ready first
            k8sutils.pod_is_scheduled(pod),  # unscheduled first
            k8sutils.get_label(pod, md.POD_HASH_LABEL) == expected_hash,  # old hash first
            -(pod.get("metadata", {}).get("creationTimestamp") or 0),  # youngest first
        )

    return sorted(pods, key=key)


def _clone_pod_template(pod: dict) -> dict:
    """Rebuild a creatable template from a live pod. A rollback must
    re-create the *old* version, whose rendered spec is no longer
    derivable from the current Model spec — the surviving pinned-hash
    pod is the only remaining record of it. Identity and runtime-only
    metadata (name/uid/owner refs/planner marks) and status are
    stripped; labels, annotations, and the spec carry over."""
    tpl = {
        "apiVersion": pod.get("apiVersion", "v1"),
        "kind": pod.get("kind", "Pod"),
        "metadata": copy.deepcopy(pod.get("metadata", {})),
        "spec": copy.deepcopy(pod.get("spec", {})),
    }
    meta = tpl["metadata"]
    for field in ("name", "uid", "resourceVersion", "creationTimestamp",
                  "generateName", "ownerReferences", "deletionTimestamp"):
        meta.pop(field, None)
    anns = meta.get("annotations")
    if anns:
        anns.pop(md.PLANNER_PREEMPT_ANNOTATION, None)
    tpl["spec"].pop("nodeName", None)
    tpl.pop("status", None)
    return tpl


def calculate_pod_plan(
    all_pods: list[dict],
    model: Model,
    desired_pod: dict,
    surge: int,
    *,
    pinned_hash: str | None = None,
    max_new: int | None = None,
    recreate_budget: int | None = None,
) -> PodPlan:
    """Compute the create/delete sets for one reconcile pass.

    `desired_pod` is the fully rendered Pod (after JSON patches); its hash
    determines up-to-dateness.

    Progressive-rollout seams (kubeai_tpu/operator/rollout), all
    defaulting to the classic surge plan:
      - `pinned_hash`: rollback — the judge condemned the rendered spec.
        When a pod of the pinned hash survives, the pinned version
        becomes the desired one (its template cloned from the survivor)
        and rendered-hash pods are torn down as out-of-date.
      - `max_new`: canary/ramp cap — at most this many rendered-hash
        pods may exist after the pass; remaining out-of-date pods are
        deliberately left serving until the controller raises the cap.
      - `recreate_budget`: not-ready out-of-date pods recreated per
        pass. Defaults to max(1, surge): a rollout whose new pods never
        go Ready must not churn the whole out-of-date set every
        reconcile (the controller's repair backoff stretches the retry
        cadence on top).
    """
    desired_pod = copy.deepcopy(desired_pod)
    expected_hash = k8sutils.pod_hash(desired_pod["spec"])
    target_hash = expected_hash
    if pinned_hash and pinned_hash != expected_hash:
        survivor = next(
            (p for p in all_pods
             if k8sutils.get_label(p, md.POD_HASH_LABEL) == pinned_hash),
            None,
        )
        # With no survivor the rendered spec is all that's left to
        # serve with; the pin only steers while the old version exists.
        if survivor is not None:
            desired_pod = _clone_pod_template(survivor)
            target_hash = pinned_hash
    desired_pod["metadata"].pop("name", None)
    desired_pod["metadata"]["generateName"] = f"model-{model.name}-{target_hash}-"
    k8sutils.set_label(desired_pod, md.POD_HASH_LABEL, target_hash)
    # The controller ownerReference is set ONCE, by PodPlan.execute
    # (k8sutils.set_owner_reference) — a second controller=true ref here
    # would be rejected by a real apiserver. Garbage collection of pods
    # on Model deletion rides that reference (store/envtest implement
    # the cluster GC's uid-matched cascade).

    pods = sort_pods_by_deletion_order(all_pods, target_hash)

    ready_all = sum(1 for p in pods if k8sutils.pod_is_ready(p))
    out_of_date = [
        p for p in pods
        if k8sutils.get_label(p, md.POD_HASH_LABEL) != target_hash
    ]
    up_to_date = len(pods) - len(out_of_date)

    # Canary cap: how many more target-hash pods this pass may mint.
    # None = unlimited (classic rollout). Rollback ignores the cap —
    # pinned pods are the good ones.
    allowed_new = None
    if max_new is not None and target_hash == expected_hash:
        allowed_new = max(0, max_new - up_to_date)

    details: list[str] = []
    to_create: list[dict] = []
    to_delete: list[dict] = []
    remainder = {p["metadata"]["name"]: p for p in pods}

    def mark_delete(p: dict) -> None:
        remainder.pop(p["metadata"]["name"], None)
        to_delete.append(p)

    desired_replicas = model.spec.replicas or 0
    if out_of_date:
        if allowed_new is None:
            desired_replicas += surge
        else:
            # Capped rollout: the surge allowance must persist while a
            # minted target-hash pod is still booting — collapsing it
            # the moment allowed_new hits 0 would delete the very pod
            # the canary step just created (not-ready sorts first in
            # deletion order) and oscillate forever. It is also clamped
            # to the cap so a surge > 1 cannot mint more target-hash
            # pods than the step admits.
            pending_new = up_to_date - sum(
                1 for p in pods
                if k8sutils.get_label(p, md.POD_HASH_LABEL) == target_hash
                and k8sutils.pod_is_ready(p)
            )
            if allowed_new > 0 or pending_new > 0:
                desired_replicas += min(surge, max(allowed_new, pending_new))

    diff = len(pods) - desired_replicas
    if diff < 0:
        details.append(f"creating {-diff} pods")
        for _ in range(-diff):
            to_create.append(copy.deepcopy(desired_pod))
    elif diff > 0:
        details.append(f"deleting {diff} pods")
        for p in pods[:diff]:
            mark_delete(p)

    recreated = 0
    churned = 0
    churn_budget = (
        max(1, surge) if recreate_budget is None else max(0, recreate_budget)
    )
    minted = len(to_create)  # target-hash pods minted this pass so far
    surge_cutoff = len(out_of_date) - surge
    for p in out_of_date:
        if p["metadata"]["name"] not in remainder:
            continue  # already being deleted above
        if allowed_new is not None and minted >= allowed_new:
            break  # canary cap reached; the rest keep serving old hash
        if not k8sutils.pod_is_ready(p):
            # Bounded: recreating EVERY not-ready out-of-date pod in
            # one pass churns create/delete each reconcile when the new
            # version never goes Ready.
            if churned >= churn_budget:
                continue
            churned += 1
            details.append(
                f"out-of-date pod {p['metadata']['name']} not ready, recreating now"
            )
            mark_delete(p)
            if recreated < surge_cutoff:
                to_create.append(copy.deepcopy(desired_pod))
                recreated += 1
                minted += 1
            continue
        if ready_all == desired_replicas:
            details.append(
                f"all pods ready, recreating out-of-date pod {p['metadata']['name']}"
            )
            mark_delete(p)
            if recreated < surge_cutoff:
                to_create.append(copy.deepcopy(desired_pod))
                recreated += 1
                minted += 1
            break  # one ready pod per reconcile: gradual rollout

    return PodPlan(
        model=model,
        to_create=to_create,
        to_delete=to_delete,
        to_remain=list(remainder.values()),
        details=details,
        churned_not_ready=churned,
    )


def calculate_group_pod_plan(
    all_pods: list[dict],
    model: Model,
    render_group,  # (group_idx) -> list[pod dict] with FIXED names
    num_hosts: int,
    *,
    max_hash_recreates: int | None = None,
) -> PodPlan:
    """Pod-group planner for multi-host replicas: replica g is the set of
    Pods model-{name}-g{g}-h{0..num_hosts-1}. Fixed names (stable
    hostnames feed the DCN coordinator address), so the diff is by name:
    missing members are created, hash-stale or surplus members deleted
    (delete-before-create; the recreate lands next reconcile). A group is
    replaced as a unit — jax.distributed cannot survive a partial host
    swap — and there is no surge (a surge group would double TPU-slice
    capacity transiently; recreate-in-place instead).

    `max_hash_recreates` (progressive rollouts) bounds how many groups
    that are stale ONLY by hash drift are torn down per pass — the
    canary rolls one whole slice-group at a time, lowest group index
    first. Groups with missing members are broken, not canaries: they
    are always recreated. None = unlimited (the classic plan,
    byte-identical)."""
    desired: dict[str, dict] = {}
    for g in range(model.spec.replicas or 0):
        for pod in render_group(g):
            expected = k8sutils.pod_hash(pod["spec"])
            k8sutils.set_label(pod, md.POD_HASH_LABEL, expected)
            desired[pod["metadata"]["name"]] = pod

    existing = {p["metadata"]["name"]: p for p in all_pods}
    details: list[str] = []
    to_create: list[dict] = []
    to_delete: list[dict] = []

    def group_of(pod: dict) -> str:
        return k8sutils.get_label(pod, md.POD_GROUP_LABEL) or "?"

    # A group is STALE when it has surviving members AND any member is
    # missing or hash-mismatched: tear it down whole this pass and
    # recreate fresh next pass (a fresh Pod must not join a coordinator
    # that's being replaced). A group with NO existing members is simply
    # new: create all its Pods now.
    members_existing: dict[str, list[dict]] = {}
    members_bad: set[str] = set()
    members_missing: set[str] = set()
    for name, pod in desired.items():
        g = group_of(pod)
        cur = existing.get(name)
        if cur is not None:
            members_existing.setdefault(g, []).append(cur)
            if k8sutils.get_label(cur, md.POD_HASH_LABEL) != k8sutils.get_label(
                pod, md.POD_HASH_LABEL
            ):
                members_bad.add(g)
        else:
            members_bad.add(g)
            members_missing.add(g)
    stale_groups = {g for g in members_bad if g in members_existing}
    # Groups stale ONLY by hash drift (every member present, some
    # hash-mismatched) — the canary-paced kind, lowest index first.
    hash_only = sorted(
        (g for g in stale_groups if g not in members_missing),
        key=lambda g: (int(g) if g.isdigit() else 1 << 30, g),
    )
    if max_hash_recreates is not None:
        # Canary: at most `max_hash_recreates` hash-drift groups roll
        # per pass; broken groups always recreate.
        for g in hash_only[max_hash_recreates:]:
            stale_groups.discard(g)
        hash_only = hash_only[:max_hash_recreates]

    for name, pod in desired.items():
        g = group_of(pod)
        cur = existing.get(name)
        if g in stale_groups:
            if cur is not None:
                details.append(f"group {g} stale, deleting {name}")
                to_delete.append(cur)
        elif cur is None:
            details.append(f"creating {name}")
            to_create.append(pod)

    for name, cur in existing.items():
        if name not in desired:
            details.append(f"deleting surplus {name}")
            to_delete.append(cur)

    deleted = {p["metadata"]["name"] for p in to_delete}
    remain = [
        p for n, p in existing.items() if n not in deleted and n in desired
    ]
    # Deletions execute in GROUP units: join the flat delete list back
    # into member lists per group index (stale teardown and surplus
    # scale-down alike), broken groups first — they serve nothing and
    # cost no budget — then youngest (highest index) first, matching
    # the single-host youngest-first scale-down bias. Pods without
    # group labels (shouldn't happen under this planner, but a manual
    # pod could drift in) stay individual deletions.
    delete_groups = slicegroup.group_pods(to_delete)

    def _group_order(item: tuple[int, list[dict]]):
        g, members = item
        broken = any(slicegroup.member_broken(p) for p in members)
        return (not broken, -g)

    to_delete_groups = [
        members for _, members in sorted(delete_groups.items(),
                                         key=_group_order)
    ]
    return PodPlan(
        model=model,
        to_create=to_create,
        to_delete=to_delete,
        to_remain=remain,
        details=details,
        to_delete_groups=to_delete_groups,
        rolled_stale_groups=hash_only,
    )
