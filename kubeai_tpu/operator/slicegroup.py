"""Slice groups: multi-host replicas as first-class atomic units.

One multi-host replica is a *process group* of N host pods spanning one
ICI-connected TPU slice (the unit of scale on TPU pods — MLPerf-0.6 on
TPU-v3 Pods; Limits of Concurrency on Google TPUs). The renderer stamps
every member with the group index (`POD_GROUP_LABEL`) and host index
(`POD_HOST_LABEL`); this module is the ONE place that joins those labels
back into group objects, so the reconciler, load balancer, fleet
aggregator, and capacity planner all agree on what a group is and when
it is healthy.

The atomicity contract every consumer enforces through these helpers:

- a group is Ready only when ALL members are Ready — no partial group
  is ever surfaced as serving capacity;
- one broken member marks the WHOLE group broken — repair replaces the
  group, never one host (lockstep multihost cannot survive a member
  restart with fresh addresses);
- deletions of group members route through the governor's group-delete
  helper and consume ONE disruption-budget unit per group, not one per
  pod (`scripts/check_actuation_paths.py` gates this).
"""

from __future__ import annotations

import dataclasses

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.operator import k8sutils


@dataclasses.dataclass(frozen=True, order=True)
class GroupKey:
    """Identity of one slice group: (model, group index). Hashable and
    ordered so groups sort deterministically in plans and snapshots."""

    model: str
    group: int

    def __str__(self) -> str:
        return f"{self.model}/g{self.group}"


def group_index(pod: dict) -> int | None:
    """The pod's group index, or None for single-host (ungrouped) pods.
    A malformed label counts as ungrouped rather than raising — one bad
    pod must not take down a reconcile pass."""
    raw = k8sutils.get_label(pod, md.POD_GROUP_LABEL)
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


def host_index(pod: dict) -> int | None:
    """The pod's host index within its group, or None when unlabeled."""
    raw = k8sutils.get_label(pod, md.POD_HOST_LABEL)
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


def group_size(pod: dict) -> int | None:
    """Expected member count of the pod's group (the renderer stamps
    `POD_GROUP_SIZE_LABEL` on every member), or None when unlabeled —
    older pods rendered before the label existed fall back to counting
    present members."""
    raw = k8sutils.get_label(pod, md.POD_GROUP_SIZE_LABEL)
    if raw is None:
        return None
    try:
        n = int(raw)
    except (TypeError, ValueError):
        return None
    return n if n >= 1 else None


def expected_size(members: list[dict], default: int = 0) -> int:
    """Best-known expected size of a group from its members' size
    labels (max wins — a rollout changing the size renders fresh
    labels), else `default`, else the member count itself."""
    sizes = [s for s in (group_size(p) for p in members) if s is not None]
    if sizes:
        return max(sizes)
    return default or len(members)


def group_pods(pods: list[dict]) -> dict[int, list[dict]]:
    """Join member pods into groups by group index, members sorted by
    host index (host 0 — the coordinator — first). Ungrouped pods are
    excluded; use `ungrouped_pods` for those."""
    groups: dict[int, list[dict]] = {}
    for pod in pods:
        g = group_index(pod)
        if g is None:
            continue
        groups.setdefault(g, []).append(pod)
    for members in groups.values():
        members.sort(key=lambda p: (host_index(p) or 0,
                                    (p.get("metadata") or {}).get("name", "")))
    return groups


def ungrouped_pods(pods: list[dict]) -> list[dict]:
    """Pods with no group label — the single-host world."""
    return [p for p in pods if group_index(p) is None]


def coordinator_pod(members: list[dict]) -> dict | None:
    """Host 0 of a group — the lockstep coordinator and the ONE
    endpoint the load balancer routes to."""
    for pod in members:
        if host_index(pod) == 0:
            return pod
    return None


def group_complete(members: list[dict], num_hosts: int) -> bool:
    """All N hosts exist (regardless of readiness)."""
    return len(members) >= num_hosts


def group_ready(members: list[dict], num_hosts: int) -> bool:
    """The group is serving capacity: complete AND every member Ready
    AND no member disrupted or terminating. Anything less is not a
    smaller group — it is no group."""
    if not group_complete(members, num_hosts):
        return False
    return not any(member_broken(p) for p in members)


def member_broken(pod: dict) -> bool:
    """One member in a state that poisons the whole group: not Ready,
    disrupted (preempted/evicted), or already terminating."""
    return (
        not k8sutils.pod_is_ready(pod)
        or k8sutils.pod_disruption_reason(pod) is not None
        or k8sutils.pod_is_terminating(pod)
    )


def group_broken(members: list[dict], num_hosts: int) -> bool:
    """True when the group needs whole-group repair: a member is
    missing, or any present member is broken. (A brand-new group that
    is merely still booting is NOT broken — callers that repair should
    classify members with `classify_pod_failure` first; this predicate
    answers routability, not repair.)"""
    return not group_ready(members, num_hosts)
