"""Small k8s helpers (reference: internal/k8sutils)."""

from __future__ import annotations

import hashlib
import json
import logging
import re

logger = logging.getLogger(__name__)


def pod_hash(pod_spec: dict) -> str:
    """Stable hash of a rendered Pod spec — drives rollout detection
    (reference: internal/k8sutils/pods.go:26-42, FNV of dumped spec).

    Uses a canonical JSON dump + FNV-1a 64; only the first 8 hex chars are
    kept for label friendliness (same shape as the reference's %x of FNV32)."""
    dumped = json.dumps(pod_spec, sort_keys=True, separators=(",", ":"))
    return f"{_fnv1a64(dumped.encode()) & 0xFFFFFFFF:x}"


def string_hash(s: str) -> str:
    """(reference: internal/k8sutils/pods.go:45-49)"""
    return f"{_fnv1a64(s.encode()) & 0xFFFFFFFF:x}"


def _fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def pod_is_ready(pod: dict) -> bool:
    """(reference: internal/k8sutils/pods.go PodIsReady)"""
    for cond in (pod.get("status") or {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def pod_is_scheduled(pod: dict) -> bool:
    for cond in (pod.get("status") or {}).get("conditions", []):
        if cond.get("type") == "PodScheduled":
            return cond.get("status") == "True"
    return bool((pod.get("spec") or {}).get("nodeName"))


def container_is_ready(pod: dict, container_name: str) -> bool:
    """(reference: internal/k8sutils/pods.go:60-66)"""
    for cs in (pod.get("status") or {}).get("containerStatuses", []):
        if cs.get("name") == container_name:
            return bool(cs.get("ready"))
    return False


# -- pod-failure classification (self-healing reconcile pass) -----------------
#
# Stable reason vocabulary: these strings surface in Model.status.conditions
# (Degraded.reason) and the kubeai_controller_pod_replacements_total metric's
# `reason` label — tests assert on them, change requires a doc update
# (docs/concepts/resilience.md).

REASON_SPOT_PREEMPTION = "SpotPreemption"
REASON_EVICTED = "Evicted"
REASON_DISRUPTED = "Disrupted"
REASON_POD_FAILED = "PodFailed"
REASON_CRASHLOOP = "CrashLoopBackOff"
REASON_STUCK_PENDING = "StuckPending"

# status.reason / DisruptionTarget-condition reasons that mean the node
# (or scheduler) took the pod — GKE spot/preemptible reclaim lands here.
_PREEMPTION_STATUS_REASONS = frozenset(
    ("Preempted", "Shutdown", "NodeShutdown", "Terminated", "NodeLost")
)
_PREEMPTION_CONDITION_REASONS = frozenset(
    (
        "PreemptionByScheduler",
        "TerminationByKubelet",
        "DeletionByPodGC",
        "NodeShutdown",
    )
)


def pod_phase(pod: dict) -> str:
    return str((pod.get("status") or {}).get("phase") or "")


def pod_is_terminating(pod: dict) -> bool:
    """deletionTimestamp set: already on its way out — never a repair
    candidate (deleting it again would just race the kubelet)."""
    return bool((pod.get("metadata") or {}).get("deletionTimestamp"))


def pod_disruption_reason(pod: dict) -> str | None:
    """Classify an externally-killed pod: spot preemption / node
    shutdown, API eviction, or a plain Failed phase. None when the pod
    shows no disruption signal (including when status is missing)."""
    status = pod.get("status") or {}
    raw = str(status.get("reason") or "")
    if raw in _PREEMPTION_STATUS_REASONS:
        return REASON_SPOT_PREEMPTION
    if raw == "Evicted":
        return REASON_EVICTED
    for cond in status.get("conditions") or []:
        if (
            cond.get("type") == "DisruptionTarget"
            and cond.get("status") == "True"
        ):
            cr = str(cond.get("reason") or "")
            if cr in _PREEMPTION_CONDITION_REASONS:
                return REASON_SPOT_PREEMPTION
            if cr == "EvictionByEvictionAPI":
                return REASON_EVICTED
            # Unknown disruption reasons are still disruptions — the pod
            # is being taken, whatever the API calls it this release.
            return REASON_DISRUPTED
    if status.get("phase") == "Failed":
        return REASON_POD_FAILED
    return None


def pod_is_crashlooping(pod: dict, restart_threshold: int = 3) -> bool:
    """CrashLoopBackOff waiting state on any container, or a restart
    count at/over the threshold (covers watchdog exit loops that kubelet
    has not yet labeled CrashLoopBackOff). containerStatuses entries
    with no `state` contribute only their restartCount."""
    for cs in (pod.get("status") or {}).get("containerStatuses") or []:
        state = cs.get("state") or {}
        waiting = state.get("waiting") or {}
        if waiting.get("reason") == "CrashLoopBackOff":
            return True
        try:
            restarts = int(cs.get("restartCount") or 0)
        except (TypeError, ValueError):
            restarts = 0
        if restart_threshold > 0 and restarts >= restart_threshold:
            return True
    return False


def pod_stuck_pending(pod: dict, now: float, deadline_s: float) -> bool:
    """Pending, unscheduled, and older than the schedule deadline — the
    cluster is never going to place it (typical on a reclaimed spot node
    pool); delete-and-replace rolls fresh scheduling dice."""
    if deadline_s <= 0:
        return False
    if pod_phase(pod) != "Pending" or pod_is_scheduled(pod):
        return False
    created = (pod.get("metadata") or {}).get("creationTimestamp")
    if not isinstance(created, (int, float)):
        return False
    return (now - float(created)) > deadline_s


def classify_pod_failure(
    pod: dict,
    now: float,
    pending_deadline_s: float = 300.0,
    restart_threshold: int = 3,
) -> str | None:
    """The pod-health pass's single entry point: returns a stable repair
    reason (REASON_*) when the pod should be delete-and-replaced, else
    None. Terminating pods are NEVER classified as repairable."""
    if pod_is_terminating(pod):
        return None
    reason = pod_disruption_reason(pod)
    if reason is not None:
        return reason
    if pod_is_crashlooping(pod, restart_threshold=restart_threshold):
        return REASON_CRASHLOOP
    if pod_stuck_pending(pod, now, pending_deadline_s):
        return REASON_STUCK_PENDING
    return None


# -- chip inventory (fleet telemetry: kubeai_tpu/fleet/aggregator;
#    chip budget: kubeai_tpu/fleet/planner) -----------------------------------

TPU_RESOURCE = "google.com/tpu"
TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"


def parse_chip_quantity(v, where: str = "") -> int:
    """Parse one `google.com/tpu` resource quantity. TPU chips are whole
    devices, so anything that isn't a non-negative integer (after
    tolerating the `4.0` float spelling) is malformed: warn and count 0
    rather than raising — a single bad pod manifest must not blind the
    whole chip inventory."""
    if v is None:
        return 0
    try:
        f = float(v)
    except (TypeError, ValueError):
        logger.warning(
            "malformed %s quantity %r%s: counting 0 chips",
            TPU_RESOURCE, v, f" on {where}" if where else "",
        )
        return 0
    if f < 0 or f != int(f):
        logger.warning(
            "non-integral %s quantity %r%s: counting 0 chips",
            TPU_RESOURCE, v, f" on {where}" if where else "",
        )
        return 0
    return int(f)


def pod_chip_count(pod: dict) -> int:
    """Total `google.com/tpu` chips this pod requests across its
    containers (limits win over requests, per scheduler semantics).
    Malformed manifests — resources that aren't mappings, quantities
    that aren't integers — contribute 0 with a warning, never an
    exception."""
    name = ((pod.get("metadata") or {}).get("name")) or "?"
    total = 0
    for c in ((pod.get("spec") or {}).get("containers") or []):
        if not isinstance(c, dict):
            continue
        res = c.get("resources")
        if not isinstance(res, dict):
            if res is not None:
                logger.warning(
                    "pod %s: container resources is %s, not a mapping; "
                    "counting 0 chips", name, type(res).__name__,
                )
            continue
        limits = res.get("limits")
        requests = res.get("requests")
        v = None
        if isinstance(limits, dict):
            v = limits.get(TPU_RESOURCE)
        if v is None and isinstance(requests, dict):
            v = requests.get(TPU_RESOURCE)
        total += parse_chip_quantity(v, where=f"pod {name}")
    return total


def _slice_shape(selectors: dict, chips: int) -> str:
    accel = selectors.get(TPU_ACCELERATOR_LABEL)
    topo = selectors.get(TPU_TOPOLOGY_LABEL)
    if accel and topo:
        return f"{accel}/{topo}"
    if accel:
        return str(accel)
    if topo:
        return f"tpu/{topo}"
    if chips:
        return f"tpu-{chips}"
    return "cpu"


_TOPOLOGY_RE = re.compile(r"^\d+x\d+(?:x\d+)?$")


def topology_chip_count(topo: str) -> int | None:
    """Total chips in an ICI topology string: "4x4" -> 16,
    "4x4x4" -> 64. On a multi-host slice this is the chips of the WHOLE
    slice, not of one member VM — chips-per-node times hosts. Returns
    None (with a warning) on malformed shapes so callers fall back to
    per-node counting instead of inventing a number."""
    if not topo or not isinstance(topo, str):
        return None
    if not _TOPOLOGY_RE.match(topo):
        logger.warning("malformed TPU topology %r: ignoring", topo)
        return None
    n = 1
    for dim in topo.split("x"):
        n *= int(dim)
    if n < 1:
        logger.warning("degenerate TPU topology %r: ignoring", topo)
        return None
    return n


def node_slice_chip_count(node: dict) -> int:
    """Chips of the whole ICI slice this Node belongs to: the topology
    product when the node carries a parseable GKE topology label, else
    the node's own allocatable chips. On a 4x4x4 slice served by
    sixteen 4-chip VMs this is 64, not 4 — the difference between
    pricing a slice and pricing one member VM."""
    labels = (node.get("metadata") or {}).get("labels") or {}
    per_slice = topology_chip_count(labels.get(TPU_TOPOLOGY_LABEL, ""))
    own = node_chip_capacity(node)
    if per_slice is not None and per_slice >= own:
        return per_slice
    return own


def pod_slice_shape(pod: dict) -> str:
    """Human-stable slice-shape key for the chip inventory: the GKE TPU
    accelerator + ICI topology node selectors when present (e.g.
    `tpu-v5-lite-podslice/2x4`), else the chip count alone (`tpu-4`),
    else `cpu`."""
    sel = (pod.get("spec") or {}).get("nodeSelector") or {}
    return _slice_shape(sel, pod_chip_count(pod))


def node_chip_capacity(node: dict) -> int:
    """`google.com/tpu` chips one Node offers (allocatable wins over
    capacity — that's what the scheduler can actually place). Malformed
    quantities count 0 with a warning, like pod_chip_count."""
    name = ((node.get("metadata") or {}).get("name")) or "?"
    status = node.get("status") or {}
    for key in ("allocatable", "capacity"):
        res = status.get(key)
        if isinstance(res, dict) and TPU_RESOURCE in res:
            return parse_chip_quantity(
                res.get(TPU_RESOURCE), where=f"node {name}"
            )
    return 0


def node_slice_shape(node: dict) -> str:
    """Slice-shape key for one Node, from its GKE TPU labels (same
    vocabulary as pod_slice_shape, so pod demand and node budget join)."""
    labels = (node.get("metadata") or {}).get("labels") or {}
    return _slice_shape(labels, node_chip_capacity(node))


def job_is_complete(job: dict) -> bool:
    """(reference: internal/k8sutils/jobs.go)"""
    for cond in (job.get("status") or {}).get("conditions", []):
        if cond.get("type") == "Complete" and cond.get("status") == "True":
            return True
    return False


def set_label(obj: dict, key: str, value: str) -> None:
    obj.setdefault("metadata", {}).setdefault("labels", {})[key] = value


def get_label(obj: dict, key: str) -> str | None:
    return ((obj.get("metadata") or {}).get("labels") or {}).get(key)


def get_annotation(obj: dict, key: str) -> str | None:
    return ((obj.get("metadata") or {}).get("annotations") or {}).get(key)


def set_owner_reference(owner: dict, obj: dict, controller: bool = True) -> None:
    """(controller-runtime SetControllerReference equivalent)"""
    m = obj.setdefault("metadata", {})
    refs = m.setdefault("ownerReferences", [])
    refs.append(
        {
            "apiVersion": owner.get("apiVersion", "v1"),
            "kind": owner.get("kind", ""),
            "name": (owner.get("metadata") or {}).get("name", ""),
            "uid": (owner.get("metadata") or {}).get("uid", ""),
            "controller": controller,
        }
    )


def is_owned_by(obj: dict, owner_uid: str) -> bool:
    for ref in ((obj.get("metadata") or {}).get("ownerReferences") or []):
        if ref.get("uid") == owner_uid:
            return True
    return False
