"""Small k8s helpers (reference: internal/k8sutils)."""

from __future__ import annotations

import hashlib
import json


def pod_hash(pod_spec: dict) -> str:
    """Stable hash of a rendered Pod spec — drives rollout detection
    (reference: internal/k8sutils/pods.go:26-42, FNV of dumped spec).

    Uses a canonical JSON dump + FNV-1a 64; only the first 8 hex chars are
    kept for label friendliness (same shape as the reference's %x of FNV32)."""
    dumped = json.dumps(pod_spec, sort_keys=True, separators=(",", ":"))
    return f"{_fnv1a64(dumped.encode()) & 0xFFFFFFFF:x}"


def string_hash(s: str) -> str:
    """(reference: internal/k8sutils/pods.go:45-49)"""
    return f"{_fnv1a64(s.encode()) & 0xFFFFFFFF:x}"


def _fnv1a64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def pod_is_ready(pod: dict) -> bool:
    """(reference: internal/k8sutils/pods.go PodIsReady)"""
    for cond in (pod.get("status") or {}).get("conditions", []):
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return False


def pod_is_scheduled(pod: dict) -> bool:
    for cond in (pod.get("status") or {}).get("conditions", []):
        if cond.get("type") == "PodScheduled":
            return cond.get("status") == "True"
    return bool((pod.get("spec") or {}).get("nodeName"))


def container_is_ready(pod: dict, container_name: str) -> bool:
    """(reference: internal/k8sutils/pods.go:60-66)"""
    for cs in (pod.get("status") or {}).get("containerStatuses", []):
        if cs.get("name") == container_name:
            return bool(cs.get("ready"))
    return False


def job_is_complete(job: dict) -> bool:
    """(reference: internal/k8sutils/jobs.go)"""
    for cond in (job.get("status") or {}).get("conditions", []):
        if cond.get("type") == "Complete" and cond.get("status") == "True":
            return True
    return False


def set_label(obj: dict, key: str, value: str) -> None:
    obj.setdefault("metadata", {}).setdefault("labels", {})[key] = value


def get_label(obj: dict, key: str) -> str | None:
    return ((obj.get("metadata") or {}).get("labels") or {}).get(key)


def get_annotation(obj: dict, key: str) -> str | None:
    return ((obj.get("metadata") or {}).get("annotations") or {}).get(key)


def set_owner_reference(owner: dict, obj: dict, controller: bool = True) -> None:
    """(controller-runtime SetControllerReference equivalent)"""
    m = obj.setdefault("metadata", {})
    refs = m.setdefault("ownerReferences", [])
    refs.append(
        {
            "apiVersion": owner.get("apiVersion", "v1"),
            "kind": owner.get("kind", ""),
            "name": (owner.get("metadata") or {}).get("name", ""),
            "uid": (owner.get("metadata") or {}).get("uid", ""),
            "controller": controller,
        }
    )


def is_owned_by(obj: dict, owner_uid: str) -> bool:
    for ref in ((obj.get("metadata") or {}).get("ownerReferences") or []):
        if ref.get("uid") == owner_uid:
            return True
    return False
