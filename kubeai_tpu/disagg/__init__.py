"""Disaggregated prefill/decode serving.

Long prefills and short decode steps have opposite resource shapes:
prefill is compute-bound and bursty, decode is memory-bandwidth-bound
and latency-sensitive. Co-batching them on one replica lets a prefill
burst stall every in-flight stream's next token. This package splits
them: prefill-role engines run (chunked) prefill and EXPORT the paged
KV as a `KVHandoff`; decode-role engines IMPORT handoffs straight into
slots and only ever run decode steps. The router orchestrates the
two-hop flow (routing/proxy.py), the operator renders per-role pod
groups (operator/controller.py), and the autoscaler scales each role
from its own bottleneck signal (autoscaler/autoscaler.py).

Roles (crd.metadata.ROLE_*): "prefill", "decode", and the default
"unified" which serves both phases monolithically — the fallback pool
when no disaggregated capacity exists.
"""

from kubeai_tpu.disagg.handoff import (
    HandoffError,
    KVHandoff,
    deserialize,
    serialize,
)
from kubeai_tpu.disagg.transport import (
    HandoffStore,
    HTTPTransport,
    InProcessTransport,
    TransferError,
    TransferResult,
    read_chunked_body,
)

__all__ = [
    "HandoffError",
    "KVHandoff",
    "serialize",
    "deserialize",
    "HandoffStore",
    "HTTPTransport",
    "InProcessTransport",
    "TransferError",
    "TransferResult",
    "read_chunked_body",
]
