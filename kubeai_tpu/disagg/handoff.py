"""KV handoff: the wire format of a prefilled request.

A `KVHandoff` is everything a decode-role engine needs to resume a
request at its FIRST decode step without re-running prefill: the token
ids (prompt), the first sampled token, the per-layer paged KV written by
prefill, the sampling state (seed, temperature, top-k/p, budget) and the
page-aligned prefix-hash chain (so a decode engine with the prefix cache
enabled can publish the imported pages).

Serialization is dtype- and page-layout-preserving: the K/V pages ship
as raw buffer bytes in the exporting pool's dtype and page size, with
geometry in a JSON header. Import re-pages into the receiving pool's own
page size by flattening to token order first — the VALUES are copied
bit-exactly either way, which is what makes a disaggregated stream
token-identical to a unified run (same KV bytes + same seeded sampler +
same decode graph ⇒ same logits ⇒ same tokens).

Wire format (all integers little-endian):

    b"KVH1" | u32 header_len | header JSON (utf-8) | K bytes | V bytes

    header: version, dtype, num_layers, kv_heads, head_dim, page_size,
            n_pages, plen, token_ids, first_token, first_finish,
            sampling {seed, temperature, top_k, top_p, max_tokens, stop},
            prefix_hashes (hex), adapter, client, priority, model

K/V arrays are [num_layers, n_pages, page_size, kv_heads, head_dim]
packed pages covering exactly the sequence (the partial last page ships
whole; junk past `plen` is masked by position on the decode side exactly
as it is in the exporting pool).
"""

from __future__ import annotations

import dataclasses
import json
import struct

import numpy as np

MAGIC = b"KVH1"
VERSION = 1

# Partial-chain page export (the cluster KV-sharing tier): same framing
# as KVH1 but a distinct magic, so the two blob kinds can never be
# confused — a KVP1 blob carries CACHE CONTENT (idle-pool prefix pages
# keyed by their hash chain), not a request in flight.
PAGES_MAGIC = b"KVP1"


class HandoffError(ValueError):
    """Malformed or incompatible handoff blob."""


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype by canonical name. bfloat16 lives in ml_dtypes (what JAX
    arrays convert to under np.asarray), not numpy proper."""
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(name)
    except TypeError as e:
        raise HandoffError(f"unknown KV dtype {name!r}") from e


@dataclasses.dataclass
class KVHandoff:
    token_ids: list[int]  # the prefilled sequence (prompt tokens)
    first_token: int  # sampled at prefill; decode resumes after it
    first_finish: str  # "" | "stop" | "length": finished at token one
    page_size: int
    dtype: str  # "bfloat16" | "float32" | ...
    k_pages: np.ndarray  # [NL, n_pages, page, KVH, D]
    v_pages: np.ndarray
    # Sampling state: the decode engine continues the SAME seeded sampler
    # the prefill engine's first sample came from.
    seed: int
    temperature: float
    top_k: int
    top_p: float
    max_tokens: int
    stop: tuple[str, ...] = ()
    # Page-aligned content-hash chain (hex) over the prompt — lets a
    # prefix-cache-enabled decode pool publish the imported pages.
    prefix_hashes: tuple[str, ...] = ()
    adapter: str = ""
    client: str = ""
    priority: str = ""
    model: str = ""

    @property
    def plen(self) -> int:
        return len(self.token_ids)

    def contiguous_kv(self) -> tuple[np.ndarray, np.ndarray]:
        """Flatten the packed pages to token order [NL, plen, KVH, D] —
        the page-size-independent view import scatters from."""
        nl, n_pages, page, kvh, d = self.k_pages.shape
        k = self.k_pages.reshape(nl, n_pages * page, kvh, d)[:, : self.plen]
        v = self.v_pages.reshape(nl, n_pages * page, kvh, d)[:, : self.plen]
        return k, v

    def nbytes(self) -> int:
        return int(self.k_pages.nbytes + self.v_pages.nbytes)


def serialize(h: KVHandoff) -> bytes:
    nl, n_pages, page, kvh, d = h.k_pages.shape
    if h.v_pages.shape != h.k_pages.shape:
        raise HandoffError(
            f"K/V shape mismatch: {h.k_pages.shape} vs {h.v_pages.shape}"
        )
    header = {
        "version": VERSION,
        "dtype": h.dtype,
        "num_layers": nl,
        "n_pages": n_pages,
        "page_size": page,
        "kv_heads": kvh,
        "head_dim": d,
        "plen": h.plen,
        "token_ids": list(map(int, h.token_ids)),
        "first_token": int(h.first_token),
        "first_finish": h.first_finish,
        "sampling": {
            "seed": int(h.seed),
            "temperature": float(h.temperature),
            "top_k": int(h.top_k),
            "top_p": float(h.top_p),
            "max_tokens": int(h.max_tokens),
            "stop": list(h.stop),
        },
        "prefix_hashes": list(h.prefix_hashes),
        "adapter": h.adapter,
        "client": h.client,
        "priority": h.priority,
        "model": h.model,
    }
    hdr = json.dumps(header).encode()
    k = np.ascontiguousarray(h.k_pages)
    v = np.ascontiguousarray(h.v_pages)
    return b"".join(
        [MAGIC, struct.pack("<I", len(hdr)), hdr, k.tobytes(), v.tobytes()]
    )


def deserialize(blob: bytes) -> KVHandoff:
    if len(blob) < 8 or blob[:4] != MAGIC:
        raise HandoffError("not a KV handoff blob (bad magic)")
    (hdr_len,) = struct.unpack("<I", blob[4:8])
    if len(blob) < 8 + hdr_len:
        raise HandoffError("truncated handoff header")
    try:
        header = json.loads(blob[8 : 8 + hdr_len])
    except json.JSONDecodeError as e:
        raise HandoffError(f"bad handoff header: {e}") from e
    if header.get("version") != VERSION:
        raise HandoffError(
            f"unsupported handoff version {header.get('version')!r}"
        )
    dtype = _resolve_dtype(header["dtype"])
    shape = (
        header["num_layers"],
        header["n_pages"],
        header["page_size"],
        header["kv_heads"],
        header["head_dim"],
    )
    count = int(np.prod(shape))
    body = blob[8 + hdr_len :]
    expected = 2 * count * dtype.itemsize
    if len(body) != expected:
        raise HandoffError(
            f"handoff body is {len(body)} bytes, expected {expected}"
        )
    k = np.frombuffer(body[: count * dtype.itemsize], dtype=dtype).reshape(
        shape
    )
    v = np.frombuffer(body[count * dtype.itemsize :], dtype=dtype).reshape(
        shape
    )
    plen = int(header["plen"])
    if not 0 < plen <= header["n_pages"] * header["page_size"]:
        raise HandoffError(f"plen {plen} outside shipped pages")
    s = header.get("sampling") or {}
    return KVHandoff(
        token_ids=[int(t) for t in header["token_ids"]],
        first_token=int(header["first_token"]),
        first_finish=str(header.get("first_finish", "")),
        page_size=int(header["page_size"]),
        dtype=str(header["dtype"]),
        k_pages=k,
        v_pages=v,
        seed=int(s.get("seed", 0)),
        temperature=float(s.get("temperature", 1.0)),
        top_k=int(s.get("top_k", 0)),
        top_p=float(s.get("top_p", 1.0)),
        max_tokens=int(s.get("max_tokens", 16)),
        stop=tuple(s.get("stop") or ()),
        prefix_hashes=tuple(header.get("prefix_hashes") or ()),
        adapter=str(header.get("adapter", "")),
        client=str(header.get("client", "")),
        priority=str(header.get("priority", "")),
        model=str(header.get("model", "")),
    )


@dataclasses.dataclass
class KVPageExport:
    """A run of consecutive prefix pages keyed by their hash chain — the
    transfer unit of the cluster KV-sharing tier. Unlike `KVHandoff`
    (one request's full state), this carries only CACHE CONTENT: every
    shipped page is a FULL page whose bytes are immutable under the
    chain hash, so the importer can park them unowned in its idle pool
    and let ordinary admission adopt them. An empty export (zero pages)
    is valid and round-trips — it is how a holder answers "I no longer
    hold any of that chain"."""

    prefix_hashes: tuple[str, ...]  # hex chain, one hash per page
    page_size: int
    dtype: str
    k_pages: np.ndarray  # [NL, n_pages, page, KVH, D]
    v_pages: np.ndarray
    model: str = ""

    @property
    def n_pages(self) -> int:
        return int(self.k_pages.shape[1])

    def nbytes(self) -> int:
        return int(self.k_pages.nbytes + self.v_pages.nbytes)


def serialize_pages(e: KVPageExport) -> bytes:
    nl, n_pages, page, kvh, d = e.k_pages.shape
    if e.v_pages.shape != e.k_pages.shape:
        raise HandoffError(
            f"K/V shape mismatch: {e.k_pages.shape} vs {e.v_pages.shape}"
        )
    if len(e.prefix_hashes) != n_pages:
        raise HandoffError(
            f"{len(e.prefix_hashes)} hashes for {n_pages} pages"
        )
    header = {
        "version": VERSION,
        "dtype": e.dtype,
        "num_layers": nl,
        "n_pages": n_pages,
        "page_size": page,
        "kv_heads": kvh,
        "head_dim": d,
        "prefix_hashes": list(e.prefix_hashes),
        "model": e.model,
    }
    hdr = json.dumps(header).encode()
    k = np.ascontiguousarray(e.k_pages)
    v = np.ascontiguousarray(e.v_pages)
    return b"".join(
        [PAGES_MAGIC, struct.pack("<I", len(hdr)), hdr, k.tobytes(),
         v.tobytes()]
    )


def deserialize_pages(blob: bytes) -> KVPageExport:
    if len(blob) < 8 or blob[:4] != PAGES_MAGIC:
        raise HandoffError("not a KV page-export blob (bad magic)")
    (hdr_len,) = struct.unpack("<I", blob[4:8])
    if len(blob) < 8 + hdr_len:
        raise HandoffError("truncated page-export header")
    try:
        header = json.loads(blob[8 : 8 + hdr_len])
    except json.JSONDecodeError as e:
        raise HandoffError(f"bad page-export header: {e}") from e
    if header.get("version") != VERSION:
        raise HandoffError(
            f"unsupported page-export version {header.get('version')!r}"
        )
    dtype = _resolve_dtype(header["dtype"])
    shape = (
        header["num_layers"],
        header["n_pages"],
        header["page_size"],
        header["kv_heads"],
        header["head_dim"],
    )
    count = int(np.prod(shape))
    body = blob[8 + hdr_len :]
    expected = 2 * count * dtype.itemsize
    if len(body) != expected:
        raise HandoffError(
            f"page-export body is {len(body)} bytes, expected {expected}"
        )
    k = np.frombuffer(body[: count * dtype.itemsize], dtype=dtype).reshape(
        shape
    )
    v = np.frombuffer(body[count * dtype.itemsize :], dtype=dtype).reshape(
        shape
    )
    hashes = tuple(header.get("prefix_hashes") or ())
    if len(hashes) != header["n_pages"]:
        raise HandoffError(
            f"{len(hashes)} hashes for {header['n_pages']} pages"
        )
    return KVPageExport(
        prefix_hashes=hashes,
        page_size=int(header["page_size"]),
        dtype=str(header["dtype"]),
        k_pages=k,
        v_pages=v,
        model=str(header.get("model", "")),
    )
