"""KV handoff: the wire format of a prefilled request.

A `KVHandoff` is everything a decode-role engine needs to resume a
request at its FIRST decode step without re-running prefill: the token
ids (prompt), the first sampled token, the per-layer paged KV written by
prefill, the sampling state (seed, temperature, top-k/p, budget) and the
page-aligned prefix-hash chain (so a decode engine with the prefix cache
enabled can publish the imported pages).

Serialization is dtype- and page-layout-preserving: the K/V pages ship
as raw buffer bytes in the exporting pool's dtype and page size, with
geometry in a JSON header. Import re-pages into the receiving pool's own
page size by flattening to token order first — the VALUES are copied
bit-exactly either way, which is what makes a disaggregated stream
token-identical to a unified run (same KV bytes + same seeded sampler +
same decode graph ⇒ same logits ⇒ same tokens).

Wire format (all integers little-endian):

    b"KVH1" | u32 header_len | header JSON (utf-8) | K bytes | V bytes

    header: version, dtype, num_layers, kv_heads, head_dim, page_size,
            n_pages, plen, token_ids, first_token, first_finish,
            sampling {seed, temperature, top_k, top_p, max_tokens, stop},
            prefix_hashes (hex), adapter, client, priority, model,
            kv_quant (int8 pools only)

K/V arrays are [num_layers, n_pages, page_size, kv_heads, head_dim]
packed pages covering exactly the sequence (the partial last page ships
whole; junk past `plen` is masked by position on the decode side exactly
as it is in the exporting pool).

Quantized pools (dtype "int8", ops/kv_quant.py): the header carries a
`kv_quant` block {"scheme": "int8-token-head", "scale_dtype": "float32"}
and the body grows two trailing scale arrays,

    ... | K bytes | V bytes | K scales | V scales

each [num_layers, n_pages, page_size, kv_heads] f32 — the per-token-
per-head scales travel WITH their pages, so a quantized handoff or page
export round-trips byte-exactly (the importer scatters the int8 values
and scales verbatim; nothing is ever re-quantized on the wire). A peer
whose pool dtype differs must refuse with `HandoffError` — casting in
either direction would silently alter KV values the exporter's chain
hashes and token stream vouch for.
"""

from __future__ import annotations

import dataclasses
import json
import struct

import numpy as np

MAGIC = b"KVH1"
VERSION = 1

# Partial-chain page export (the cluster KV-sharing tier): same framing
# as KVH1 but a distinct magic, so the two blob kinds can never be
# confused — a KVP1 blob carries CACHE CONTENT (idle-pool prefix pages
# keyed by their hash chain), not a request in flight.
PAGES_MAGIC = b"KVP1"


class HandoffError(ValueError):
    """Malformed or incompatible handoff blob."""


# The one quantization scheme the wire speaks (ops/kv_quant.py): int8
# values with per-token-per-head float32 scales. The header block names
# it explicitly so a future coarser scheme can't be confused for it.
KV_QUANT_SCHEME = "int8-token-head"
_SCALE_DTYPE = np.dtype(np.float32)


def _quant_header(dtype: str, k_scales, v_scales) -> dict | None:
    """Validate scale presence against the dtype and build the header
    block (None for unquantized blobs — the wire stays v1-compatible)."""
    if dtype == "int8":
        if k_scales is None or v_scales is None:
            raise HandoffError("int8 KV requires k_scales/v_scales")
        return {"scheme": KV_QUANT_SCHEME, "scale_dtype": "float32"}
    if k_scales is not None or v_scales is not None:
        raise HandoffError(
            f"scales supplied for non-quantized dtype {dtype!r}"
        )
    return None


def _check_quant_block(header: dict, kind: str) -> bool:
    """True when the blob is quantized; typed refusal on any mismatch
    between the dtype and the kv_quant block."""
    quant = header.get("kv_quant")
    if header.get("dtype") == "int8":
        if not isinstance(quant, dict):
            raise HandoffError(f"int8 {kind} is missing its kv_quant block")
        if quant.get("scheme") != KV_QUANT_SCHEME:
            raise HandoffError(
                f"unsupported KV quant scheme {quant.get('scheme')!r}"
            )
        if quant.get("scale_dtype", "float32") != "float32":
            raise HandoffError(
                f"unsupported scale dtype {quant.get('scale_dtype')!r}"
            )
        return True
    if quant is not None:
        raise HandoffError(
            f"kv_quant block on non-int8 {kind} "
            f"(dtype {header.get('dtype')!r})"
        )
    return False


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype by canonical name. bfloat16 lives in ml_dtypes (what JAX
    arrays convert to under np.asarray), not numpy proper."""
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    try:
        return np.dtype(name)
    except TypeError as e:
        raise HandoffError(f"unknown KV dtype {name!r}") from e


@dataclasses.dataclass
class KVHandoff:
    token_ids: list[int]  # the prefilled sequence (prompt tokens)
    first_token: int  # sampled at prefill; decode resumes after it
    first_finish: str  # "" | "stop" | "length": finished at token one
    page_size: int
    dtype: str  # "bfloat16" | "float32" | ...
    k_pages: np.ndarray  # [NL, n_pages, page, KVH, D]
    v_pages: np.ndarray
    # Sampling state: the decode engine continues the SAME seeded sampler
    # the prefill engine's first sample came from.
    seed: int
    temperature: float
    top_k: int
    top_p: float
    max_tokens: int
    stop: tuple[str, ...] = ()
    # Page-aligned content-hash chain (hex) over the prompt — lets a
    # prefix-cache-enabled decode pool publish the imported pages.
    prefix_hashes: tuple[str, ...] = ()
    adapter: str = ""
    client: str = ""
    priority: str = ""
    model: str = ""
    # Int8 pools only: per-token-per-head f32 scales riding with their
    # pages, [NL, n_pages, page, KVH]. None for unquantized handoffs.
    k_scales: np.ndarray | None = None
    v_scales: np.ndarray | None = None

    @property
    def plen(self) -> int:
        return len(self.token_ids)

    @property
    def quantized(self) -> bool:
        return self.k_scales is not None

    def contiguous_kv(self) -> tuple[np.ndarray, np.ndarray]:
        """Flatten the packed pages to token order [NL, plen, KVH, D] —
        the page-size-independent view import scatters from."""
        nl, n_pages, page, kvh, d = self.k_pages.shape
        k = self.k_pages.reshape(nl, n_pages * page, kvh, d)[:, : self.plen]
        v = self.v_pages.reshape(nl, n_pages * page, kvh, d)[:, : self.plen]
        return k, v

    def contiguous_scales(self) -> tuple[np.ndarray, np.ndarray]:
        """Token-order view [NL, plen, KVH] of the scale arrays (int8
        handoffs only) — scattered alongside contiguous_kv()."""
        nl, n_pages, page, kvh = self.k_scales.shape
        ks = self.k_scales.reshape(nl, n_pages * page, kvh)[:, : self.plen]
        vs = self.v_scales.reshape(nl, n_pages * page, kvh)[:, : self.plen]
        return ks, vs

    def nbytes(self) -> int:
        n = int(self.k_pages.nbytes + self.v_pages.nbytes)
        if self.quantized:
            n += int(self.k_scales.nbytes + self.v_scales.nbytes)
        return n


def serialize(h: KVHandoff) -> bytes:
    nl, n_pages, page, kvh, d = h.k_pages.shape
    if h.v_pages.shape != h.k_pages.shape:
        raise HandoffError(
            f"K/V shape mismatch: {h.k_pages.shape} vs {h.v_pages.shape}"
        )
    quant = _quant_header(h.dtype, h.k_scales, h.v_scales)
    if quant is not None and h.k_scales.shape != (nl, n_pages, page, kvh):
        raise HandoffError(
            f"scale shape {h.k_scales.shape} does not match pages "
            f"{(nl, n_pages, page, kvh)}"
        )
    header = {
        "version": VERSION,
        "dtype": h.dtype,
        "num_layers": nl,
        "n_pages": n_pages,
        "page_size": page,
        "kv_heads": kvh,
        "head_dim": d,
        "plen": h.plen,
        "token_ids": list(map(int, h.token_ids)),
        "first_token": int(h.first_token),
        "first_finish": h.first_finish,
        "sampling": {
            "seed": int(h.seed),
            "temperature": float(h.temperature),
            "top_k": int(h.top_k),
            "top_p": float(h.top_p),
            "max_tokens": int(h.max_tokens),
            "stop": list(h.stop),
        },
        "prefix_hashes": list(h.prefix_hashes),
        "adapter": h.adapter,
        "client": h.client,
        "priority": h.priority,
        "model": h.model,
    }
    if quant is not None:
        header["kv_quant"] = quant
    hdr = json.dumps(header).encode()
    k = np.ascontiguousarray(h.k_pages)
    v = np.ascontiguousarray(h.v_pages)
    parts = [MAGIC, struct.pack("<I", len(hdr)), hdr, k.tobytes(), v.tobytes()]
    if quant is not None:
        parts.append(
            np.ascontiguousarray(h.k_scales, _SCALE_DTYPE).tobytes()
        )
        parts.append(
            np.ascontiguousarray(h.v_scales, _SCALE_DTYPE).tobytes()
        )
    return b"".join(parts)


def deserialize(blob: bytes) -> KVHandoff:
    if len(blob) < 8 or blob[:4] != MAGIC:
        raise HandoffError("not a KV handoff blob (bad magic)")
    (hdr_len,) = struct.unpack("<I", blob[4:8])
    if len(blob) < 8 + hdr_len:
        raise HandoffError("truncated handoff header")
    try:
        header = json.loads(blob[8 : 8 + hdr_len])
    except json.JSONDecodeError as e:
        raise HandoffError(f"bad handoff header: {e}") from e
    if header.get("version") != VERSION:
        raise HandoffError(
            f"unsupported handoff version {header.get('version')!r}"
        )
    dtype = _resolve_dtype(header["dtype"])
    quantized = _check_quant_block(header, "handoff")
    shape = (
        header["num_layers"],
        header["n_pages"],
        header["page_size"],
        header["kv_heads"],
        header["head_dim"],
    )
    count = int(np.prod(shape))
    scale_count = int(np.prod(shape[:-1])) if quantized else 0
    body = blob[8 + hdr_len :]
    expected = 2 * count * dtype.itemsize
    expected += 2 * scale_count * _SCALE_DTYPE.itemsize
    if len(body) != expected:
        raise HandoffError(
            f"handoff body is {len(body)} bytes, expected {expected}"
        )
    k = np.frombuffer(body[: count * dtype.itemsize], dtype=dtype).reshape(
        shape
    )
    v = np.frombuffer(
        body[count * dtype.itemsize : 2 * count * dtype.itemsize],
        dtype=dtype,
    ).reshape(shape)
    k_scales = v_scales = None
    if quantized:
        off = 2 * count * dtype.itemsize
        sz = scale_count * _SCALE_DTYPE.itemsize
        k_scales = np.frombuffer(
            body[off : off + sz], dtype=_SCALE_DTYPE
        ).reshape(shape[:-1])
        v_scales = np.frombuffer(
            body[off + sz :], dtype=_SCALE_DTYPE
        ).reshape(shape[:-1])
    plen = int(header["plen"])
    if not 0 < plen <= header["n_pages"] * header["page_size"]:
        raise HandoffError(f"plen {plen} outside shipped pages")
    s = header.get("sampling") or {}
    return KVHandoff(
        token_ids=[int(t) for t in header["token_ids"]],
        first_token=int(header["first_token"]),
        first_finish=str(header.get("first_finish", "")),
        page_size=int(header["page_size"]),
        dtype=str(header["dtype"]),
        k_pages=k,
        v_pages=v,
        seed=int(s.get("seed", 0)),
        temperature=float(s.get("temperature", 1.0)),
        top_k=int(s.get("top_k", 0)),
        top_p=float(s.get("top_p", 1.0)),
        max_tokens=int(s.get("max_tokens", 16)),
        stop=tuple(s.get("stop") or ()),
        prefix_hashes=tuple(header.get("prefix_hashes") or ()),
        adapter=str(header.get("adapter", "")),
        client=str(header.get("client", "")),
        priority=str(header.get("priority", "")),
        model=str(header.get("model", "")),
        k_scales=k_scales,
        v_scales=v_scales,
    )


@dataclasses.dataclass
class KVPageExport:
    """A run of consecutive prefix pages keyed by their hash chain — the
    transfer unit of the cluster KV-sharing tier. Unlike `KVHandoff`
    (one request's full state), this carries only CACHE CONTENT: every
    shipped page is a FULL page whose bytes are immutable under the
    chain hash, so the importer can park them unowned in its idle pool
    and let ordinary admission adopt them. An empty export (zero pages)
    is valid and round-trips — it is how a holder answers "I no longer
    hold any of that chain"."""

    prefix_hashes: tuple[str, ...]  # hex chain, one hash per page
    page_size: int
    dtype: str
    k_pages: np.ndarray  # [NL, n_pages, page, KVH, D]
    v_pages: np.ndarray
    model: str = ""
    # Int8 pools only: [NL, n_pages, page, KVH] f32 scales.
    k_scales: np.ndarray | None = None
    v_scales: np.ndarray | None = None

    @property
    def n_pages(self) -> int:
        return int(self.k_pages.shape[1])

    @property
    def quantized(self) -> bool:
        return self.k_scales is not None

    def nbytes(self) -> int:
        n = int(self.k_pages.nbytes + self.v_pages.nbytes)
        if self.quantized:
            n += int(self.k_scales.nbytes + self.v_scales.nbytes)
        return n


def serialize_pages(e: KVPageExport) -> bytes:
    nl, n_pages, page, kvh, d = e.k_pages.shape
    if e.v_pages.shape != e.k_pages.shape:
        raise HandoffError(
            f"K/V shape mismatch: {e.k_pages.shape} vs {e.v_pages.shape}"
        )
    if len(e.prefix_hashes) != n_pages:
        raise HandoffError(
            f"{len(e.prefix_hashes)} hashes for {n_pages} pages"
        )
    quant = _quant_header(e.dtype, e.k_scales, e.v_scales)
    if quant is not None and e.k_scales.shape != (nl, n_pages, page, kvh):
        raise HandoffError(
            f"scale shape {e.k_scales.shape} does not match pages "
            f"{(nl, n_pages, page, kvh)}"
        )
    header = {
        "version": VERSION,
        "dtype": e.dtype,
        "num_layers": nl,
        "n_pages": n_pages,
        "page_size": page,
        "kv_heads": kvh,
        "head_dim": d,
        "prefix_hashes": list(e.prefix_hashes),
        "model": e.model,
    }
    if quant is not None:
        header["kv_quant"] = quant
    hdr = json.dumps(header).encode()
    k = np.ascontiguousarray(e.k_pages)
    v = np.ascontiguousarray(e.v_pages)
    parts = [
        PAGES_MAGIC, struct.pack("<I", len(hdr)), hdr, k.tobytes(),
        v.tobytes(),
    ]
    if quant is not None:
        parts.append(
            np.ascontiguousarray(e.k_scales, _SCALE_DTYPE).tobytes()
        )
        parts.append(
            np.ascontiguousarray(e.v_scales, _SCALE_DTYPE).tobytes()
        )
    return b"".join(parts)


def deserialize_pages(blob: bytes) -> KVPageExport:
    if len(blob) < 8 or blob[:4] != PAGES_MAGIC:
        raise HandoffError("not a KV page-export blob (bad magic)")
    (hdr_len,) = struct.unpack("<I", blob[4:8])
    if len(blob) < 8 + hdr_len:
        raise HandoffError("truncated page-export header")
    try:
        header = json.loads(blob[8 : 8 + hdr_len])
    except json.JSONDecodeError as e:
        raise HandoffError(f"bad page-export header: {e}") from e
    if header.get("version") != VERSION:
        raise HandoffError(
            f"unsupported page-export version {header.get('version')!r}"
        )
    dtype = _resolve_dtype(header["dtype"])
    shape = (
        header["num_layers"],
        header["n_pages"],
        header["page_size"],
        header["kv_heads"],
        header["head_dim"],
    )
    count = int(np.prod(shape))
    quantized = _check_quant_block(header, "page-export")
    scale_count = int(np.prod(shape[:-1])) if quantized else 0
    body = blob[8 + hdr_len :]
    expected = 2 * count * dtype.itemsize + 2 * scale_count * 4
    if len(body) != expected:
        raise HandoffError(
            f"page-export body is {len(body)} bytes, expected {expected}"
        )
    k = np.frombuffer(body[: count * dtype.itemsize], dtype=dtype).reshape(
        shape
    )
    v = np.frombuffer(
        body[count * dtype.itemsize : 2 * count * dtype.itemsize],
        dtype=dtype,
    ).reshape(shape)
    k_scales = v_scales = None
    if quantized:
        off = 2 * count * dtype.itemsize
        k_scales = np.frombuffer(
            body[off : off + scale_count * 4], dtype=_SCALE_DTYPE
        ).reshape(shape[:-1])
        v_scales = np.frombuffer(
            body[off + scale_count * 4 :], dtype=_SCALE_DTYPE
        ).reshape(shape[:-1])
    hashes = tuple(header.get("prefix_hashes") or ())
    if len(hashes) != header["n_pages"]:
        raise HandoffError(
            f"{len(hashes)} hashes for {header['n_pages']} pages"
        )
    return KVPageExport(
        prefix_hashes=hashes,
        page_size=int(header["page_size"]),
        dtype=str(header["dtype"]),
        k_pages=k,
        v_pages=v,
        model=str(header.get("model", "")),
        k_scales=k_scales,
        v_scales=v_scales,
    )
