"""Handoff transports: how a prefilled request's KV reaches a decode
engine.

Two implementations behind one `send(handoff, ...) -> TransferResult`
shape:

  * `InProcessTransport` — hands the KVHandoff object straight to a
    `HandoffStore` (the same store a decode `EngineServer` admits from).
    Zero-copy, for tests and single-process topologies.
  * `HTTPTransport` — serializes and POSTs to the decode engine's
    `POST /v1/kv/import` with a CHUNKED upload (KV blobs are tens to
    hundreds of MB at production sequence lengths; chunking keeps the
    sender's memory flat at `chunk_bytes` past the one serialized copy
    and lets the receiver start draining immediately).

Both record transfer bytes + wall seconds so the caller can feed the
engine's kv-transfer metrics.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
import uuid
from collections import OrderedDict

from kubeai_tpu.disagg.handoff import KVHandoff, serialize


class TransferError(RuntimeError):
    """The decode side refused or the connection failed mid-transfer."""


@dataclasses.dataclass(frozen=True)
class TransferResult:
    handoff_id: str
    bytes: int
    seconds: float


class HandoffStore:
    """Bounded id → KVHandoff buffer on the decode side. Entries are
    consumed exactly once (pop) by the generate request that references
    them; the cap evicts oldest-first so an orchestrator that crashed
    between the two hops cannot leak pool-sized blobs forever."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, KVHandoff]" = OrderedDict()
        self.evicted = 0

    def put(self, handoff: KVHandoff, handoff_id: str | None = None) -> str:
        hid = handoff_id or f"kvh-{uuid.uuid4().hex[:16]}"
        with self._lock:
            self._entries[hid] = handoff
            self._entries.move_to_end(hid)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evicted += 1
        return hid

    def pop(self, handoff_id: str) -> KVHandoff | None:
        with self._lock:
            return self._entries.pop(handoff_id, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class InProcessTransport:
    """Deliver handoffs to a local HandoffStore (tests, co-located
    prefill/decode engines)."""

    def __init__(self, store: HandoffStore):
        self.store = store

    def send(
        self, handoff: KVHandoff, handoff_id: str | None = None
    ) -> TransferResult:
        t0 = time.monotonic()
        hid = self.store.put(handoff, handoff_id)
        return TransferResult(
            handoff_id=hid,
            bytes=handoff.nbytes(),
            seconds=time.monotonic() - t0,
        )


class HTTPTransport:
    """Push a serialized handoff to `POST http://{addr}/v1/kv/import`
    with Transfer-Encoding: chunked."""

    def __init__(
        self,
        addr: str,
        timeout: float = 30.0,
        chunk_bytes: int = 256 * 1024,
    ):
        self.addr = addr
        self.timeout = timeout
        self.chunk_bytes = max(1, chunk_bytes)

    def send(
        self, handoff: KVHandoff, handoff_id: str | None = None
    ) -> TransferResult:
        blob = serialize(handoff)
        host, _, port = self.addr.partition(":")
        t0 = time.monotonic()
        conn = http.client.HTTPConnection(
            host, int(port or 80), timeout=self.timeout
        )
        try:
            conn.putrequest("POST", "/v1/kv/import")
            conn.putheader("Content-Type", "application/x-kv-handoff")
            conn.putheader("Transfer-Encoding", "chunked")
            if handoff_id:
                conn.putheader("X-Handoff-Id", handoff_id)
            conn.endheaders()
            for off in range(0, len(blob), self.chunk_bytes):
                chunk = blob[off : off + self.chunk_bytes]
                conn.send(f"{len(chunk):x}\r\n".encode())
                conn.send(chunk)
                conn.send(b"\r\n")
            conn.send(b"0\r\n\r\n")
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                msg = body.decode(errors="replace")[:500]
                raise TransferError(
                    f"kv import to {self.addr} failed: HTTP {resp.status} "
                    f"{msg}"
                )
            try:
                payload = json.loads(body or b"{}")
            except json.JSONDecodeError:
                payload = {}
            hid = str(payload.get("handoff_id") or handoff_id or "")
            if not hid:
                raise TransferError(
                    f"kv import to {self.addr} returned no handoff_id"
                )
            return TransferResult(
                handoff_id=hid,
                bytes=len(blob),
                seconds=time.monotonic() - t0,
            )
        except (OSError, http.client.HTTPException) as e:
            raise TransferError(
                f"kv import to {self.addr} failed: {e}"
            ) from e
        finally:
            conn.close()


def read_chunked_body(rfile, max_bytes: int = 0) -> bytes:
    """Parse a Transfer-Encoding: chunked request body off `rfile`
    (http.server does NOT decode chunked uploads). `max_bytes` > 0 caps
    the accepted size — the CRD's transfer limit — raising TransferError
    past it so a runaway upload cannot balloon the receiver."""
    parts: list[bytes] = []
    total = 0
    while True:
        size_line = rfile.readline(64)
        if not size_line:
            raise TransferError("truncated chunked upload (no size line)")
        try:
            size = int(size_line.split(b";", 1)[0].strip() or b"0", 16)
        except ValueError as e:
            raise TransferError(
                f"bad chunk size line {size_line!r}"
            ) from e
        if size == 0:
            # Trailer section ends with a blank line.
            while True:
                line = rfile.readline(1024)
                if line in (b"\r\n", b"\n", b""):
                    break
            return b"".join(parts)
        total += size
        if max_bytes and total > max_bytes:
            raise TransferError(
                f"chunked upload exceeds the {max_bytes}-byte transfer limit"
            )
        chunk = rfile.read(size)
        if len(chunk) != size:
            raise TransferError("truncated chunked upload (short chunk)")
        parts.append(chunk)
        rfile.read(2)  # trailing CRLF
