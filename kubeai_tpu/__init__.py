"""kubeai_tpu — a TPU-native Kubernetes AI inference framework.

A ground-up rebuild of the capabilities of substratusai/kubeai (reference:
/root/reference), designed TPU-first:

- **Serving engine** (`kubeai_tpu.engine`, `kubeai_tpu.models`,
  `kubeai_tpu.ops`, `kubeai_tpu.parallel`): a JAX/XLA/Pallas inference
  engine — continuous batching, slot-based KV cache, pjit/GSPMD tensor
  parallelism over a TPU device mesh, Pallas attention kernels — replacing
  the CUDA vLLM images the reference delegates to
  (reference: charts/kubeai/values.yaml:45-48).
- **Operator control plane** (`kubeai_tpu.operator`, `kubeai_tpu.crd`,
  `kubeai_tpu.config`): Model resource + reconciler + pod planner with
  surge rollouts, scale-from-zero, model-artifact caching and LoRA adapter
  orchestration (reference: internal/modelcontroller).
- **Routing tier** (`kubeai_tpu.routing`): OpenAI-compatible front door,
  prefix-aware CHWBL load balancer, retrying proxy, pub/sub messenger
  (reference: internal/{openaiserver,loadbalancer,modelproxy,messenger}).
- **Autoscaler** (`kubeai_tpu.autoscaler`): metrics-driven, leader-elected,
  state-persisted (reference: internal/modelautoscaler).
"""

__version__ = "0.1.0"
