"""Priority-band + weighted-fair-queueing request scheduler with
deadline-aware admission control.

Queue discipline, outermost to innermost:

  1. Priority bands (``realtime`` > ``standard`` > ``batch``): strict
     precedence — a lower band is served only when every higher band is
     empty, UNLESS the lower band's configured queue share is due (see
     below). This is the contract latency-sensitive traffic needs: batch
     work can never delay a realtime request by more than the share it
     was explicitly granted.
  2. Share credits (anti-starvation): ``SchedulingPolicy.queue_shares``
     grants a band a fraction of dispatches. Every time a non-empty band
     is passed over, it accrues its share as credit; at credit >= 1 it is
     due and takes the next dispatch even though a higher band has work.
     The default share of 0 keeps pure strict precedence.
  3. Weighted fair queueing within a band, keyed by client: classic
     finish-tag virtual-time accounting (SFQ). Entry i of client c gets
     ``finish = max(band_vtime, prev_finish(c)) + cost / weight`` and the
     band pops the smallest finish tag. Two backlogged clients with 2:1
     weights converge to a 2:1 dispatch ratio; a newly arriving client
     starts at the band's virtual time, so it can neither starve nor be
     starved by an old backlog.

Admission control: the scheduler keeps a decayed estimate of the service
rate (cost units completed per second, fed by ``observe_service``). A
request whose deadline cannot be met given the queued work ahead of it is
refused at enqueue with :class:`DeadlineInfeasible`, carrying a COMPUTED
retry hint (queue depth ÷ drain rate, clamped) — never a fixed constant —
so clients and load balancers can make informed retry decisions.

The scheduler is clock-injected (``clock=``) so unit tests drive it with
a fake clock and assert the fairness/feasibility math deterministically.
All public methods are thread-safe (internal lock; no callbacks run
under it).
"""

from __future__ import annotations

import dataclasses
import heapq
import threading
import time
from collections import deque
from typing import Any, Callable, Iterator

CLASS_REALTIME = "realtime"
CLASS_STANDARD = "standard"
CLASS_BATCH = "batch"
# Strict precedence order, highest first.
PRIORITY_CLASSES = (CLASS_REALTIME, CLASS_STANDARD, CLASS_BATCH)
CLASS_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}


class DeadlineInfeasible(Exception):
    """Raised at submit() when the request's deadline cannot be met given
    queued work and the measured service rate. ``retry_after`` is the
    computed backoff hint (seconds) the HTTP layer surfaces as
    ``Retry-After``."""

    def __init__(
        self, message: str, retry_after: float, estimated_wait: float,
        deadline_s: float,
    ):
        super().__init__(message)
        self.retry_after = retry_after
        self.estimated_wait = estimated_wait
        self.deadline_s = deadline_s


@dataclasses.dataclass(frozen=True)
class SchedulingPolicy:
    """Per-model scheduling policy (CRD ``scheduling:`` block)."""

    default_priority: str = CLASS_STANDARD
    # class -> guaranteed fraction of dispatches while backlogged (0..1).
    # 0 (the default) = pure strict precedence below higher bands.
    queue_shares: dict[str, float] = dataclasses.field(default_factory=dict)
    # Cap on client-requested deadlines (ms). 0 = uncapped.
    max_deadline_ms: int = 0
    # Retry-After clamp: the hint must be useful (not 0 on an empty
    # queue) and bounded (a 10-minute backlog should not tell clients to
    # disappear for 10 minutes — the LB retries elsewhere first).
    min_retry_after_s: float = 0.25
    max_retry_after_s: float = 30.0
    # Service-rate estimator decay per observation (decayed num/den
    # counters are robust to zero-completion steps, unlike a raw EWMA of
    # cost/dt samples).
    rate_decay: float = 0.95

    def validate(self) -> None:
        if self.default_priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"default_priority must be one of {PRIORITY_CLASSES}, "
                f"got {self.default_priority!r}"
            )
        for cls, share in self.queue_shares.items():
            if cls not in PRIORITY_CLASSES:
                raise ValueError(f"queue_shares: unknown class {cls!r}")
            if not 0.0 <= float(share) < 1.0:
                raise ValueError(
                    f"queue_shares[{cls!r}] must be in [0, 1), got {share}"
                )
        if self.max_deadline_ms < 0:
            raise ValueError("max_deadline_ms must be >= 0")
        if not 0.0 < self.rate_decay < 1.0:
            raise ValueError("rate_decay must be in (0, 1)")


class _Entry:
    __slots__ = (
        "item", "priority", "client", "weight", "cost", "deadline",
        "t_enqueue", "vstart", "vfinish", "seq", "removed", "counted",
    )

    def __init__(self, item, priority, client, weight, cost, deadline,
                 t_enqueue, seq):
        self.item = item
        self.priority = priority
        self.client = client
        self.weight = weight
        self.cost = cost
        self.deadline = deadline  # absolute clock value or None
        self.t_enqueue = t_enqueue
        self.vstart = 0.0
        self.vfinish = 0.0
        self.seq = seq
        self.removed = False
        # True once this entry's queue-wait was recorded (a preempted
        # request re-queued at the front must not count twice).
        self.counted = False


class _Band:
    """One priority band: a finish-tag heap over live entries plus the
    per-client virtual-time bookkeeping."""

    __slots__ = (
        "name", "vtime", "heap", "client_finish", "client_count",
        "depth", "cost_total", "credit",
    )

    def __init__(self, name: str):
        self.name = name
        self.vtime = 0.0
        self.heap: list[tuple[float, int, _Entry]] = []
        self.client_finish: dict[str, float] = {}
        self.client_count: dict[str, int] = {}
        self.depth = 0
        self.cost_total = 0.0
        self.credit = 0.0

    def push(self, e: _Entry) -> None:
        start = max(self.vtime, self.client_finish.get(e.client, 0.0))
        e.vstart = start
        e.vfinish = start + e.cost / max(e.weight, 1e-9)
        self.client_finish[e.client] = e.vfinish
        self.client_count[e.client] = self.client_count.get(e.client, 0) + 1
        heapq.heappush(self.heap, (e.vfinish, e.seq, e))
        self.depth += 1
        self.cost_total += e.cost

    def peek(self) -> _Entry | None:
        while self.heap:
            _, _, e = self.heap[0]
            if e.removed:
                heapq.heappop(self.heap)
                continue
            return e
        return None

    def pop(self) -> _Entry | None:
        e = self.peek()
        if e is None:
            return None
        heapq.heappop(self.heap)
        self._drop(e)
        # Advance virtual time to the dispatched entry's start tag: new
        # arrivals join at the frontier instead of replaying history.
        self.vtime = max(self.vtime, e.vstart)
        return e

    def discard(self, e: _Entry) -> None:
        """Lazy removal: the heap tuple stays until it surfaces."""
        e.removed = True
        self._drop(e)

    def _drop(self, e: _Entry) -> None:
        self.depth -= 1
        self.cost_total -= e.cost
        n = self.client_count.get(e.client, 0) - 1
        if n <= 0:
            self.client_count.pop(e.client, None)
            # The client drained; once virtual time passes its last
            # finish tag, the memo is inert — drop it so client churn
            # cannot grow the dict without bound.
            if self.client_finish.get(e.client, 0.0) <= self.vtime:
                self.client_finish.pop(e.client, None)
        else:
            self.client_count[e.client] = n


class RequestScheduler:
    """Admission-controlled priority/WFQ queue (see module docstring).

    Items are opaque objects tracked by identity; the engine queues its
    ``_Request`` records directly.
    """

    def __init__(
        self,
        policy: SchedulingPolicy | None = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy or SchedulingPolicy()
        self.policy.validate()
        self._clock = clock
        self._lock = threading.Lock()
        self._bands = {c: _Band(c) for c in PRIORITY_CLASSES}
        # Preempted requests re-enter here and are served before any
        # band: they already hold partial progress (recompute state) and
        # re-subjecting them to fairness would double-charge their class.
        self._front: deque[_Entry] = deque()
        self._entries: dict[int, _Entry] = {}  # id(item) -> entry
        self._seq = 0
        # Decayed service-rate estimate: cost units per second.
        self._rate_num = 0.0
        self._rate_den = 0.0
        # Per-class lifetime stats.
        self._admitted = {c: 0 for c in PRIORITY_CLASSES}
        self._wait_sum = {c: 0.0 for c in PRIORITY_CLASSES}
        self._shed = {c: 0 for c in PRIORITY_CLASSES}

    # -- admission -------------------------------------------------------------

    def submit(
        self,
        item: Any,
        *,
        priority: str | None = None,
        client: str = "",
        weight: float = 1.0,
        cost: float = 1.0,
        deadline_ms: float | None = None,
    ) -> str:
        """Enqueue ``item``. Returns the resolved priority class.

        Raises ``ValueError`` on an unknown class / bad deadline and
        :class:`DeadlineInfeasible` when the deadline cannot be met given
        queued work and the measured service rate (the item is NOT
        queued). A ``deadline_ms`` beyond the policy's ``max_deadline_ms``
        cap is clamped, not rejected — the cap is an operator bound on
        how long a request may ask to wait, so clamping preserves the
        operator's intent."""
        prio = priority or self.policy.default_priority
        if prio not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority class {prio!r} "
                f"(expected one of {PRIORITY_CLASSES})"
            )
        if weight <= 0:
            raise ValueError("weight must be > 0")
        if cost <= 0:
            raise ValueError("cost must be > 0")
        deadline = None
        now = self._clock()
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)
            if deadline_ms <= 0:
                raise ValueError("deadline_ms must be > 0")
            if self.policy.max_deadline_ms > 0:
                deadline_ms = min(deadline_ms, self.policy.max_deadline_ms)
            deadline = now + deadline_ms / 1000.0
        with self._lock:
            if deadline is not None:
                est = self._estimate_wait_locked(prio)
                if est is not None and now + est > deadline:
                    self._shed[prio] += 1
                    raise DeadlineInfeasible(
                        f"deadline {deadline_ms:.0f}ms infeasible: "
                        f"estimated queue wait {est:.2f}s at the current "
                        "drain rate",
                        retry_after=self._retry_after_locked(),
                        estimated_wait=est,
                        deadline_s=deadline_ms / 1000.0,
                    )
            self._seq += 1
            e = _Entry(item, prio, client, float(weight), float(cost),
                       deadline, now, self._seq)
            self._entries[id(item)] = e
            self._bands[prio].push(e)
        return prio

    # -- dispatch --------------------------------------------------------------

    def peek(self) -> Any | None:
        """The item pop() would return next, without removing it."""
        with self._lock:
            e = self._peek_entry_locked()
            return e.item if e is not None else None

    def pop(self) -> Any | None:
        with self._lock:
            while self._front:
                e = self._front.popleft()
                if not e.removed:
                    self._entries.pop(id(e.item), None)
                    return e.item
            band = self._choose_band_locked(consume=True)
            if band is None:
                return None
            e = band.pop()
            self._entries.pop(id(e.item), None)
            if not e.counted:
                e.counted = True
                self._admitted[e.priority] += 1
                self._wait_sum[e.priority] += max(
                    0.0, self._clock() - e.t_enqueue
                )
            return e.item

    def _peek_entry_locked(self) -> _Entry | None:
        while self._front and self._front[0].removed:
            self._front.popleft()
        if self._front:
            return self._front[0]
        band = self._choose_band_locked(consume=False)
        return band.peek() if band is not None else None

    def _choose_band_locked(self, consume: bool) -> _Band | None:
        """Pick the band to serve next. ``consume=True`` also updates the
        share credits (peek must be side-effect free so that a deferred
        admission — peek without pop — cannot drain a band's credit)."""
        nonempty = [
            self._bands[c] for c in PRIORITY_CLASSES
            if self._bands[c].depth > 0
        ]
        if not nonempty:
            return None
        chosen = nonempty[0]
        # A passed-over band whose share is due takes precedence; among
        # several due bands, the highest-priority one wins.
        for band in nonempty[1:]:
            if band.credit >= 1.0:
                chosen = band
                break
        if consume:
            if chosen is not nonempty[0]:
                chosen.credit -= 1.0
            for band in nonempty:
                if band is chosen:
                    continue
                share = float(self.policy.queue_shares.get(band.name, 0.0))
                if share > 0.0:
                    # Cap: an idle spell must not bank unbounded credit
                    # and then burst past the share.
                    band.credit = min(band.credit + share, 2.0)
        return chosen

    def requeue_front(self, item: Any) -> None:
        """Re-queue a preempted item at the absolute front (it resumes by
        recompute and must re-admit before anything else). Its original
        enqueue time and class stats are preserved — preemption is
        recompute, not a second queue wait."""
        with self._lock:
            e = self._entries.get(id(item))
            if e is None:
                self._seq += 1
                e = _Entry(item, self.policy.default_priority, "", 1.0, 1.0,
                           None, self._clock(), self._seq)
                e.counted = True
            else:
                # Already queued (shouldn't happen) — pull it out of its
                # band first.
                self._bands[e.priority].discard(e)
                e.removed = False
            self._entries[id(item)] = e
            self._front.appendleft(e)

    def remove(self, item: Any) -> bool:
        """Drop a queued item (cancellation). False if not queued."""
        with self._lock:
            e = self._entries.pop(id(item), None)
            if e is None:
                return False
            if e in self._front:
                e.removed = True  # popped lazily
            else:
                self._bands[e.priority].discard(e)
            return True

    def __contains__(self, item: Any) -> bool:
        with self._lock:
            return id(item) in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __bool__(self) -> bool:
        return len(self) > 0

    def items(self) -> Iterator[Any]:
        """Snapshot of queued items (any order)."""
        with self._lock:
            return iter([e.item for e in self._entries.values()])

    # -- service-rate estimation & feasibility ---------------------------------

    def observe_service(self, cost: float, seconds: float) -> None:
        """Fold one service observation (``cost`` units completed over
        ``seconds`` of wall time) into the decayed drain-rate estimate.
        Zero-completion observations are valid — they pull the rate down
        during stalls."""
        if seconds <= 0 or cost < 0:
            return
        with self._lock:
            d = self.policy.rate_decay
            self._rate_num = d * self._rate_num + cost
            self._rate_den = d * self._rate_den + seconds

    def service_rate(self) -> float | None:
        """Estimated drain rate (cost units/second); None before any
        observation."""
        with self._lock:
            return self._rate_locked()

    def _rate_locked(self) -> float | None:
        if self._rate_den <= 0.0 or self._rate_num <= 0.0:
            return None
        return self._rate_num / self._rate_den

    def estimate_wait(self, priority: str | None = None) -> float | None:
        """Expected queue wait (seconds) for a NEW request of the given
        class: work that will run before it ÷ drain rate. None while the
        rate is unmeasured."""
        prio = priority or self.policy.default_priority
        with self._lock:
            return self._estimate_wait_locked(prio)

    def _estimate_wait_locked(self, priority: str) -> float | None:
        rate = self._rate_locked()
        if rate is None:
            return None
        rank = CLASS_RANK[priority]
        ahead = sum(e.cost for e in self._front) + sum(
            self._bands[c].cost_total
            for c in PRIORITY_CLASSES
            if CLASS_RANK[c] <= rank
        )
        return ahead / rate

    def retry_after(self) -> float:
        """Computed backoff hint: total queued cost ÷ drain rate, clamped
        to the policy's [min, max]. Meaningful even when the rate is
        unmeasured (the min clamp)."""
        with self._lock:
            return self._retry_after_locked()

    def _retry_after_locked(self) -> float:
        rate = self._rate_locked()
        total = sum(e.cost for e in self._front) + sum(
            b.cost_total for b in self._bands.values()
        )
        if rate is None or rate <= 0:
            est = 0.0
        else:
            est = total / rate
        return min(
            max(est, self.policy.min_retry_after_s),
            self.policy.max_retry_after_s,
        )

    # -- introspection ---------------------------------------------------------

    def class_depths(self) -> dict[str, int]:
        with self._lock:
            depths = {c: self._bands[c].depth for c in PRIORITY_CLASSES}
            for e in self._front:
                if not e.removed:
                    depths[e.priority] += 1
            return depths

    def oldest_wait(self) -> float:
        """Age (seconds) of the oldest queued request, 0 when empty —
        the queue-pressure signal the autoscaler consumes."""
        with self._lock:
            now = self._clock()
            oldest = 0.0
            for e in self._entries.values():
                if not e.removed:
                    oldest = max(oldest, now - e.t_enqueue)
            return oldest

    def snapshot(self) -> dict:
        """Serving-state snapshot for /metrics and /v1/state: per-class
        depth / oldest-waiter age / admitted / shed / mean queue wait,
        plus the drain-rate estimate and the current retry hint."""
        with self._lock:
            now = self._clock()
            classes = {}
            oldest_by_class = {c: 0.0 for c in PRIORITY_CLASSES}
            for e in self._entries.values():
                if not e.removed:
                    age = max(0.0, now - e.t_enqueue)
                    if age > oldest_by_class[e.priority]:
                        oldest_by_class[e.priority] = age
            depths = {c: self._bands[c].depth for c in PRIORITY_CLASSES}
            for e in self._front:
                if not e.removed:
                    depths[e.priority] += 1
            for c in PRIORITY_CLASSES:
                admitted = self._admitted[c]
                classes[c] = {
                    "depth": depths[c],
                    "oldest_wait_s": oldest_by_class[c],
                    "admitted_total": admitted,
                    "shed_total": self._shed[c],
                    "mean_queue_wait_s": (
                        self._wait_sum[c] / admitted if admitted else 0.0
                    ),
                }
            rate = self._rate_locked()
            return {
                "classes": classes,
                "depth": sum(depths.values()),
                "oldest_wait_s": max(oldest_by_class.values(), default=0.0),
                "service_rate": rate if rate is not None else 0.0,
                "retry_after_s": self._retry_after_locked(),
            }
