"""SLO-aware request scheduling & admission control.

The serving-path queue discipline that coordinated-autoscaling work
assumes exists ("Taming the Chaos", arXiv:2508.19559) and that
serverless-inference schedulers make central (SLINFER, arXiv:2507.00507):
requests carry a priority class and an optional deadline, the pending
queue orders by them (strict precedence between bands, weighted fair
queueing within a band), and work whose deadline is infeasible given
queue state and measured service rates is shed at enqueue with an honest,
computed retry hint.
"""

from kubeai_tpu.scheduling.scheduler import (
    CLASS_BATCH,
    CLASS_RANK,
    CLASS_REALTIME,
    CLASS_STANDARD,
    DeadlineInfeasible,
    PRIORITY_CLASSES,
    RequestScheduler,
    SchedulingPolicy,
)

__all__ = [
    "CLASS_BATCH",
    "CLASS_RANK",
    "CLASS_REALTIME",
    "CLASS_STANDARD",
    "DeadlineInfeasible",
    "PRIORITY_CLASSES",
    "RequestScheduler",
    "SchedulingPolicy",
]
