"""Production pub/sub broker drivers behind the Messenger's Broker seam.

The reference registers gocloud.dev drivers for SQS/SNS, Azure Service
Bus, GCP Pub/Sub, Kafka, NATS and RabbitMQ (reference:
internal/manager/run.go:47-52). This zero-dependency rebuild speaks the
wire protocols directly:

  GCPPubSubBroker — Google Cloud Pub/Sub REST API (JSON over HTTP):
      subscriptions.pull / acknowledge / modifyAckDeadline and
      topics.publish. Points at the real service (metadata-server OAuth
      on GKE) or at PUBSUB_EMULATOR_HOST / an explicit endpoint (no
      auth) — the official emulator and the test fake speak the same
      surface. nack = modifyAckDeadline(0) → immediate redelivery.

  NATSBroker — core NATS text protocol over TCP (INFO/CONNECT/SUB/PUB/
      MSG/PING/PONG), queue-group subscription so multiple operator
      replicas compete for messages (gocloud natspubsub parity: core
      NATS is at-most-once; ack/nack are no-ops).

  SQSBroker (routing/sqs.py) — the SQS JSON protocol with shared SigV4
      signing: ReceiveMessage long-poll pull, DeleteMessage ack,
      ChangeMessageVisibility(0) nack (gocloud awssnssqs parity).

  KafkaBroker (routing/kafka.py) — the Kafka binary protocol:
      Metadata/Produce/Fetch with record-batch v2 + CRC32C, consumer
      groups (JoinGroup/SyncGroup/Heartbeat, leader-computed range
      assignment), committed offsets as the delivery cursor
      (at-least-once: ack commits offset+1, nack rewinds the fetch
      cursor).

Both carry the reference's failure behavior: the receive path restarts
its subscription with exponential backoff after transport errors
(reference: messenger.go:98-127 recreates the subscription with backoff,
max 20 restarts), and publish failures raise so the Messenger nacks.

URL forms (config `messaging.streams`):
  gcppubsub://projects/P/subscriptions/S   (requestSubscription)
  gcppubsub://projects/P/topics/T          (responseTopic)
  nats://host:4222/subject                 (both)
  kafka://host:9092/topic                  (both)
  sqs://sqs.REGION.amazonaws.com/ACCT/q    (both; routing/sqs.py)
  rabbit://host:5672/queue (or amqp://)    (both; routing/amqp.py)
  azuresb://NS.servicebus.windows.net/q    (both; routing/amqp10.py)
  plain names (no scheme)                  → in-memory MemBroker
"""

from __future__ import annotations

import base64
import http.client
import json
import logging
import os
import queue
import socket
import threading
import time
import urllib.parse

from kubeai_tpu.routing.messenger import Broker, MemBroker, Message

logger = logging.getLogger(__name__)

SUPPORTED_SCHEMES = (
    "mem", "gcppubsub", "nats", "kafka", "sqs", "rabbit", "amqp", "azuresb",
)

# The reference aborts the process after 20 subscription restarts
# (messenger.go:98) and lets the Pod restart. A library thread can't
# usefully kill the manager, so we retry forever with capped backoff and
# log loudly every RESTARTS_LOG_EVERY failures instead — a deaf
# subscription is worse than a noisy one.
RESTARTS_LOG_EVERY = 20


def scheme_of(url: str) -> str:
    return url.split("://", 1)[0] if "://" in url else "mem"


def make_broker(url: str, **kwargs) -> Broker:
    """Build a broker for a stream URL. One broker per stream; brokers
    multiplex subscriptions/topics internally."""
    scheme = scheme_of(url)
    parsed = urllib.parse.urlparse(url if "://" in url else "mem://" + url)
    host = parsed.hostname or "localhost"
    if scheme == "mem":
        return MemBroker()
    if scheme == "gcppubsub":
        return GCPPubSubBroker(**kwargs)
    if scheme == "nats":
        return NATSBroker(host, parsed.port or 4222, **kwargs)
    if scheme == "kafka":
        from kubeai_tpu.routing.kafka import KafkaBroker

        return KafkaBroker(host, parsed.port or 9092, **kwargs)
    if scheme in ("rabbit", "amqp"):
        from kubeai_tpu.routing.amqp import AMQPBroker

        # amqp:// URLs conventionally carry credentials; dropping them
        # would always authenticate as guest/guest, which production
        # RabbitMQ restricts to localhost.
        if parsed.username and "username" not in kwargs:
            kwargs["username"] = urllib.parse.unquote(parsed.username)
        if parsed.password and "password" not in kwargs:
            kwargs["password"] = urllib.parse.unquote(parsed.password)
        return AMQPBroker(host, parsed.port or 5672, **kwargs)
    if scheme == "azuresb":
        from kubeai_tpu.routing.amqp10 import AzureSBBroker

        return AzureSBBroker(host, parsed.port, **kwargs)
    if scheme == "sqs":
        from kubeai_tpu.routing.sqs import SQSBroker

        # The queue URL's host carries the region
        # (sqs.REGION.amazonaws.com) — signing with $AWS_REGION's default
        # against a different-region host would 403 on every call.
        host_parts = host.split(".")
        if (
            "region" not in kwargs
            and len(host_parts) >= 4
            and host_parts[0] == "sqs"
        ):
            kwargs["region"] = host_parts[1]
        return SQSBroker(**kwargs)
    raise ValueError(
        f"unsupported messaging scheme {scheme!r} "
        f"(supported: {', '.join(SUPPORTED_SCHEMES)})"
    )


def _backoff(attempt: int, cap: float = 30.0) -> float:
    return min(0.1 * (2 ** min(attempt, 10)), cap)


# ---- GCP Pub/Sub over REST ---------------------------------------------------


class GCPPubSubBroker:
    """REST driver. `endpoint` like "http://127.0.0.1:8085" (emulator or
    test fake; no auth) or None for https://pubsub.googleapis.com with
    metadata-server OAuth (GKE workload identity)."""

    def __init__(self, endpoint: str | None = None, pull_batch: int = 10):
        endpoint = endpoint or os.environ.get("PUBSUB_EMULATOR_HOST")
        if endpoint and "://" not in endpoint:
            endpoint = "http://" + endpoint
        self.endpoint = endpoint  # None = production API
        self.pull_batch = pull_batch
        # Bounded local queues: the puller blocks when the Messenger falls
        # behind, so a deep subscription backlog stays server-side (where
        # ack deadlines and redelivery are managed) instead of parking
        # unacked in process memory.
        self._queues: dict[str, queue.Queue] = {}
        self._pullers: dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

    # -- transport ------------------------------------------------------------

    def _conn(self) -> http.client.HTTPConnection:
        from kubeai_tpu.objstore import _http

        return _http(self.endpoint, "pubsub.googleapis.com", timeout=35)

    def _auth_header(self) -> dict:
        if self.endpoint:  # emulator/fake: no auth
            return {}
        from kubeai_tpu.objstore import gcp_metadata_token

        token = gcp_metadata_token(required=True)
        return {"Authorization": f"Bearer {token}"}

    def _call(self, method: str, path: str, payload: dict) -> dict:
        conn = self._conn()
        try:
            body = json.dumps(payload).encode()
            headers = {"Content-Type": "application/json"}
            headers.update(self._auth_header())
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise RuntimeError(
                    f"pubsub {path} -> {resp.status}: {data[:200]!r}"
                )
            return json.loads(data) if data else {}
        finally:
            conn.close()

    @staticmethod
    def _resource(url: str) -> str:
        """gcppubsub://projects/p/subscriptions/s -> projects/p/subscriptions/s"""
        if "://" in url:
            parsed = urllib.parse.urlparse(url)
            return (parsed.netloc + parsed.path).strip("/")
        return url.strip("/")

    # -- Broker interface -------------------------------------------------------

    def publish(self, topic: str, body: bytes) -> None:
        self._call(
            "POST",
            f"/v1/{self._resource(topic)}:publish",
            {"messages": [{"data": base64.b64encode(body).decode()}]},
        )

    def receive(self, subscription: str, timeout: float) -> Message | None:
        sub = self._resource(subscription)
        with self._lock:
            if sub not in self._queues:
                self._queues[sub] = queue.Queue(maxsize=2 * self.pull_batch)
                t = threading.Thread(
                    target=self._pull_loop, args=(sub,), daemon=True
                )
                self._pullers[sub] = t
                t.start()
        try:
            return self._queues[sub].get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()

    # -- pull loop with subscription-restart backoff ----------------------------

    def _pull_loop(self, sub: str) -> None:
        restarts = 0
        while not self._stop.is_set():
            try:
                out = self._call(
                    "POST", f"/v1/{sub}:pull", {"maxMessages": self.pull_batch}
                )
                restarts = 0
            except (socket.timeout, TimeoutError):
                # An idle synchronous pull can outlive the socket timeout —
                # that's a quiet subscription, not a failure.
                continue
            except Exception as e:
                restarts += 1
                log = (
                    logger.error
                    if restarts % RESTARTS_LOG_EVERY == 0
                    else logger.warning
                )
                log("pubsub pull %s failed (restart %d): %s", sub, restarts, e)
                if self._stop.wait(_backoff(restarts)):
                    return
                continue
            for rm in out.get("receivedMessages", []):
                ack_id = rm["ackId"]
                data = base64.b64decode(
                    (rm.get("message") or {}).get("data", "")
                )
                msg = Message(
                    data,
                    on_ack=lambda a=ack_id: self._ack(sub, a),
                    on_nack=lambda a=ack_id: self._nack(sub, a),
                )
                # Bounded put: blocks (flow control) until the Messenger
                # drains; poll so stop() still wins.
                while not self._stop.is_set():
                    try:
                        self._queues[sub].put(msg, timeout=1.0)
                        break
                    except queue.Full:
                        continue

    def _ack(self, sub: str, ack_id: str) -> None:
        try:
            self._call("POST", f"/v1/{sub}:acknowledge", {"ackIds": [ack_id]})
        except Exception:
            logger.warning("pubsub ack failed (message will redeliver)",
                           exc_info=True)

    def _nack(self, sub: str, ack_id: str) -> None:
        # Ack deadline 0 = immediate redelivery (gocloud parity).
        try:
            self._call(
                "POST",
                f"/v1/{sub}:modifyAckDeadline",
                {"ackIds": [ack_id], "ackDeadlineSeconds": 0},
            )
        except Exception:
            logger.warning("pubsub nack failed", exc_info=True)


# ---- NATS over TCP -----------------------------------------------------------


class NATSBroker:
    """Core NATS client: queue-group subscriptions, auto-reconnect with
    backoff + re-SUB (the reference's subscription-recreate behavior).
    At-most-once: ack/nack are no-ops, matching gocloud natspubsub."""

    def __init__(
        self, host: str, port: int = 4222, queue_group: str = "kubeai"
    ):
        self.host, self.port = host, port
        self.queue_group = queue_group
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._queues: dict[str, queue.Queue] = {}  # subject -> messages
        self._sids: dict[int, str] = {}  # sid -> subject
        self._next_sid = 1
        self._stop = threading.Event()
        self._reader: threading.Thread | None = None

    @staticmethod
    def _subject(url: str) -> str:
        if "://" in url:
            return urllib.parse.urlparse(url).path.strip("/") or "default"
        return url

    # -- connection -------------------------------------------------------------

    def _connect_locked(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        # Connect timeout only: as a read timeout, any subject idle for
        # >10 s (NATS server PINGs default to ~2 min) would look like a
        # dead connection and churn reconnects forever.
        sock.settimeout(None)
        f = sock.makefile("rb")
        info = f.readline()  # INFO {...}
        if not info.startswith(b"INFO"):
            raise RuntimeError(f"unexpected NATS greeting: {info[:60]!r}")
        sock.sendall(
            b'CONNECT {"verbose":false,"pedantic":false,'
            b'"name":"kubeai-tpu","lang":"python","version":"1"}\r\n'
        )
        self._sock, self._file = sock, f
        # Re-establish every subscription on (re)connect.
        for sid, subject in self._sids.items():
            sock.sendall(
                f"SUB {subject} {self.queue_group} {sid}\r\n".encode()
            )
        if self._reader is None or not self._reader.is_alive():
            self._reader = threading.Thread(
                target=self._read_loop, daemon=True
            )
            self._reader.start()

    def _ensure_connected(self) -> None:
        with self._lock:
            if self._sock is None:
                self._connect_locked()

    def _read_loop(self) -> None:
        restarts = 0
        while not self._stop.is_set():
            try:
                f = self._file
                line = f.readline()
                if not line:
                    raise ConnectionError("NATS connection closed")
                if line.startswith(b"MSG"):
                    # MSG <subject> <sid> [reply-to] <#bytes>
                    parts = line.decode().split()
                    subject, nbytes = parts[1], int(parts[-1])
                    payload = f.read(nbytes)
                    f.read(2)  # trailing \r\n
                    q = self._queues.get(subject)
                    if q is not None:
                        q.put(Message(payload))  # ack/nack: core NATS no-ops
                elif line.startswith(b"PING"):
                    with self._wlock:
                        self._sock.sendall(b"PONG\r\n")
                restarts = 0
                # -ERR / +OK / PONG lines are ignored.
            except Exception as e:
                if self._stop.is_set():
                    return
                restarts += 1
                log = (
                    logger.error
                    if restarts % RESTARTS_LOG_EVERY == 0
                    else logger.warning
                )
                log("NATS connection lost (reconnect %d): %s", restarts, e)
                with self._lock:
                    self._close_locked()
                # Back off WITHOUT the lock: publish()/receive() must be
                # able to fail fast (and nack) during the outage instead
                # of blocking behind the reconnect sleep.
                if self._stop.wait(_backoff(restarts)):
                    return
                with self._lock:
                    if self._sock is None:
                        try:
                            self._connect_locked()
                        except Exception:
                            self._sock = None  # retried next iteration

    def _close_locked(self) -> None:
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None

    # -- Broker interface -------------------------------------------------------

    def publish(self, topic: str, body: bytes) -> None:
        subject = self._subject(topic)
        self._ensure_connected()
        with self._wlock:
            self._sock.sendall(
                f"PUB {subject} {len(body)}\r\n".encode() + body + b"\r\n"
            )

    def receive(self, subscription: str, timeout: float) -> Message | None:
        subject = self._subject(subscription)
        with self._lock:
            if subject not in self._queues:
                self._queues[subject] = queue.Queue()
                sid = self._next_sid
                self._next_sid += 1
                self._sids[sid] = subject
                if self._sock is None:
                    try:
                        self._connect_locked()  # SUBs sent on connect
                    except Exception as e:
                        del self._queues[subject], self._sids[sid]
                        raise ConnectionError(f"NATS connect failed: {e}")
                else:
                    with self._wlock:
                        self._sock.sendall(
                            f"SUB {subject} {self.queue_group} {sid}\r\n".encode()
                        )
        try:
            return self._queues[subject].get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            self._close_locked()
