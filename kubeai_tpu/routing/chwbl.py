"""Consistent Hashing With Bounded Loads — the PrefixHash strategy's core
(reference: internal/loadbalancer/balance_chwbl.go).

Ring: each endpoint is inserted `replication` times (vnodes); a request's
prefix hashes to a point; we walk clockwise until we find an endpoint whose
in-flight load is within the bound:

    load <= ceil((total_in_flight + 1) / num_endpoints) * load_factor

(reference: balance_chwbl.go:152-162). Adapter-aware walk: endpoints not
serving the requested adapter are skipped; if no adapter-serving endpoint
meets the bound, the first adapter-serving endpoint in ring order is
returned, and an endpoint without the adapter is never returned
(reference: balance_chwbl.go:14-84 defaultEndpoint).

Uses the native C++ ring (kubeai_tpu.native) when available; the pure-
Python path is the reference semantics and test oracle.
"""

from __future__ import annotations

import bisect

from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.routing.xxhash import xxhash64


class CHWBL:
    def __init__(
        self,
        load_factor: float = 1.25,
        replication: int = 256,
        metrics: Metrics = DEFAULT_METRICS,
    ):
        self.load_factor = load_factor
        self.replication = replication
        self.metrics = metrics
        self._hashes: list[int] = []  # sorted ring points
        self._ring: dict[int, str] = {}  # point -> endpoint
        self._members: set[str] = set()  # O(1) membership

    def _point(self, endpoint: str, i: int) -> int:
        return xxhash64(f"{endpoint}{i}".encode())

    def add(self, endpoint: str) -> None:
        self._members.add(endpoint)
        for i in range(self.replication):
            h = self._point(endpoint, i)
            if h in self._ring:
                continue
            self._ring[h] = endpoint
            bisect.insort(self._hashes, h)

    def remove(self, endpoint: str) -> None:
        self._members.discard(endpoint)
        for i in range(self.replication):
            h = self._point(endpoint, i)
            if self._ring.get(h) == endpoint:
                del self._ring[h]
                idx = bisect.bisect_left(self._hashes, h)
                if idx < len(self._hashes) and self._hashes[idx] == h:
                    self._hashes.pop(idx)

    def __contains__(self, endpoint: str) -> bool:
        # O(1): the LB checks membership on every sync; scanning all
        # replication × N ring values was O(R·N) per check.
        return endpoint in self._members

    def get(
        self,
        key: str,
        loads: dict[str, int],
        adapter_endpoints: set[str] | None = None,
    ) -> str | None:
        """Pick an endpoint for `key`. `loads` maps endpoint -> in-flight
        count (must cover every ring endpoint). `adapter_endpoints`
        restricts preferred endpoints (None = no restriction)."""
        if not self._hashes:
            return None
        self.metrics.chwbl_lookups.inc()
        total = sum(loads.values())
        n = max(len(loads), 1)
        # "+1" simulates the incoming request (reference: balance_chwbl.go:152-162).
        threshold = (total + 1) / n * self.load_factor

        def load_ok(ep: str) -> bool:
            return total == 0 or loads.get(ep, 0) <= threshold

        # surrogatepass: a lone-surrogate key (invalid JSON escapes the
        # front door passed through) must hash deterministically, never
        # raise — apiutils sanitizes its prefixes, but CHWBL is also used
        # with raw keys.
        start = bisect.bisect_left(
            self._hashes, xxhash64(key.encode("utf-8", "surrogatepass"))
        ) % len(self._hashes)
        # The default is the FIRST endpoint in ring order that can serve the
        # request (has the adapter); it is returned when no serving-capable
        # endpoint meets the load bound. An endpoint without the adapter is
        # never returned — the engine would silently serve the base model
        # (reference: balance_chwbl.go defaultEndpoint, :29-31,74-84).
        default: str | None = None
        seen: set[str] = set()
        displaced = False
        for off in range(len(self._hashes)):
            h = self._hashes[(start + off) % len(self._hashes)]
            ep = self._ring[h]
            if ep in seen:
                continue
            seen.add(ep)
            if adapter_endpoints is not None and ep not in adapter_endpoints:
                continue
            if default is None:
                default = ep
            if load_ok(ep):
                if displaced:
                    self.metrics.chwbl_displacements.inc()
                return ep
            displaced = True
        # None ⇔ no endpoint serves the adapter; caller falls back to
        # least-load over adapter-serving candidates.
        return default


class _NativeRing:
    """Thin adapter over the C++ ring: same interface as CHWBL, Python-side
    metrics accounting."""

    def __init__(self, native, metrics: Metrics):
        self._native = native
        self.metrics = metrics

    def add(self, endpoint: str) -> None:
        self._native.add(endpoint)

    def remove(self, endpoint: str) -> None:
        self._native.remove(endpoint)

    def get(self, key, loads, adapter_endpoints=None):
        self.metrics.chwbl_lookups.inc()
        return self._native.get(key, loads, adapter_endpoints)


def make_ring(
    load_factor: float = 1.25,
    replication: int = 256,
    metrics: Metrics = DEFAULT_METRICS,
    prefer_native: bool = True,
):
    """Build the CHWBL ring: native C++ when the library is available
    (tests assert pick-for-pick parity with the Python oracle), else the
    pure-Python implementation."""
    if prefer_native:
        try:
            from kubeai_tpu.native import NativeCHWBL, load_native

            if load_native() is not None:
                return _NativeRing(
                    NativeCHWBL(load_factor, replication), metrics
                )
        except Exception:
            pass
    return CHWBL(load_factor, replication, metrics)
