"""Routing tier: request parsing, load balancing, proxying, serving mux
(reference: internal/{apiutils,loadbalancer,modelproxy,openaiserver}).
"""
