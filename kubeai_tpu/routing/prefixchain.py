"""Front-door page-hash chain computation — the routing half of the
cluster KV-sharing tier.

The engine keys its prefix cache by a page-aligned content-hash chain
over the PROMPT TOKENS (engine.py `_prefix_hashes`): a blake2b-16 chain
seeded `apc1:<adapter_idx>:<generation>`, folded one full page of int32
token ids at a time. For longest-held-prefix routing the front door must
produce the SAME chain the serving engine would — which means the same
tokenization (`apply_chat_template` for chat, `encode` for completions)
and the same hash fold, bit for bit. `tests/unit/test_kv_sharing.py`
asserts parity against the live engine.

Base-model chains only (`adapter_idx=0, gen=0`): LoRA adapters occupy
per-replica slot indices, so adapter chains are incomparable across
replicas — adapter requests keep the classic char-prefix CHWBL key.

The tokenizer comes from the same `load_tokenizer` seam the engine uses:
a model directory shared with (or mirroring) the engine's yields the
HuggingFace tokenizer; no directory yields the deterministic
ByteTokenizer both sides agree on in offline tests.
"""

from __future__ import annotations

import hashlib

import numpy as np

from kubeai_tpu.engine.tokenizer import load_tokenizer

CHAIN_SEED_PREFIX = "apc1"


def page_hash_chain(
    token_ids: list[int],
    page_size: int,
    adapter_idx: int = 0,
    gen: int = 0,
) -> list[str]:
    """Hex blake2b-16 chain over full pages of `token_ids` — must stay
    bit-identical to engine.py `_prefix_hashes`."""
    h = hashlib.blake2b(
        f"{CHAIN_SEED_PREFIX}:{adapter_idx}:{gen}".encode(), digest_size=16
    ).digest()
    arr = np.asarray(token_ids, np.int32)
    out: list[str] = []
    for i in range(len(token_ids) // page_size):
        h = hashlib.blake2b(
            h + arr[i * page_size : (i + 1) * page_size].tobytes(),
            digest_size=16,
        ).digest()
        out.append(h.hex())
    return out


class ChainComputer:
    """Per-model chain oracle for the proxy: tokenizes a request body
    exactly as the engine server's generate handler does and hashes the
    result. Construction is cheap for the ByteTokenizer path; HF
    tokenizers load once and are reused across requests."""

    def __init__(self, page_size: int, tokenizer_dir: str = ""):
        if page_size <= 0:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.page_size = page_size
        self.tokenizer = load_tokenizer(tokenizer_dir or "")

    def prompt_ids(self, body: dict, chat: bool) -> list[int]:
        """Replicates EngineServer._handle_generate tokenization,
        including the empty-prompt [0] default."""
        if chat:
            messages = body.get("messages") or []
            ids = self.tokenizer.apply_chat_template(messages)
        else:
            prompt = body.get("prompt", "")
            if isinstance(prompt, list):
                prompt = prompt[0] if prompt else ""
            ids = self.tokenizer.encode(str(prompt))
        return ids or [0]

    def chain_for_request(self, body: dict, chat: bool) -> list[str]:
        """The request's routable chain: full-page hashes capped at the
        engine's admission hit limit ((plen-1)//page_size — the final
        token always computes its own logits), so routing never chases
        pages no engine could adopt."""
        ids = self.prompt_ids(body, chat)
        chain = page_hash_chain(ids, self.page_size)
        return chain[: max(0, (len(ids) - 1) // self.page_size)]
