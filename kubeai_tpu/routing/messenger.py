"""Async pub/sub ingestion path (reference: internal/messenger/messenger.go).

Requests arrive as messages:
    {"metadata": {...}, "path": "/v1/chat/completions", "body": {...}}
and responses are published as:
    {"metadata": {...}, "status_code": N, "body": {...}}
(reference: messenger.go:180-348). A missing "path" defaults to
/v1/completions and a missing leading "/" is prepended
(reference: messenger.go:266-272).

The broker seam mirrors gocloud.dev/pubsub's driver model
(reference: internal/manager/run.go:47-52 registers SQS/PubSub/Kafka/...);
`MemBroker` is the `mem://` driver used by tests
(reference: test/integration/main_test.go:18,60-62). Production drivers
plug in behind the same two methods.

Failure behavior mirrored: per-message handler semaphore (`maxHandlers`),
responses published BEFORE ack (publish failure → Nack → redelivery),
bad-request replies count toward the consecutive-error throttle so a
malformed-message flood backs off (reference: messenger.go:98-178).
"""

from __future__ import annotations

import inspect
import json
import logging
import queue
import threading
from typing import Protocol

from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.routing import apiutils
from kubeai_tpu.routing.loadbalancer import LoadBalancer, LoadBalancerTimeout
from kubeai_tpu.routing.modelclient import (
    AdapterNotFound,
    ModelClient,
    ModelNotFound,
)

logger = logging.getLogger(__name__)

DEFAULT_PATH = "/v1/completions"

# Message-metadata keys mapped onto the SLO-scheduling headers the engine
# parses (kubeai_tpu/scheduling): async requests carry the same priority/
# deadline/fairness identity as HTTP ones, so a batch pipeline publishing
# messages competes in the same queue discipline as interactive clients.
METADATA_SCHEDULING_KEYS = (
    ("priority", "X-Priority"),
    ("deadline_ms", "X-Deadline-Ms"),
    ("client_id", "X-Client-Id"),
)


def scheduling_headers(metadata: dict) -> dict[str, str]:
    """Extract scheduling headers from a message's metadata block.
    Values are stringified verbatim — validation happens at the engine
    (a bad class/deadline answers 400, which flows back on the response
    topic like any other client error)."""
    headers: dict[str, str] = {}
    for key, header in METADATA_SCHEDULING_KEYS:
        value = metadata.get(key)
        if value is not None and value != "":
            headers[header] = str(value)
    return headers


class Message:
    """One delivered message. `on_ack`/`on_nack` carry the driver's side
    effects (Pub/Sub acknowledge / modifyAckDeadline(0); no-ops for
    MemBroker and core NATS). ack/nack are idempotent — the first call
    wins, mirroring broker semantics."""

    def __init__(self, body: bytes, on_ack=None, on_nack=None):
        self.body = body
        self.acked: bool | None = None
        self._on_ack = on_ack
        self._on_nack = on_nack

    def ack(self) -> None:
        if self.acked is not None:
            return
        self.acked = True
        if self._on_ack:
            self._on_ack()

    def nack(self) -> None:
        if self.acked is not None:
            return
        self.acked = False
        if self._on_nack:
            self._on_nack()


class Broker(Protocol):
    def receive(self, subscription: str, timeout: float) -> Message | None: ...
    def publish(self, topic: str, body: bytes) -> None: ...


class MemBroker:
    """In-memory pub/sub (the `mem://` driver equivalent)."""

    def __init__(self):
        self._topics: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()

    def _q(self, name: str) -> queue.Queue:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = queue.Queue()
            return self._topics[name]

    def publish(self, topic: str, body: bytes) -> None:
        self._q(topic).put(Message(body))

    def receive(self, subscription: str, timeout: float) -> Message | None:
        try:
            return self._q(subscription).get(timeout=timeout)
        except queue.Empty:
            return None


class Messenger:
    def __init__(
        self,
        broker: Broker,
        request_subscription: str,
        response_topic: str,
        lb: LoadBalancer,
        model_client: ModelClient,
        max_handlers: int = 100,
        error_max_backoff: float = 30.0,
        http_send=None,  # injectable for tests
        metrics: Metrics = DEFAULT_METRICS,
        usage=None,
        governor=None,
    ):
        self.metrics = metrics
        # Per-tenant usage metering (kubeai_tpu/fleet/metering): async
        # requests carry the same tenant identity as HTTP ones via
        # metadata.client_id, so a batch pipeline's tokens land in the
        # same ledger interactive traffic does.
        self.usage = usage
        # Tenant admission (kubeai_tpu/fleet/tenancy): same door policy
        # as the HTTP path, applied before the scale/dispatch work.
        self.governor = governor
        self.broker = broker
        self.request_subscription = request_subscription
        self.response_topic = response_topic
        self.lb = lb
        self.model_client = model_client
        self._semaphore = threading.Semaphore(max_handlers)
        self.error_max_backoff = error_max_backoff
        # Two throttles: handler errors (bad-request floods, backend
        # failures — reset on a clean request) and transport errors
        # (broker receive failures — reset on any successful receive, so
        # an idle stream doesn't stay pinned at max backoff after an
        # outage).
        self._consecutive_errors = 0
        self._transport_errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._http_send = http_send or self._default_http_send
        # Backward-compatible seam: older injected senders take
        # (addr, path, body); scheduling-aware ones add a headers kwarg.
        try:
            params = inspect.signature(self._http_send).parameters
            self._send_takes_headers = "headers" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            )
        except (TypeError, ValueError):  # builtins / C callables
            self._send_takes_headers = False

    def start(self) -> None:
        self._thread = threading.Thread(target=self._receive_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    # -- receive loop (reference: messenger.go:82-178) --------------------------

    def _receive_loop(self) -> None:
        while not self._stop.is_set():
            # Consecutive-error throttle (reference: messenger.go:156-178).
            errors = max(self._consecutive_errors, self._transport_errors)
            if errors:
                backoff = min(
                    2 ** min(errors, 10) * 0.1,
                    self.error_max_backoff,
                )
                if self._stop.wait(backoff):
                    return
            # Reserve a handler slot BEFORE pulling a message, so a message
            # is never stranded un-acked while we wait; keep the wait
            # interruptible by stop().
            if not self._semaphore.acquire(timeout=0.2):
                continue
            if self._stop.is_set():
                self._semaphore.release()
                return
            try:
                msg = self.broker.receive(self.request_subscription, timeout=0.2)
            except Exception as e:
                # A driver may raise on transport failure (e.g. NATS
                # connect refused); the loop must survive and retry —
                # a dead receive loop deafens the stream permanently.
                logger.warning("broker receive failed: %s", e)
                self._semaphore.release()
                self._transport_errors += 1
                continue
            # A successful receive — even an empty one — proves transport
            # health; the handler-error throttle is tracked separately.
            self._transport_errors = 0
            if msg is None:
                self._semaphore.release()
                continue
            threading.Thread(
                target=self._handle_wrapper, args=(msg,), daemon=True
            ).start()

    def _handle_wrapper(self, msg: Message) -> None:
        try:
            err = self.handle_request(msg)
            self._consecutive_errors = (
                0 if not err else self._consecutive_errors + 1
            )
        except Exception:
            logger.exception("messenger handler crashed")
            msg.nack()
            self._consecutive_errors += 1
        finally:
            self._semaphore.release()

    # -- one request (reference: messenger.go:180-348) --------------------------

    def handle_request(self, msg: Message) -> bool:
        """Process one message. Returns True when the error throttle should
        count this message (bad requests included — a malformed flood must
        back off; reference: messenger.go:148-155)."""
        metadata: dict = {}
        try:
            envelope = json.loads(msg.body)
            metadata = envelope.get("metadata") or {}
            path = envelope.get("path") or DEFAULT_PATH
            if not path.startswith("/"):
                path = "/" + path
            body = json.dumps(envelope["body"]).encode()
        except (json.JSONDecodeError, KeyError, TypeError, AttributeError) as e:
            return self._reply_error(
                msg, metadata, 400, f"invalid message envelope: {e}"
            )

        try:
            preq = apiutils.parse_request(body, path, {})
        except apiutils.APIError as e:
            return self._reply_error(msg, metadata, e.status, e.message)

        try:
            model = self.model_client.lookup_model(
                preq.model, preq.adapter, preq.selectors
            )
        except (ModelNotFound, AdapterNotFound) as e:
            return self._reply_error(
                msg, metadata, 404, f"model not found: {e}"
            )

        # Tenant admission before any work is queued: no scale-up, no
        # load-balancer wait, no dispatch for a refused message. The
        # shed response (429 + retry_after_s hint) publishes before ack,
        # like every reply; a deliberate refusal is not a handler error,
        # so it never feeds the consecutive-error throttle.
        if self.governor is not None:
            refusal = self.governor.admit_message(metadata, model, body)
            if refusal is not None:
                if self.usage is not None:
                    self.usage.record_response(
                        refusal.tenant, model.name, refusal.status
                    )
                ok = self._respond(
                    metadata,
                    refusal.status,
                    {
                        "error": {
                            "message": refusal.message,
                            "type": "rate_limit_exceeded",
                            "code": refusal.reason,
                        },
                        "retry_after_s": round(refusal.retry_after_s, 3),
                    },
                )
                if ok:
                    msg.ack()
                else:
                    msg.nack()
                return False

        self.metrics.inference_requests_active.inc(model=model.name)
        self.metrics.inference_requests_total.inc(model=model.name)
        try:
            self.model_client.scale_at_least_one_replica(model.name)
            addr, done = self.lb.await_best_address(
                model.name,
                adapter=preq.adapter,
                prefix=preq.prefix,
                strategy=model.spec.load_balancing.strategy,
            )
            try:
                if self._send_takes_headers:
                    status, resp_body = self._http_send(
                        addr, path, preq.body,
                        headers=scheduling_headers(metadata),
                    )
                else:
                    status, resp_body = self._http_send(addr, path, preq.body)
            finally:
                done()
        except LoadBalancerTimeout:
            self._respond(metadata, 503, {"error": {"message": "no endpoints ready"}})
            msg.nack()
            return True
        except Exception as e:
            msg.nack()
            logger.warning("backend send failed: %s", e)
            return True
        finally:
            self.metrics.inference_requests_active.dec(model=model.name)

        try:
            parsed = json.loads(resp_body)
        except json.JSONDecodeError:
            parsed = {"raw": resp_body.decode(errors="replace")}
        if self.usage is not None:
            self.usage.record_response(
                str(metadata.get("client_id") or "") or None,
                model.name,
                status,
                usage=(
                    parsed.get("usage")
                    if isinstance(parsed, dict) else None
                ),
            )
        if self._respond(metadata, status, parsed):
            msg.ack()
            return False
        msg.nack()  # publish failure → redelivery (reference: messenger.go:308-348)
        return True

    def _reply_error(
        self, msg: Message, metadata: dict, status: int, message: str
    ) -> bool:
        """Bad-request reply: publish first, ack only if published; always
        counts toward the throttle."""
        ok = self._respond(metadata, status, {"error": {"message": message}})
        if ok:
            msg.ack()
        else:
            msg.nack()
        return True

    def _respond(self, metadata: dict, status: int, body: dict) -> bool:
        payload = json.dumps(
            {"metadata": metadata, "status_code": status, "body": body}
        ).encode()
        try:
            self.broker.publish(self.response_topic, payload)
            return True
        except Exception:
            logger.exception("publishing response failed")
            return False

    @staticmethod
    def _default_http_send(
        addr: str, path: str, body: bytes, headers: dict | None = None
    ) -> tuple[int, bytes]:
        """Plain non-streaming POST (reference: messenger.go:285-306)."""
        import http.client

        host, _, port = addr.partition(":")
        conn = http.client.HTTPConnection(host, int(port or 80), timeout=300)
        try:
            conn.request(
                "POST",
                path,
                body=body,
                headers={"Content-Type": "application/json", **(headers or {})},
            )
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()
