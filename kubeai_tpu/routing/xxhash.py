"""xxHash64 (pure Python; the native C++ implementation in native/ is used
when built — see kubeai_tpu.routing.chwbl). Same algorithm family the
reference uses for its CHWBL ring (reference: internal/loadbalancer/
balance_chwbl.go uses cespare/xxhash)."""

from __future__ import annotations

import struct

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5
_M = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M


def _round(acc: int, inp: int) -> int:
    acc = (acc + inp * _P2) & _M
    acc = _rotl(acc, 31)
    return (acc * _P1) & _M


def _merge_round(acc: int, val: int) -> int:
    acc ^= _round(0, val)
    return ((acc * _P1) + _P4) & _M


def xxhash64(data: bytes, seed: int = 0) -> int:
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M
        v2 = (seed + _P2) & _M
        v3 = seed
        v4 = (seed - _P1) & _M
        while i <= n - 32:
            x1, x2, x3, x4 = struct.unpack_from("<QQQQ", data, i)
            v1 = _round(v1, x1)
            v2 = _round(v2, x2)
            v3 = _round(v3, x3)
            v4 = _round(v4, x4)
            i += 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)) & _M
        h = _merge_round(h, v1)
        h = _merge_round(h, v2)
        h = _merge_round(h, v3)
        h = _merge_round(h, v4)
    else:
        h = (seed + _P5) & _M
    h = (h + n) & _M
    while i <= n - 8:
        (k1,) = struct.unpack_from("<Q", data, i)
        h ^= _round(0, k1)
        h = (_rotl(h, 27) * _P1 + _P4) & _M
        i += 8
    if i <= n - 4:
        (k1,) = struct.unpack_from("<I", data, i)
        h ^= (k1 * _P1) & _M
        h = (_rotl(h, 23) * _P2 + _P3) & _M
        i += 4
    while i < n:
        h ^= (data[i] * _P5) & _M
        h = (_rotl(h, 11) * _P1) & _M
        i += 1
    h ^= h >> 33
    h = (h * _P2) & _M
    h ^= h >> 29
    h = (h * _P3) & _M
    h ^= h >> 32
    return h
