"""Gossiped CRDT state plane for the sharded front door.

The front door (TenantGovernor + breakers + prefix-holdings routing)
used to be ONE process; running N of them naively means N x every
token bucket and N independent breaker views.  This module gives N
door shards a decentralized, partition-tolerant shared brain built
from state-based CRDTs:

* ``GCounter`` / ``PNCounter`` — per-(tenant, model) token-bucket
  consumption and UsageMeter ledger totals.  Components are keyed by
  shard name; merge is an element-wise max, so re-delivered deltas are
  idempotent and any merge order converges to the same bytes.
* ``LWWRegister`` / ``LWWMap`` — breaker states, the global overload
  latch, and the per-model KV-holdings map.  Timestamps are hybrid
  logical clocks (``HLC``), never wall clock, so ordering is total and
  deterministic under clock skew between shards.
* ``FWWRegister`` — first-writer-wins claims for half-open breaker
  probe election (exactly one shard probes per half-open window).
* ``DoorShardSet`` — membership plus the anti-entropy loop: push-pull
  digest exchange, delta-state sync with per-peer dirty tracking,
  per-peer staleness, and a partition seam for chaos drills.  When a
  shard cannot hear its peers it degrades to local-view enforcement
  with a conservative budget split (see ``DoorGossipNode.split``).

Determinism contract: everything in this file is driven by an injected
clock (FakeClock in tests/sims) and deterministic peer rotation — no
wall clock, no unseeded randomness.  Serialization is sorted-key JSON
so converged state is byte-comparable across shards.
"""

from __future__ import annotations

import hashlib
import json
import logging

logger = logging.getLogger(__name__)

# Registry consumed by scripts/check_shared_state.py: mutable
# cross-request state fields on the door/breaker classes that are
# backed by this state plane.  Every other mutable field on these
# classes must carry a reviewed `# local-state:` pragma.  The gate
# checks both directions (unregistered field -> violation; registered
# field that no longer exists -> violation).
CRDT_BACKED_FIELDS: dict[str, tuple[str, ...]] = {
    # fleet/tenancy.py — bucket consumption is gossiped as G-Counters,
    # the overload latch as an LWW register.
    "TenantGovernor": ("_buckets", "_overload"),
    # routing/health.py — open/half-open transitions are published as
    # LWW entries and adopted by peer shards.
    "EndpointHealth": ("state", "_opened_at"),
    # routing/loadbalancer.py — per-endpoint prefix-chain holdings are
    # read from the gossiped LWW map when a provider is wired.
    "Group": ("_kv_holdings", "_kv_holdings_ts"),
    # fleet/metering.py — the billing ledger merges peer-shard
    # cumulative snapshots (G-Counter semantics per component).
    "UsageMeter": ("_ledger", "_remote"),
}


def _canon(obj) -> str:
    """Canonical JSON used for digests and byte-compare convergence."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


# ---------------------------------------------------------------------------
# Hybrid logical clock


class HLC:
    """Hybrid logical clock: stamps are ``(physical, logical, node)``.

    ``physical`` comes from the injected clock (FakeClock in tests) —
    never ``time.time()`` directly — and ``logical`` breaks ties so
    stamps issued by one node are strictly increasing even when the
    clock does not advance.  Tuple comparison gives a deterministic
    total order under arbitrary clock skew between shards.
    """

    __slots__ = ("node", "_clock", "physical", "logical")

    def __init__(self, node: str, clock) -> None:
        self.node = node
        self._clock = clock
        self.physical = 0.0
        self.logical = 0

    def tick(self) -> tuple[float, int, str]:
        """Stamp a local event."""
        now = float(self._clock())
        if now > self.physical:
            self.physical, self.logical = now, 0
        else:
            self.logical += 1
        return (self.physical, self.logical, self.node)

    def observe(self, stamp) -> None:
        """Fold a remote stamp so future local stamps sort after it."""
        rp, rl = float(stamp[0]), int(stamp[1])
        now = float(self._clock())
        top = max(self.physical, rp, now)
        if top == self.physical and top == rp:
            self.logical = max(self.logical, rl) + 1
        elif top == self.physical:
            self.logical += 1
        elif top == rp:
            self.logical = rl + 1
        else:
            self.logical = 0
        self.physical = top


# ---------------------------------------------------------------------------
# CRDT primitives


class GCounter:
    """Grow-only counter: one monotone component per shard.

    ``merge`` is element-wise max — commutative, associative,
    idempotent — so counting is exact under re-delivery and arbitrary
    merge order.  Components may be ``set`` to a cumulative value
    (ledger snapshots) or ``add``-ed (bucket consumption); both keep
    the per-component monotonicity the merge relies on.
    """

    __slots__ = ("counts",)

    def __init__(self, counts: dict[str, float] | None = None) -> None:
        self.counts: dict[str, float] = dict(counts or {})

    def add(self, node: str, n: float) -> None:
        if n < 0:
            raise ValueError("GCounter.add requires n >= 0")
        if n == 0 and node not in self.counts:
            # Never materialize a zero component: merge only copies
            # strictly-greater values, so an explicit 0.0 would live on
            # one replica but never transfer — semantically equal
            # states with different bytes, a permanent digest mismatch.
            return
        self.counts[node] = self.counts.get(node, 0.0) + n

    def set_component(self, node: str, value: float) -> None:
        cur = self.counts.get(node, 0.0)
        if value < cur:
            raise ValueError(
                f"GCounter component for {node} would regress "
                f"({value} < {cur})"
            )
        self.counts[node] = value

    def value(self) -> float:
        return sum(self.counts.values())

    def of(self, node: str) -> float:
        return self.counts.get(node, 0.0)

    def except_of(self, node: str) -> float:
        return sum(v for k, v in self.counts.items() if k != node)

    def merge(self, other: "GCounter") -> bool:
        changed = False
        for node, v in other.counts.items():
            if v > self.counts.get(node, 0.0):
                self.counts[node] = v
                changed = True
        return changed

    def to_wire(self) -> dict:
        # Zero components are dropped from the canonical form (they
        # contribute nothing and cannot transfer through merge), so
        # byte-compared digests agree across replicas.
        return {
            "t": "g",
            "c": {k: v for k, v in sorted(self.counts.items()) if v != 0.0},
        }

    @classmethod
    def from_wire(cls, wire: dict) -> "GCounter":
        return cls({str(k): float(v) for k, v in wire["c"].items()})


class PNCounter:
    """Positive-negative counter: a G-Counter pair (adds, removes)."""

    __slots__ = ("pos", "neg")

    def __init__(self, pos: GCounter | None = None,
                 neg: GCounter | None = None) -> None:
        self.pos = pos or GCounter()
        self.neg = neg or GCounter()

    def add(self, node: str, n: float) -> None:
        if n >= 0:
            self.pos.add(node, n)
        else:
            self.neg.add(node, -n)

    def value(self) -> float:
        return self.pos.value() - self.neg.value()

    def merge(self, other: "PNCounter") -> bool:
        a = self.pos.merge(other.pos)
        b = self.neg.merge(other.neg)
        return a or b

    def to_wire(self) -> dict:
        return {"t": "pn", "p": self.pos.to_wire(), "n": self.neg.to_wire()}

    @classmethod
    def from_wire(cls, wire: dict) -> "PNCounter":
        return cls(GCounter.from_wire(wire["p"]), GCounter.from_wire(wire["n"]))


class LWWRegister:
    """Last-writer-wins register ordered by HLC stamp.

    The stamp includes the writing node, so ties are impossible:
    ``(physical, logical, node)`` is a strict total order and any two
    merge orders agree on the winner.
    """

    __slots__ = ("value", "stamp")

    _ZERO = (-1.0, 0, "")

    def __init__(self, value=None, stamp=None) -> None:
        self.value = value
        self.stamp = tuple(stamp) if stamp else self._ZERO

    def set(self, value, stamp) -> None:
        stamp = tuple(stamp)
        if stamp > self.stamp:
            self.value, self.stamp = value, stamp

    def merge(self, other: "LWWRegister") -> bool:
        if other.stamp > self.stamp:
            self.value, self.stamp = other.value, other.stamp
            return True
        return False

    def to_wire(self) -> dict:
        return {"t": "lww", "v": self.value, "s": list(self.stamp)}

    @classmethod
    def from_wire(cls, wire: dict) -> "LWWRegister":
        reg = cls()
        reg.value = wire["v"]
        s = wire["s"]
        reg.stamp = (float(s[0]), int(s[1]), str(s[2]))
        return reg


class FWWRegister:
    """First-writer-wins register: the EARLIEST stamp wins.

    Merge keeps the minimum stamp — still commutative, associative and
    idempotent — which is what probe election needs: the first shard to
    claim a half-open window owns it, and later claims lose
    deterministically on every shard.
    """

    __slots__ = ("value", "stamp")

    _INF = (float("inf"), 0, "￿")

    def __init__(self, value=None, stamp=None) -> None:
        self.value = value
        self.stamp = tuple(stamp) if stamp else self._INF

    def set(self, value, stamp) -> None:
        stamp = tuple(stamp)
        if stamp < self.stamp:
            self.value, self.stamp = value, stamp

    def merge(self, other: "FWWRegister") -> bool:
        if other.stamp < self.stamp:
            self.value, self.stamp = other.value, other.stamp
            return True
        return False

    def to_wire(self) -> dict:
        if self.stamp == self._INF:
            return {"t": "fww", "v": self.value, "s": None}
        return {"t": "fww", "v": self.value, "s": list(self.stamp)}

    @classmethod
    def from_wire(cls, wire: dict) -> "FWWRegister":
        reg = cls()
        reg.value = wire["v"]
        s = wire["s"]
        if s is not None:
            reg.stamp = (float(s[0]), int(s[1]), str(s[2]))
        return reg


_WIRE_TYPES = {
    "g": GCounter,
    "pn": PNCounter,
    "lww": LWWRegister,
    "fww": FWWRegister,
}


def entry_from_wire(wire: dict):
    return _WIRE_TYPES[wire["t"]].from_wire(wire)


# ---------------------------------------------------------------------------
# Replicated door state

# Entry-key namespaces.  Keys are flat strings "<ns>!<parts...>" so the
# whole state serializes as one sorted map.
NS_REQ = "req"        # request-bucket consumption, key tenant|model
NS_TOK = "tok"        # token-bucket consumption, key tenant|model
NS_LEDGER = "led"     # usage ledger, key tenant|model|field
NS_BREAKER = "brk"    # breaker LWW, key model|addr
NS_OVERLOAD = "ovl"   # overload LWW, key "global"
NS_HOLDINGS = "kvh"   # holdings LWW, key model|addr
NS_PROBE = "prb"      # probe-claim FWW, key model|addr|window

_SEP = "!"
_CTOR = {
    NS_REQ: GCounter,
    NS_TOK: GCounter,
    NS_LEDGER: GCounter,
    NS_BREAKER: LWWRegister,
    NS_OVERLOAD: LWWRegister,
    NS_HOLDINGS: LWWRegister,
    NS_PROBE: FWWRegister,
}


class DoorShardState:
    """The full replicated state of one door shard: a flat map of
    namespaced keys to CRDT entries.  State-based: merging a peer's
    entries (full state or any delta suffix, in any order, any number
    of times) converges to the same bytes."""

    __slots__ = ("entries", "_entry_hashes", "_acc", "_pending", "_digest")

    def __init__(self) -> None:
        self.entries: dict[str, object] = {}
        # Incremental digest: per-entry 128-bit hashes XOR-combined
        # into `_acc`.  XOR is order-independent, so two replicas with
        # the same entry set produce the same digest no matter what
        # order the entries arrived in — and updating it costs O(keys
        # touched), not O(total entries), which is what keeps gossip
        # rounds affordable at million-tenant state sizes.
        self._entry_hashes: dict[str, int] = {}
        self._acc = 0
        # Keys whose hash is stale; None = rebuild everything.
        self._pending: set[str] | None = None
        self._digest: str | None = None

    def bump(self, full_key: str | None = None) -> None:
        """Invalidate the digest for one key — callers that hand out
        entries for in-place mutation (DoorGossipNode._touch) must call
        this.  ``None`` invalidates the whole state (rare, slow)."""
        self._digest = None
        if full_key is None or self._pending is None:
            self._pending = None
        else:
            self._pending.add(full_key)

    def get(self, ns: str, key: str, create: bool = False):
        full = f"{ns}{_SEP}{key}"
        entry = self.entries.get(full)
        if entry is None and create:
            entry = _CTOR[ns]()
            self.entries[full] = entry
        return entry

    def in_namespace(self, ns: str):
        prefix = f"{ns}{_SEP}"
        for full, entry in self.entries.items():
            if full.startswith(prefix):
                yield full[len(prefix):], entry

    def merge_entry(self, full_key: str, wire: dict) -> bool:
        incoming = entry_from_wire(wire)
        mine = self.entries.get(full_key)
        if mine is None:
            ns = full_key.split(_SEP, 1)[0]
            expect = _CTOR.get(ns)
            if expect is not None and not isinstance(incoming, expect):
                raise ValueError(
                    f"wire type mismatch for {full_key!r}: {wire['t']}"
                )
            self.entries[full_key] = incoming
            self.bump(full_key)
            return True
        changed = mine.merge(incoming)
        if changed:
            self.bump(full_key)
        return changed

    def merge(self, other: "DoorShardState") -> bool:
        changed = False
        for full, entry in other.entries.items():
            if self.merge_entry(full, entry.to_wire()):
                changed = True
        return changed

    def to_wire(self) -> dict[str, dict]:
        return {k: self.entries[k].to_wire() for k in sorted(self.entries)}

    def delta_wire(self, keys) -> dict[str, dict]:
        return {
            k: self.entries[k].to_wire()
            for k in sorted(keys)
            if k in self.entries
        }

    @staticmethod
    def _entry_hash(full_key: str, entry) -> int:
        h = hashlib.sha256(
            f"{full_key}={_canon(entry.to_wire())}".encode()
        ).digest()
        return int.from_bytes(h[:16], "big")

    def digest(self) -> str:
        if self._digest is not None:
            return self._digest
        if self._pending is None:
            self._entry_hashes = {
                k: self._entry_hash(k, e) for k, e in self.entries.items()
            }
            acc = 0
            for h in self._entry_hashes.values():
                acc ^= h
            self._acc = acc
        else:
            for k in self._pending:
                entry = self.entries.get(k)
                if entry is None:
                    continue
                old = self._entry_hashes.get(k, 0)
                new = self._entry_hash(k, entry)
                self._acc ^= old ^ new
                self._entry_hashes[k] = new
        self._pending = set()
        # Entry count disambiguates the empty-XOR case and paired
        # duplicates; replicas with identical entry sets always agree.
        self._digest = f"{len(self.entries)}:{self._acc:032x}"
        return self._digest

    def __len__(self) -> int:
        return len(self.entries)


# ---------------------------------------------------------------------------
# Per-shard node handle


class DoorGossipNode:
    """One door shard's handle onto the replicated state.

    The TenantGovernor, breaker plumbing, and prefix router talk to
    this object; the DoorShardSet moves state between nodes.  All
    mutation goes through the CRDT entries so anti-entropy stays
    idempotent.
    """

    def __init__(self, name: str, clock, *, stale_after_s: float = 5.0):
        self.name = name
        self.clock = clock
        self.hlc = HLC(name, clock)
        self.state = DoorShardState()
        # Bumped on every local touch and every absorbed change —
        # readers (Group's holdings view) key caches off it.
        self.version = 0
        self.stale_after_s = float(stale_after_s)
        # Peer name -> last time we successfully exchanged state with
        # it (direct sync only; transitivity is handled by rotation).
        self.last_heard: dict[str, float] = {}
        self.peers: tuple[str, ...] = ()
        # Keys touched locally since the last successful sync with each
        # peer (delta-state sync).  None sentinel = send full state.
        self._dirty: dict[str, set | None] = {}
        # Callable returning the local UsageMeter's cumulative ledger
        # snapshot {(tenant|model|field): int}; folded into NS_LEDGER
        # before each outbound sync.
        self.usage_source = None

    # -- membership -----------------------------------------------------

    def set_peers(self, peers) -> None:
        self.peers = tuple(sorted(p for p in peers if p != self.name))
        for p in self.peers:
            self.last_heard.setdefault(p, float(self.clock()))
            self._dirty.setdefault(p, None)

    def mark_dirty(self, full_key: str) -> None:
        for peer, keys in self._dirty.items():
            if keys is not None:
                keys.add(full_key)

    def _touch(self, ns: str, key: str, create: bool = True):
        entry = self.state.get(ns, key, create=create)
        if entry is not None:
            full = f"{ns}{_SEP}{key}"
            self.mark_dirty(full)
            self.version += 1
            self.state.bump(full)
        return entry

    # -- partition awareness --------------------------------------------

    def stale_peers(self, now: float) -> tuple[str, ...]:
        return tuple(
            p for p in self.peers
            if now - self.last_heard.get(p, 0.0) > self.stale_after_s
        )

    def degraded(self, now: float) -> bool:
        return bool(self.stale_peers(now))

    def split(self, now: float) -> float:
        """Conservative budget split while degraded.

        With F of N-1 peers stale, this shard can only vouch for the
        shards it still hears; it charges each admission
        ``N / reachable`` tokens, i.e. enforces a ``1/reachable`` slice
        of a budget conservatively scaled as if every unreachable shard
        were spending its own full slice.  Any partition of N shards
        therefore admits at most the one global budget (plus the
        staleness-detection lag, which the sims fold into epsilon).
        Fully connected -> split == 1.0 -> byte-identical single-door
        arithmetic.
        """
        total = len(self.peers) + 1
        reachable = total - len(self.stale_peers(now))
        return total / max(1, reachable)

    # -- token-bucket consumption ---------------------------------------

    def consume(self, ns: str, tenant: str, model: str, n: float) -> None:
        self._touch(ns, f"{tenant}|{model}").add(self.name, n)

    def remote_consumed(self, ns: str, tenant: str, model: str) -> float:
        entry = self.state.get(ns, f"{tenant}|{model}")
        return entry.except_of(self.name) if entry is not None else 0.0

    # -- usage ledger ----------------------------------------------------

    def publish_usage(self, snapshot: dict[str, float]) -> None:
        """Fold the local meter's cumulative ledger into the state.
        Components are set (not added) so re-publication is idempotent."""
        for key, value in snapshot.items():
            entry = self.state.get(NS_LEDGER, key, create=True)
            if value > entry.of(self.name):
                entry.set_component(self.name, value)
                full = f"{NS_LEDGER}{_SEP}{key}"
                self.mark_dirty(full)
                self.version += 1
                self.state.bump(full)

    def remote_ledger(self) -> dict[str, float]:
        """Peer-shard ledger totals keyed tenant|model|field."""
        out: dict[str, float] = {}
        for key, entry in self.state.in_namespace(NS_LEDGER):
            v = entry.except_of(self.name)
            if v:
                out[key] = v
        return out

    def ledger_components(self) -> dict[str, dict[str, float]]:
        """Per-peer cumulative ledger snapshots learned via gossip,
        keyed shard -> {tenant|model|field: value}; own component
        excluded.  Feed for ``UsageMeter.merge_shard_snapshot``."""
        out: dict[str, dict[str, float]] = {}
        for key, entry in self.state.in_namespace(NS_LEDGER):
            for node, v in entry.counts.items():
                if node != self.name and v:
                    out.setdefault(node, {})[key] = v
        return out

    def remote_ledger_tokens(self, tenant: str, model: str) -> int:
        total = 0.0
        for fld in ("prompt_tokens", "completion_tokens"):
            entry = self.state.get(NS_LEDGER, f"{tenant}|{model}|{fld}")
            if entry is not None:
                total += entry.except_of(self.name)
        return int(total)

    # -- overload latch --------------------------------------------------

    def set_overload(self, value: bool) -> None:
        self._touch(NS_OVERLOAD, "global").set(bool(value), self.hlc.tick())

    def overload(self, default: bool = False) -> bool:
        entry = self.state.get(NS_OVERLOAD, "global")
        if entry is None or entry.value is None:
            return default
        return bool(entry.value)

    # -- breaker propagation ---------------------------------------------

    def publish_breaker(self, model: str, addr: str, state: str,
                        opened_at: float, error: str = "") -> None:
        key = f"{model}|{addr}"
        stamp = self.hlc.tick()
        self._touch(NS_BREAKER, key).set(
            {"state": state, "opened_at": float(opened_at),
             "error": error, "by": self.name},
            stamp,
        )
        if state == "open":
            # The tripping shard claims the upcoming half-open window
            # eagerly; adopters see the claim and stand down, so
            # exactly one probe lands per window fleet-wide.
            self.claim_probe(model, addr, opened_at, stamp=stamp)

    def breaker_view(self, model: str) -> dict[str, dict]:
        out = {}
        prefix = f"{model}|"
        for key, entry in self.state.in_namespace(NS_BREAKER):
            if key.startswith(prefix) and entry.value is not None:
                out[key[len(prefix):]] = dict(entry.value, stamp=entry.stamp)
        return out

    # -- probe election --------------------------------------------------

    @staticmethod
    def _probe_window(opened_at: float) -> str:
        return f"{float(opened_at):.6f}"

    def claim_probe(self, model: str, addr: str, opened_at: float,
                    *, stamp=None) -> bool:
        """Claim the half-open probe window keyed by the open stamp.
        Returns True when this shard holds the claim (first writer)."""
        key = f"{model}|{addr}|{self._probe_window(opened_at)}"
        entry = self._touch(NS_PROBE, key)
        entry.set(self.name, stamp or self.hlc.tick())
        return entry.value == self.name

    def probe_winner(self, model: str, addr: str,
                     opened_at: float) -> str | None:
        key = f"{model}|{addr}|{self._probe_window(opened_at)}"
        entry = self.state.get(NS_PROBE, key)
        return entry.value if entry is not None else None

    def may_probe(self, model: str, addr: str, opened_at: float) -> bool:
        """Probe-election gate for Group.get_best_addr.  A shard may
        probe when it holds the window claim, or when nobody has
        claimed it yet (it claims on the way in)."""
        winner = self.probe_winner(model, addr, opened_at)
        if winner is None:
            return self.claim_probe(model, addr, opened_at)
        return winner == self.name

    # -- prefix holdings -------------------------------------------------

    def publish_holdings(self, model: str, addr: str,
                         chains, ts: float) -> None:
        self._touch(NS_HOLDINGS, f"{model}|{addr}").set(
            {"chains": sorted(chains), "ts": float(ts)}, self.hlc.tick()
        )

    def holdings(self, model: str) -> tuple[dict[str, frozenset], float | None]:
        """Merged per-endpoint chain holdings for one model, plus the
        newest publication timestamp (None when cold)."""
        out: dict[str, frozenset] = {}
        newest: float | None = None
        prefix = f"{model}|"
        for key, entry in self.state.in_namespace(NS_HOLDINGS):
            if not key.startswith(prefix) or entry.value is None:
                continue
            out[key[len(prefix):]] = frozenset(entry.value["chains"])
            ts = float(entry.value["ts"])
            if newest is None or ts > newest:
                newest = ts
        return out, newest

    # -- sync plumbing ---------------------------------------------------

    def flush_usage(self) -> None:
        """Fold the local meter's ledger into the state if a source is
        wired. Must run BEFORE digest comparison: fresh local usage on
        top of otherwise-identical gossip state would otherwise hit the
        equal-digest skip and never enter the plane."""
        if self.usage_source is not None:
            self.publish_usage(self.usage_source())

    def outbound(self, peer: str) -> dict[str, dict]:
        """Wire delta for a peer: only keys dirtied since the last
        successful sync, or the full state when history is unknown
        (fresh peer, post-crash, post-partition churn)."""
        self.flush_usage()
        dirty = self._dirty.get(peer)
        if dirty is None:
            return self.state.to_wire()
        return self.state.delta_wire(dirty)

    def absorb(self, wire: dict[str, dict], now: float,
               from_peer: str | None = None) -> int:
        """Merge a peer's wire delta; returns entries changed."""
        changed = 0
        for full_key, entry_wire in sorted(wire.items()):
            if self.state.merge_entry(full_key, entry_wire):
                changed += 1
                self.version += 1
                # Adopted entries must keep flowing to *other* peers.
                for peer, keys in self._dirty.items():
                    if peer != from_peer and keys is not None:
                        keys.add(full_key)
            t = entry_wire.get("s")
            if entry_wire.get("t") in ("lww", "fww") and t:
                self.hlc.observe((float(t[0]), int(t[1]), str(t[2])))
        if from_peer is not None:
            self.last_heard[from_peer] = now
            self._dirty[from_peer] = set()
        return changed

    def forget_peer_history(self, peer: str) -> None:
        self._dirty[peer] = None


class DoorShardSet:
    """Membership and anti-entropy for N in-process door shards.

    One gossip round (``step``): every node, in sorted name order,
    push-pulls with one peer chosen by deterministic rotation
    (node i's r-th round partner cycles through the other N-1 nodes).
    Digests are exchanged first; equal digests skip the transfer.  A
    ``partition`` seam severs links between groups for chaos drills;
    ``heal`` restores them, and full-state resync on the first
    post-heal round guarantees convergence within a bounded number of
    rounds (<= N-1 with rotation).  Deterministic: the only inputs are
    the injected clock and the seed.
    """

    def __init__(self, names, clock, *, seed: int = 0,
                 interval_s: float = 1.0, stale_after_s: float = 5.0,
                 metrics=None):
        names = sorted(names)
        if len(set(names)) != len(names):
            raise ValueError("duplicate shard names")
        self.clock = clock
        self.seed = int(seed)
        self.interval_s = float(interval_s)
        self.stale_after_s = float(stale_after_s)
        self.metrics = metrics
        self.nodes: dict[str, DoorGossipNode] = {
            n: DoorGossipNode(n, clock, stale_after_s=stale_after_s)
            for n in names
        }
        for node in self.nodes.values():
            node.set_peers(names)
        self._round = 0
        self._last_round_t: float | None = None
        # Severed links: frozenset({a, b}) pairs that cannot sync.
        self._cut: set[frozenset] = set()

    # -- membership / chaos seams ---------------------------------------

    def node(self, name: str) -> DoorGossipNode:
        return self.nodes[name]

    def names(self) -> tuple[str, ...]:
        return tuple(sorted(self.nodes))

    def partition(self, groups) -> None:
        """Sever every link that crosses group boundaries."""
        lookup = {}
        for gi, group in enumerate(groups):
            for name in group:
                lookup[name] = gi
        cut = set()
        names = self.names()
        for i, a in enumerate(names):
            for b in names[i + 1:]:
                if lookup.get(a, -1) != lookup.get(b, -2):
                    cut.add(frozenset((a, b)))
        self._cut = cut

    def heal(self) -> None:
        """Restore all links; force full-state resync so convergence
        after a partition is bounded by the rotation period."""
        for pair in self._cut:
            a, b = sorted(pair)
            if a in self.nodes:
                self.nodes[a].forget_peer_history(b)
            if b in self.nodes:
                self.nodes[b].forget_peer_history(a)
        self._cut = set()

    def partitioned(self) -> bool:
        return bool(self._cut)

    def crash(self, name: str) -> DoorGossipNode:
        """Replace a shard's node with an empty-state one (process
        restart).  Its pre-crash counter components live on in peer
        replicas and flow back on the next full-state syncs — the CRDT
        reconstruction path the game day asserts."""
        if name not in self.nodes:
            raise KeyError(name)
        fresh = DoorGossipNode(
            name, self.clock, stale_after_s=self.stale_after_s
        )
        self.nodes[name] = fresh
        fresh.set_peers(self.names())
        for other in self.nodes.values():
            if other is not fresh:
                other.forget_peer_history(name)
        return fresh

    def link_up(self, a: str, b: str) -> bool:
        return frozenset((a, b)) not in self._cut

    # -- anti-entropy ----------------------------------------------------

    def _partner(self, idx: int, rnd: int, n: int) -> int:
        # Deterministic rotation seeded by construction seed: node i's
        # partner cycles through the other n-1 nodes across rounds.
        return (idx + 1 + (rnd + self.seed) % (n - 1)) % n

    def step(self, now: float | None = None) -> int:
        """Run one gossip round; returns total entries merged."""
        now = float(self.clock()) if now is None else float(now)
        names = self.names()
        n = len(names)
        merged = 0
        if n >= 2:
            for i, name in enumerate(names):
                peer = names[self._partner(i, self._round, n)]
                merged += self._sync_pair(name, peer, now)
        self._round += 1
        self._last_round_t = now
        m = self.metrics
        if m is not None:
            m.gossip_rounds.inc()
            for name in names:
                node = self.nodes[name]
                m.gossip_state_entries.set(
                    float(len(node.state)), shard=name
                )
                m.gossip_degraded.set(
                    1.0 if node.degraded(now) else 0.0, shard=name
                )
                for peer in node.peers:
                    m.gossip_peer_staleness.set(
                        max(0.0, now - node.last_heard.get(peer, 0.0)),
                        shard=name, peer=peer,
                    )
        return merged

    def _sync_pair(self, a_name: str, b_name: str, now: float) -> int:
        m = self.metrics
        if not self.link_up(a_name, b_name):
            if m is not None:
                m.gossip_syncs.inc(
                    shard=a_name, result="unreachable"
                )
            return 0
        a, b = self.nodes[a_name], self.nodes[b_name]
        a.flush_usage()
        b.flush_usage()
        # Push-pull digest exchange: equal digests -> nothing to ship.
        if a.state.digest() == b.state.digest():
            a.last_heard[b_name] = now
            b.last_heard[a_name] = now
            a._dirty[b_name] = set()
            b._dirty[a_name] = set()
            if m is not None:
                m.gossip_syncs.inc(shard=a_name, result="skip")
            return 0
        out_a = a.outbound(b_name)
        out_b = b.outbound(a_name)
        changed = b.absorb(out_a, now, from_peer=a_name)
        changed += a.absorb(out_b, now, from_peer=b_name)
        if m is not None:
            m.gossip_syncs.inc(shard=a_name, result="ok")
            m.gossip_entries_sent.inc(len(out_a) + len(out_b))
            if changed:
                m.gossip_merges.inc(changed)
        return changed

    def maybe_step(self, now: float) -> bool:
        """Lazy driver: run a round when the interval has elapsed.
        Admissions call this, so no background thread is needed and
        FakeClock sims stay deterministic."""
        if (self._last_round_t is not None
                and now - self._last_round_t < self.interval_s):
            return False
        self.step(now)
        return True

    def run_rounds(self, k: int, now: float | None = None) -> None:
        for _ in range(k):
            self.step(now)

    # -- convergence -----------------------------------------------------

    def digests(self) -> dict[str, str]:
        return {n: self.nodes[n].state.digest() for n in self.names()}

    def converged(self) -> bool:
        return len(set(self.digests().values())) <= 1
