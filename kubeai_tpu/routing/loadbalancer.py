"""Endpoint-group load balancer (reference: internal/loadbalancer).

A Pod-watching component maintaining per-model endpoint groups from Ready
Pods (+ `model-pod-ip`/`model-pod-port` annotation overrides and adapter
labels — reference: load_balancer.go:53-140). Strategies:

  LeastLoad   — endpoint with fewest in-flight requests
                (reference: balance_least_load.go:3-23)
  PrefixHash  — CHWBL over the request prefix (see chwbl.py)

`await_best_address` BLOCKS until an endpoint exists — the scale-from-zero
hold (reference: group.go:53-94 broadcast channel; here a Condition).
Returns a completion callback that decrements in-flight counters.
"""

from __future__ import annotations

import threading
from typing import Callable

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import (
    LB_STRATEGY_PREFIX_HASH,
)
from kubeai_tpu.operator import k8sutils
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.routing.chwbl import make_ring


class LoadBalancerTimeout(TimeoutError):
    pass


# Operator replicas self-identify with this label; the LB collects their
# metrics addresses so the leader's autoscaler can scrape EVERY replica
# (reference: load_balancer.go:64-83 tracks kubeai self pods the same way).
SELF_POD_LABEL = "app.kubernetes.io/name"
SELF_POD_VALUE = "kubeai"
SELF_METRICS_ADDR_ANNOTATION = "kubeai.org/metrics-addr"


class _Endpoint:
    __slots__ = ("address", "adapters", "in_flight")

    def __init__(self, address: str, adapters: set[str]):
        self.address = address
        self.adapters = adapters
        self.in_flight = 0


class Group:
    """Per-model endpoint set with in-flight accounting and a blocking wait
    (reference: internal/loadbalancer/group.go)."""

    def __init__(
        self,
        load_factor: float = 1.25,
        replication: int = 256,
        metrics: Metrics = DEFAULT_METRICS,
    ):
        self._cond = threading.Condition()
        self._endpoints: dict[str, _Endpoint] = {}
        self._chwbl = make_ring(
            load_factor=load_factor, replication=replication, metrics=metrics
        )
        self.total_in_flight = 0

    def reconcile_endpoints(self, observed: dict[str, set[str]]) -> None:
        """observed: address -> adapter names. Broadcasts on any addition
        so blocked requests wake (reference: group.go:108-137)."""
        with self._cond:
            added = False
            for addr, adapters in observed.items():
                ep = self._endpoints.get(addr)
                if ep is None:
                    self._endpoints[addr] = _Endpoint(addr, set(adapters))
                    self._chwbl.add(addr)
                    added = True
                else:
                    ep.adapters = set(adapters)
            for addr in list(self._endpoints):
                if addr not in observed:
                    del self._endpoints[addr]
                    self._chwbl.remove(addr)
            if added:
                self._cond.notify_all()

    def addresses(self) -> list[str]:
        with self._cond:
            return list(self._endpoints)

    def get_best_addr(
        self,
        strategy: str,
        adapter: str,
        prefix: str,
        timeout: float,
    ) -> tuple[str, Callable[[], None]]:
        """Block until a suitable endpoint exists; account the request."""
        with self._cond:
            deadline_ok = self._cond.wait_for(
                lambda: bool(self._candidates(adapter)), timeout=timeout
            )
            if not deadline_ok:
                raise LoadBalancerTimeout(
                    f"no endpoint became ready within {timeout}s"
                )
            addr = self._pick(strategy, adapter, prefix)
            ep = self._endpoints[addr]
            ep.in_flight += 1
            self.total_in_flight += 1

        done_called = threading.Event()

        def done(ep=ep) -> None:
            if done_called.is_set():
                return
            done_called.set()
            with self._cond:
                # Decrement the endpoint OBJECT acquired above, not a lookup:
                # if the endpoint was removed and re-added mid-request, a
                # lookup would push the fresh endpoint's counter negative.
                ep.in_flight -= 1
                self.total_in_flight -= 1

        return addr, done

    def _candidates(self, adapter: str) -> list[_Endpoint]:
        eps = list(self._endpoints.values())
        if adapter:
            with_adapter = [e for e in eps if adapter in e.adapters]
            return with_adapter
        return eps

    def _pick(self, strategy: str, adapter: str, prefix: str) -> str:
        if strategy == LB_STRATEGY_PREFIX_HASH and prefix:
            loads = {a: e.in_flight for a, e in self._endpoints.items()}
            adapter_eps = (
                {e.address for e in self._candidates(adapter)} if adapter else None
            )
            addr = self._chwbl.get(prefix, loads, adapter_eps)
            if addr is not None:
                return addr
        # LeastLoad (and PrefixHash fallback when no prefix/ring).
        candidates = self._candidates(adapter)
        best = min(candidates, key=lambda e: e.in_flight)
        return best.address


class LoadBalancer:
    """Watches Pods in the store and maintains groups + self IPs
    (reference: internal/loadbalancer/load_balancer.go)."""

    def __init__(
        self,
        store: KubeStore,
        default_timeout: float = 600.0,
        metrics: Metrics = DEFAULT_METRICS,
    ):
        self.store = store
        self.default_timeout = default_timeout
        self.metrics = metrics
        self._lock = threading.Lock()
        self._groups: dict[str, Group] = {}
        self._self_ips: list[str] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._events = store.watch(("Pod",))

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        self.sync_all()
        self._thread = threading.Thread(target=self._watch_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._events.put(None)
        if self._thread:
            self._thread.join(timeout=5)

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            item = self._events.get()
            if item is None:
                return
            _event, pod = item
            if _event == "RELIST":
                # Watch gap (410 Gone relist): deletions in the gap left
                # no event, so rebuild every group from the snapshot.
                self.sync_all()
                continue
            model = k8sutils.get_label(pod, md.POD_MODEL_LABEL)
            if model:
                self.sync_model(model, pod["metadata"].get("namespace", "default"))
            elif k8sutils.get_label(pod, SELF_POD_LABEL) == SELF_POD_VALUE:
                self._sync_self_ips()

    # -- endpoint discovery (reference: load_balancer.go:90-140) --------------

    def sync_all(self) -> None:
        models: set[tuple[str, str]] = set()
        for pod in self.store.list("Pod"):
            model = k8sutils.get_label(pod, md.POD_MODEL_LABEL)
            if model:
                models.add((model, pod["metadata"].get("namespace", "default")))
        for model, ns in models:
            self.sync_model(model, ns)
        self._sync_self_ips()

    def _sync_self_ips(self) -> None:
        """Collect metrics addresses of ALL operator replicas from their
        self pods — the autoscaler scrapes every one of these each tick."""
        addrs = []
        for pod in self.store.list(
            "Pod", label_selector={SELF_POD_LABEL: SELF_POD_VALUE}
        ):
            if not k8sutils.pod_is_ready(pod):
                continue
            addr = k8sutils.get_annotation(pod, SELF_METRICS_ADDR_ANNOTATION)
            if not addr:
                ip = (pod.get("status") or {}).get("podIP")
                port = k8sutils.get_annotation(pod, md.MODEL_POD_PORT_ANNOTATION) or "8080"
                addr = f"{ip}:{port}" if ip else None
            if addr:
                addrs.append(addr)
        with self._lock:
            self._self_ips = addrs

    def sync_model(self, model: str, namespace: str = "default") -> None:
        observed: dict[str, set[str]] = {}
        for pod in self.store.list(
            "Pod", namespace, {md.POD_MODEL_LABEL: model}
        ):
            if not k8sutils.pod_is_ready(pod):
                continue
            # Multi-host worker Pods participate in the mesh but do not
            # serve HTTP; only host-0 is an endpoint.
            if (
                k8sutils.get_annotation(pod, md.MODEL_POD_SERVING_ANNOTATION)
                == "false"
            ):
                continue
            ip = k8sutils.get_annotation(pod, md.MODEL_POD_IP_ANNOTATION) or (
                (pod.get("status") or {}).get("podIP")
            )
            if not ip:
                continue
            port = (
                k8sutils.get_annotation(pod, md.MODEL_POD_PORT_ANNOTATION)
                or "8000"
            )
            adapters = set()
            prefix = md.ADAPTER_LABEL_DOMAIN + "/"
            for k in (pod["metadata"].get("labels") or {}):
                if k.startswith(prefix):
                    adapters.add(k[len(prefix):])
            observed[f"{ip}:{port}"] = adapters
        self.group(model).reconcile_endpoints(observed)

    def group(self, model: str) -> Group:
        with self._lock:
            if model not in self._groups:
                self._groups[model] = Group(metrics=self.metrics)
            return self._groups[model]

    # -- API (reference: load_balancer.go:182-204) -----------------------------

    def get_self_ips(self) -> list[str]:
        with self._lock:
            return list(self._self_ips)

    def set_self_ips(self, ips: list[str]) -> None:
        with self._lock:
            self._self_ips = list(ips)

    def await_best_address(
        self,
        model: str,
        adapter: str = "",
        prefix: str = "",
        strategy: str = "LeastLoad",
        timeout: float | None = None,
    ) -> tuple[str, Callable[[], None]]:
        return self.group(model).get_best_addr(
            strategy, adapter, prefix,
            timeout=self.default_timeout if timeout is None else timeout,
        )
