"""Endpoint-group load balancer (reference: internal/loadbalancer).

A Pod-watching component maintaining per-model endpoint groups from Ready
Pods (+ `model-pod-ip`/`model-pod-port` annotation overrides and adapter
labels — reference: load_balancer.go:53-140). Strategies:

  LeastLoad   — endpoint with fewest in-flight requests
                (reference: balance_least_load.go:3-23)
  PrefixHash  — CHWBL over the request prefix (see chwbl.py)

`await_best_address` BLOCKS until an endpoint exists — the scale-from-zero
hold (reference: group.go:53-94 broadcast channel; here a Condition).
Returns a completion callback that decrements in-flight counters.

Resilience (no reference analog — the reference trusts readiness probes):
each endpoint carries a passive-health circuit breaker (routing/health.py)
fed by the proxy's attempt outcomes. Open circuits are excluded from the
pick; when every endpoint is open the pick FAILS FAST with
`NoHealthyEndpoints` (rather than hanging to the scale-from-zero timeout)
carrying last-seen error context for the 503 body. Retries pass an
`exclude` set so an attempt never re-picks the exact address that just
failed — unless that would leave nowhere to go (single-replica groups
still retry in place rather than fail).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

from kubeai_tpu.crd import metadata as md
from kubeai_tpu.crd.model import (
    LB_STRATEGY_PREFIX_HASH,
)
from kubeai_tpu.operator import k8sutils, slicegroup
from kubeai_tpu.operator.k8s.store import KubeStore
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.metrics import flightrecorder
from kubeai_tpu.routing.chwbl import make_ring
from kubeai_tpu.routing.health import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    BreakerPolicy,
    EndpointHealth,
)


class LoadBalancerTimeout(TimeoutError):
    pass


class NoHealthyEndpoints(LoadBalancerTimeout):
    """Endpoints exist but every circuit is open (within backoff): fail
    fast instead of blocking — the caller answers 503 immediately with
    the per-endpoint last-seen errors so clients see WHY."""

    def __init__(self, model: str, last_errors: dict[str, str]):
        self.model = model
        self.last_errors = dict(last_errors)
        detail = "; ".join(
            f"{addr}: {err or 'unknown failure'}"
            for addr, err in sorted(last_errors.items())
        )
        super().__init__(
            f"all endpoints have open circuits ({detail})"
            if detail else "all endpoints have open circuits"
        )


# Numeric encoding of breaker state for the /metrics gauge.
_STATE_VALUE = {STATE_CLOSED: 0, STATE_HALF_OPEN: 1, STATE_OPEN: 2}


# Operator replicas self-identify with this label; the LB collects their
# metrics addresses so the leader's autoscaler can scrape EVERY replica
# (reference: load_balancer.go:64-83 tracks kubeai self pods the same way).
SELF_POD_LABEL = "app.kubernetes.io/name"
SELF_POD_VALUE = "kubeai"
SELF_METRICS_ADDR_ANNOTATION = "kubeai.org/metrics-addr"


class _Endpoint:
    __slots__ = ("address", "adapters", "in_flight", "health", "role",
                 "version")

    def __init__(
        self,
        address: str,
        adapters: set[str],
        policy: BreakerPolicy | None = None,
        clock=time.monotonic,
        role: str = md.ROLE_UNIFIED,
        version: str = "",
    ):
        self.address = address
        self.adapters = adapters
        self.in_flight = 0
        self.health = EndpointHealth(policy, clock=clock)
        # Disaggregated serving role from the pod's model-role label:
        # "prefill" / "decode", or "unified" (no label). Role-filtered
        # picks drive the proxy's two-hop prefill→decode flow.
        self.role = role
        # Pod-hash of the backing pod's rendered spec — the serving
        # VERSION. Always stamped (rollout controller or not) so version
        # split is observable, and canary weighting keys on it.
        self.version = version


class Group:
    """Per-model endpoint set with in-flight accounting, passive-health
    circuit breaking, and a blocking wait
    (reference: internal/loadbalancer/group.go)."""

    def __init__(
        self,
        load_factor: float = 1.25,
        replication: int = 256,
        metrics: Metrics = DEFAULT_METRICS,
        model: str = "",
        breaker: BreakerPolicy | None = None,
        clock=time.monotonic,
    ):
        self._cond = threading.Condition()  # local-state: process-local lock, not replicated data
        self._endpoints: dict[str, _Endpoint] = {}  # local-state: rebuilt from the shared KubeStore watch; membership is store-derived
        self._chwbl = make_ring(
            load_factor=load_factor, replication=replication, metrics=metrics
        )
        self.load_factor = load_factor
        self.total_in_flight = 0  # local-state: this shard's own in-flight accounting
        self.model = model
        self.metrics = metrics
        self.breaker_policy = breaker or BreakerPolicy()
        self._clock = clock
        # Cluster KV-sharing: advertised prefix holdings per endpoint
        # (addr -> set of held chain hashes, hex). CRDT-backed when the
        # door is sharded: reads come from the gossiped LWW holdings
        # map (zero aggregator round-trips on the hot path); without
        # gossip the fleet aggregator pushes this map after each
        # collect. Advisory and freshness-gated either way — past the
        # TTL the longest-held-prefix pick disables itself and routing
        # degrades byte-identically to classic CHWBL.
        self._kv_holdings: dict[str, frozenset[str]] = {}
        self._kv_holdings_ts: float | None = None
        self.kv_holdings_ttl_s = 15.0  # local-state: freshness policy constant, not shared state
        # The door shard's gossip node (routing/gossip.DoorGossipNode)
        # when sharded: holdings reads, breaker publication/adoption,
        # and half-open probe election flow through it. None -> classic
        # single-door behavior, byte-identical.
        self.gossip = None  # local-state: wiring seam set by the manager/sims, not request state
        self._gossip_holdings_cache = None  # local-state: per-version cache of the gossiped holdings view
        # LWW stamps already applied from the gossiped breaker map, so
        # remote sync is idempotent per publication.
        self._breaker_stamps: dict[str, tuple] = {}  # local-state: applied-stamp cursor over the CRDT breaker map
        self._adopting = False  # local-state: reentrancy guard while applying remote breaker verdicts
        # Endpoints removed by reconcile while requests were still in
        # flight: their done() callbacks must keep draining the group
        # totals, and the snapshot must show them until they empty.
        self._retired: dict[int, _Endpoint] = {}  # local-state: in-flight accounting for reconciled-away endpoints
        # Flight recorder + last state it saw per endpoint, so only
        # genuine breaker TRANSITIONS land in the ring (the sync runs
        # on every done(), transitions are rare).
        self.recorder = None  # local-state: wiring seam set by the manager, not request state
        self._breaker_states: dict[str, str] = {}  # local-state: last-seen states for transition detection
        # Progressive rollouts: while a canary version is declared, its
        # endpoints receive at most `share` of routed requests — replica
        # count alone under-enforces the cap when the canary is idle and
        # least-load would pile onto it. Rolling counters reset whenever
        # the declaration changes; share 0.0 (rollback) stops routing to
        # the condemned version instantly, ahead of pod teardown.
        self._canary_version: str | None = None  # local-state: declared by the rollout controller
        self._canary_share = 0.0  # local-state: canary traffic ceiling in [0,1]
        self._canary_routed = 0  # local-state: requests routed to the canary version since declaration
        self._canary_total = 0  # local-state: all requests routed since declaration

    def set_breaker_policy(self, policy: BreakerPolicy) -> None:
        with self._cond:
            if policy == self.breaker_policy:
                return
            self.breaker_policy = policy
            for ep in self._endpoints.values():
                ep.health.set_policy(policy)

    def reconcile_endpoints(
        self,
        observed: dict[str, set[str]],
        roles: dict[str, str] | None = None,
        versions: dict[str, str] | None = None,
    ) -> None:
        """observed: address -> adapter names; roles: address -> serving
        role (absent/"" = unified); versions: address -> pod-hash of the
        backing pod. Broadcasts on ANY change: additions wake the
        scale-from-zero hold (reference: group.go:108-137), removals and
        role/version flips wake waiters whose candidate/exclude
        predicate just changed so they re-evaluate instead of sleeping on
        a stale view."""
        roles = roles or {}
        versions = versions or {}
        with self._cond:
            changed = False
            for addr, adapters in observed.items():
                role = roles.get(addr) or md.ROLE_UNIFIED
                version = versions.get(addr) or ""
                ep = self._endpoints.get(addr)
                if ep is None:
                    self._endpoints[addr] = _Endpoint(
                        addr, set(adapters),
                        policy=self.breaker_policy, clock=self._clock,
                        role=role, version=version,
                    )
                    self._chwbl.add(addr)
                    changed = True
                else:
                    ep.adapters = set(adapters)
                    if ep.role != role:
                        ep.role = role
                        changed = True
                    if ep.version != version:
                        ep.version = version
                        changed = True
            for addr in list(self._endpoints):
                if addr not in observed:
                    ep = self._endpoints.pop(addr)
                    self._chwbl.remove(addr)
                    changed = True
                    self._drop_breaker_metrics(addr)
                    if ep.in_flight > 0:
                        # Requests are still bound to this endpoint
                        # object; park it so done() bookkeeping stays
                        # visible until the last one drains (the leak:
                        # an ejected endpoint silently vanishing while
                        # its active count never reached zero in any
                        # snapshot).
                        self._retired[id(ep)] = ep
            if changed:
                self._cond.notify_all()

    def set_kv_holdings(self, holdings: dict[str, Iterable[str]]) -> None:
        """Replace the advertised prefix-holdings map (fleet-aggregator
        push after each collect; stale endpoints simply don't appear).
        When this door is sharded, the map is additionally published
        into the gossiped state plane so every peer shard routes from
        the same view without its own aggregator sweep."""
        with self._cond:
            self._kv_holdings = {
                a: frozenset(h) for a, h in holdings.items() if h
            }
            self._kv_holdings_ts = self._clock()
            if self.gossip is not None:
                ts = self._kv_holdings_ts
                for addr, held in self._kv_holdings.items():
                    self.gossip.publish_holdings(
                        self.model, addr, held, ts
                    )

    def _holdings_view(self) -> tuple[dict[str, frozenset], float | None]:
        """The (holdings, newest-ts) pair the prefix pick routes from:
        the gossiped LWW map when sharded (cached per state version —
        the hot path never rebuilds it unless gossip moved), else the
        aggregator-pushed local map."""
        g = self.gossip
        if g is None:
            return self._kv_holdings, self._kv_holdings_ts
        cache = self._gossip_holdings_cache
        if cache is not None and cache[0] == g.version:
            return cache[1], cache[2]
        held, ts = g.holdings(self.model)
        self._gossip_holdings_cache = (g.version, held, ts)
        return held, ts

    def _holdings_fresh(self) -> bool:
        _, ts = self._holdings_view()
        return (
            ts is not None
            and self._clock() - ts <= self.kv_holdings_ttl_s
        )

    def _chain_depth(self, chain: list[str], held: frozenset[str]) -> int:
        depth = 0
        for h in chain:
            if h not in held:
                break
            depth += 1
        return depth

    def kv_holder(
        self, chain: list[str], exclude: Iterable[str] = ()
    ) -> tuple[str | None, int]:
        """Deepest advertised CLOSED-CIRCUIT holder of the chain — the
        proxy's X-KV-Source hint for the serving replica's peer fetch.
        Open or half-open endpoints are never suggested (an open-circuit
        peer must receive no fetch traffic; half-open gets exactly its
        one probe request, not a side-channel transfer). Returns
        (address, depth) or (None, 0)."""
        excluded = frozenset(exclude or ())
        with self._cond:
            if not self._holdings_fresh():
                return None, 0
            held_map, _ = self._holdings_view()
            best, best_depth = None, 0
            for addr in sorted(held_map):
                if addr in excluded:
                    continue
                ep = self._endpoints.get(addr)
                if ep is None or ep.health.state != STATE_CLOSED:
                    continue
                depth = self._chain_depth(chain, held_map[addr])
                if depth > best_depth:
                    best, best_depth = addr, depth
            return best, best_depth

    def set_canary(self, version: str | None, share: float = 0.0) -> None:
        """Declare (or clear, with None) the canary version and its
        traffic ceiling. Idempotent when unchanged so the rollout
        controller can call it every tick; a change resets the rolling
        counters — the share is enforced over the NEW declaration's
        traffic, not history."""
        with self._cond:
            version = version or None
            share = max(0.0, min(1.0, share))
            if version == self._canary_version and share == self._canary_share:
                return
            self._canary_version = version
            self._canary_share = share
            self._canary_routed = 0
            self._canary_total = 0
            self._cond.notify_all()

    def _canary_filter(self, avail: list[_Endpoint]) -> list[_Endpoint]:
        """Drop canary-version endpoints from the pick when routing one
        more request to them would push their traffic share past the
        ceiling. When ONLY canary endpoints are available the cap yields
        — serving beats starving (the zero-share rollback case never
        hits this: the old version's pods are kept by the pin)."""
        v = self._canary_version
        if v is None:
            return avail
        stable = [e for e in avail if e.version != v]
        if not stable:
            return avail
        if self._canary_routed + 1 > self._canary_share * (self._canary_total + 1):
            return stable
        return avail

    def addresses(self, role: str = "") -> list[str]:
        with self._cond:
            if not role:
                return list(self._endpoints)
            return [
                a for a, e in self._endpoints.items() if e.role == role
            ]

    def has_role(self, role: str) -> bool:
        """True when any endpoint carries the role — the proxy's cheap
        "does a disaggregated pool exist" probe before committing to the
        two-hop flow."""
        with self._cond:
            return any(e.role == role for e in self._endpoints.values())

    def get_best_addr(
        self,
        strategy: str,
        adapter: str,
        prefix: str,
        timeout: float,
        exclude: Iterable[str] | None = None,
        role: str = "",
        chain: list[str] | None = None,
    ) -> tuple[str, Callable[..., None]]:
        """Block until a suitable endpoint exists; account the request.

        `exclude` is the retry path's do-not-repick set: excluded
        addresses are avoided while any other available endpoint exists,
        and ignored otherwise (a single-replica group must still retry in
        place rather than starve). `role` restricts the candidate set to
        one serving role ("" = any). `chain` is the request's page-hash
        chain (hex) for models on the KV-sharing tier: when the fleet
        holdings map is fresh, the pick prefers the load-bounded endpoint
        holding the deepest matching chain and falls back to classic
        CHWBL otherwise. Raises `NoHealthyEndpoints` without waiting when
        endpoints exist but every circuit is open."""
        excluded = frozenset(exclude or ())
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                eps = self._candidates(adapter, role)
                if eps:
                    avail = [
                        e for e in eps
                        if e.health.available(e.in_flight)
                        and self._may_probe(e)
                    ]
                    if not avail:
                        # Fail fast: blocking would just burn the whole
                        # scale-from-zero budget against dead replicas.
                        if self.recorder is not None:
                            self.recorder.record(
                                flightrecorder.LB_NO_ENDPOINTS, "lb",
                                target=self.model,
                                endpoints=len(eps),
                            )
                            self.recorder.trigger(
                                flightrecorder.TRIGGER_ALL_CIRCUITS_OPEN,
                                detail=(
                                    f"model {self.model}: all "
                                    f"{len(eps)} circuits open"
                                ),
                            )
                        raise NoHealthyEndpoints(
                            self.model,
                            {
                                e.address: e.health.last_error
                                for e in eps
                                if e.health.state != STATE_CLOSED
                            },
                        )
                    avail = self._canary_filter(avail)
                    picks = [
                        e for e in avail if e.address not in excluded
                    ] or avail
                    addr = self._pick(
                        strategy, adapter, prefix,
                        {e.address for e in picks},
                        role,
                        chain,
                    )
                    ep = self._endpoints[addr]
                    # An open circuit past its backoff transitions to
                    # half-open here; in_flight == 0 was required by
                    # available(), so this request IS the single probe.
                    ep.health.on_pick()
                    self._sync_breaker_metrics(ep)
                    ep.in_flight += 1
                    self.total_in_flight += 1
                    if self._canary_version is not None:
                        self._canary_total += 1
                        if ep.version == self._canary_version:
                            self._canary_routed += 1
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise LoadBalancerTimeout(
                        f"no endpoint became ready within {timeout}s"
                    )
                self._cond.wait(timeout=remaining)

        done_called = threading.Event()

        def done(outcome: str | None = None, error: str = "", ep=ep) -> None:
            """Release the in-flight slot. `outcome` (health.py outcome
            vocabulary) additionally feeds the endpoint's breaker; a bare
            done() only releases accounting (legacy callers, cancelled
            work)."""
            if done_called.is_set():
                return
            done_called.set()
            with self._cond:
                # Decrement the endpoint OBJECT acquired above, not a
                # lookup: if the endpoint was removed and re-added
                # mid-request, a lookup would push the fresh endpoint's
                # counter negative.
                ep.in_flight -= 1
                self.total_in_flight -= 1
                if ep.in_flight <= 0 and id(ep) in self._retired:
                    del self._retired[id(ep)]
                changed = False
                if outcome is not None:
                    changed = ep.health.record(outcome, error)
                    if self._endpoints.get(ep.address) is ep:
                        self._sync_breaker_metrics(ep)
                # A freed slot can admit the half-open probe; a state
                # change alters the candidate set — either way waiters
                # must re-evaluate.
                if changed or ep.health.state != STATE_CLOSED:
                    self._cond.notify_all()

        return addr, done

    def report_outcome(self, addr: str, outcome: str, error: str = "") -> None:
        """Fold an outcome in for an attempt that is no longer holding an
        in-flight slot (e.g. a mid-stream death noticed after done()
        already ran). Unknown addresses are ignored — the endpoint may
        have been reconciled away."""
        with self._cond:
            ep = self._endpoints.get(addr)
            if ep is None:
                return
            if ep.health.record(outcome, error):
                self._sync_breaker_metrics(ep)
                self._cond.notify_all()

    def _may_probe(self, e: _Endpoint) -> bool:
        """Half-open probe election across door shards: a non-closed
        endpoint is only routable (i.e. probe-able) when this shard
        holds the gossip claim for the half-open window keyed by the
        open stamp. Unclaimed windows are claimed on the way in, so a
        solo shard (or a gossip-less build) behaves exactly as before."""
        if self.gossip is None or e.health.state == STATE_CLOSED:
            return True
        return self.gossip.may_probe(
            self.model, e.address, e.health.opened_at
        )

    def sync_remote_breakers(self) -> int:
        """Apply peer door shards' breaker verdicts from the gossiped
        LWW map: adopt opens (stop sending before this shard pays the
        failure tax itself) and adopt closes stamped at-or-after our
        open (the elected prober's probe succeeded). Idempotent per
        publication — applied stamps are remembered. Returns the number
        of local state changes."""
        g = self.gossip
        if g is None:
            return 0
        changed = 0
        with self._cond:
            self._adopting = True
            try:
                for addr, entry in sorted(
                    g.breaker_view(self.model).items()
                ):
                    ep = self._endpoints.get(addr)
                    if ep is None:
                        continue
                    stamp = entry.get("stamp")
                    if self._breaker_stamps.get(addr) == stamp:
                        continue
                    self._breaker_stamps[addr] = stamp
                    if entry.get("by") == g.name:
                        continue  # our own publication, round-tripped
                    state = entry.get("state")
                    opened_at = float(entry.get("opened_at", 0.0))
                    if state == "open" and ep.health.state == STATE_CLOSED:
                        if ep.health.adopt_open(
                            opened_at, error=entry.get("error", "")
                        ):
                            changed += 1
                            self.metrics.gossip_breaker_adoptions.inc(
                                model=self.model
                            )
                            self._sync_breaker_metrics(ep)
                    elif (
                        state == "closed"
                        and ep.health.state != STATE_CLOSED
                        and opened_at >= ep.health.opened_at
                    ):
                        if ep.health.remote_close():
                            changed += 1
                            self._sync_breaker_metrics(ep)
            finally:
                self._adopting = False
            if changed:
                self._cond.notify_all()
        return changed

    def _sync_breaker_metrics(self, ep: _Endpoint) -> None:
        self.metrics.lb_circuit_state.set(
            _STATE_VALUE[ep.health.state],
            model=self.model, endpoint=ep.address,
        )
        prev_state = self._breaker_states.get(ep.address, STATE_CLOSED)
        if (
            self.gossip is not None
            and not self._adopting
            and ep.health.state != prev_state
        ):
            # Publish genuine local transitions into the state plane.
            # HALF_OPEN is deliberately not published: peers keep the
            # endpoint open while the elected prober works, and learn
            # the VERDICT (closed, or a re-open with a fresh stamp).
            if ep.health.state == STATE_OPEN:
                self.gossip.publish_breaker(
                    self.model, ep.address, "open",
                    ep.health.opened_at, ep.health.last_error,
                )
            elif ep.health.state == STATE_CLOSED:
                self.gossip.publish_breaker(
                    self.model, ep.address, "closed", ep.health.opened_at
                )
        if self.recorder is not None:
            prev = self._breaker_states.get(ep.address, STATE_CLOSED)
            if ep.health.state != prev:
                self.recorder.record(
                    flightrecorder.BREAKER, "lb", target=ep.address,
                    model=self.model, from_state=prev,
                    to_state=ep.health.state,
                    last_error=ep.health.last_error,
                )
        self._breaker_states[ep.address] = ep.health.state
        ejections = self.metrics.lb_circuit_ejections
        recorded = ejections.get(model=self.model, endpoint=ep.address)
        if ep.health.ejections > recorded:
            ejections.inc(
                ep.health.ejections - recorded,
                model=self.model, endpoint=ep.address,
            )

    def _drop_breaker_metrics(self, addr: str) -> None:
        # BOTH per-endpoint series go: a reconciled-away endpoint's
        # frozen state gauge AND its ejection counter would otherwise
        # accrete forever on a long-lived registry as pods churn (a
        # re-added address starts a fresh breaker, so the counter
        # restarting from zero is the truthful series).
        self.metrics.lb_circuit_state.remove(
            model=self.model, endpoint=addr
        )
        self.metrics.lb_circuit_ejections.remove(
            model=self.model, endpoint=addr
        )
        self._breaker_states.pop(addr, None)
        self._breaker_stamps.pop(addr, None)

    def snapshot(self) -> dict:
        """Breaker + in-flight state for the LB state snapshot."""
        with self._cond:
            snap = {
                "total_in_flight": self.total_in_flight,
                "endpoints": {
                    ep.address: {
                        "in_flight": ep.in_flight,
                        "adapters": sorted(ep.adapters),
                        "role": ep.role,
                        "version": ep.version,
                        **ep.health.snapshot(),
                    }
                    for ep in self._endpoints.values()
                },
                "retired_in_flight": sum(
                    ep.in_flight for ep in self._retired.values()
                ),
            }
            if self._canary_version is not None:
                snap["canary"] = {
                    "version": self._canary_version,
                    "share": self._canary_share,
                    "routed": self._canary_routed,
                    "total": self._canary_total,
                }
            return snap

    def _candidates(self, adapter: str, role: str = "") -> list[_Endpoint]:
        eps = list(self._endpoints.values())
        if role:
            eps = [e for e in eps if e.role == role]
        if adapter:
            with_adapter = [e for e in eps if adapter in e.adapters]
            return with_adapter
        return eps

    def _pick(
        self, strategy: str, adapter: str, prefix: str,
        allowed: set[str], role: str = "", chain: list[str] | None = None,
    ) -> str:
        if chain:
            addr = self._pick_longest_held(chain, allowed)
            if addr is not None:
                self.metrics.lb_prefix_route_hits.inc(model=self.model)
                return addr
            # Miss: stale/empty holdings map or no endpoint within the
            # load bound holds any of the chain — classic CHWBL below,
            # byte-identical to a request that carried no chain.
            self.metrics.lb_prefix_route_misses.inc(model=self.model)
        if strategy == LB_STRATEGY_PREFIX_HASH and prefix:
            loads = {a: e.in_flight for a, e in self._endpoints.items()}
            addr = self._chwbl.get(prefix, loads, allowed)
            if addr is not None:
                return addr
        # LeastLoad (and PrefixHash fallback when no prefix/ring).
        candidates = [
            e for e in self._candidates(adapter, role)
            if e.address in allowed
        ]
        best = min(candidates, key=lambda e: e.in_flight)
        return best.address

    def _pick_longest_held(
        self, chain: list[str], allowed: set[str]
    ) -> str | None:
        """Longest-held-prefix pick: the allowed endpoint advertising the
        deepest leading match of the chain, subject to the SAME bounded-
        load threshold CHWBL enforces (a hot prefix must not stampede its
        holder). None when the map is stale or nothing within the bound
        holds a single page — the caller falls back to classic CHWBL."""
        if not self._holdings_fresh():
            return None
        held_map, _ = self._holdings_view()
        loads = {a: e.in_flight for a, e in self._endpoints.items()}
        total = sum(loads.values())
        n = max(len(loads), 1)
        threshold = (total + 1) / n * self.load_factor

        best, best_depth = None, 0
        for addr in sorted(allowed):
            held = held_map.get(addr)
            if not held:
                continue
            if total and loads.get(addr, 0) > threshold:
                continue
            depth = self._chain_depth(chain, held)
            if depth > best_depth:
                best, best_depth = addr, depth
        return best


class LoadBalancer:
    """Watches Pods in the store and maintains groups + self IPs
    (reference: internal/loadbalancer/load_balancer.go)."""

    def __init__(
        self,
        store: KubeStore,
        default_timeout: float = 600.0,
        metrics: Metrics = DEFAULT_METRICS,
        default_breaker: BreakerPolicy | None = None,
    ):
        self.store = store
        self.default_timeout = default_timeout
        self.metrics = metrics
        self.default_breaker = default_breaker or BreakerPolicy()
        self.recorder = None
        self.gossip = None
        self._lock = threading.Lock()
        self._groups: dict[str, Group] = {}
        self._self_ips: list[str] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._events = store.watch(("Pod",))

    # -- lifecycle ------------------------------------------------------------

    def set_recorder(self, recorder) -> None:
        """Wire the flight recorder into every group, existing and
        future (the manager constructs the recorder after the LB)."""
        with self._lock:
            self.recorder = recorder
            for group in self._groups.values():
                group.recorder = recorder

    def set_gossip(self, node) -> None:
        """Wire this door shard's gossip node
        (routing/gossip.DoorGossipNode) into every group, existing and
        future: breaker verdicts publish/adopt through it, half-open
        probes are elected through it, and prefix-holdings reads come
        from the gossiped map."""
        with self._lock:
            self.gossip = node
            for group in self._groups.values():
                group.gossip = node

    def sync_remote_breakers(self) -> int:
        """Apply peer shards' gossiped breaker verdicts to every group
        (called after anti-entropy rounds). Returns state changes."""
        with self._lock:
            groups = list(self._groups.values())
        return sum(g.sync_remote_breakers() for g in groups)

    def start(self) -> None:
        self.sync_all()
        self._thread = threading.Thread(target=self._watch_loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._events.put(None)
        if self._thread:
            self._thread.join(timeout=5)

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            item = self._events.get()
            if item is None:
                return
            _event, pod = item
            if _event == "RELIST":
                # Watch gap (410 Gone relist): deletions in the gap left
                # no event, so rebuild every group from the snapshot.
                self.sync_all()
                continue
            model = k8sutils.get_label(pod, md.POD_MODEL_LABEL)
            if model:
                self.sync_model(model, pod["metadata"].get("namespace", "default"))
            elif k8sutils.get_label(pod, SELF_POD_LABEL) == SELF_POD_VALUE:
                self._sync_self_ips()

    # -- endpoint discovery (reference: load_balancer.go:90-140) --------------

    def sync_all(self) -> None:
        models: set[tuple[str, str]] = set()
        for pod in self.store.list("Pod"):
            model = k8sutils.get_label(pod, md.POD_MODEL_LABEL)
            if model:
                models.add((model, pod["metadata"].get("namespace", "default")))
        for model, ns in models:
            self.sync_model(model, ns)
        self._sync_self_ips()

    def _sync_self_ips(self) -> None:
        """Collect metrics addresses of ALL operator replicas from their
        self pods — the autoscaler scrapes every one of these each tick."""
        addrs = []
        for pod in self.store.list(
            "Pod", label_selector={SELF_POD_LABEL: SELF_POD_VALUE}
        ):
            if not k8sutils.pod_is_ready(pod):
                continue
            addr = k8sutils.get_annotation(pod, SELF_METRICS_ADDR_ANNOTATION)
            if not addr:
                ip = (pod.get("status") or {}).get("podIP")
                port = k8sutils.get_annotation(pod, md.MODEL_POD_PORT_ANNOTATION) or "8080"
                addr = f"{ip}:{port}" if ip else None
            if addr:
                addrs.append(addr)
        with self._lock:
            self._self_ips = addrs

    def sync_model(self, model: str, namespace: str = "default") -> None:
        pods = self.store.list("Pod", namespace, {md.POD_MODEL_LABEL: model})
        # A slice group is ONE endpoint, keyed to host 0 — and it is
        # ejected WHOLE when any member is missing, not ready, disrupted,
        # or terminating. A lockstep group short one host serves nothing,
        # even while its coordinator still reports Ready; routing to it
        # would hang requests until the group repair lands.
        blocked_groups: set[int] = set()
        for g, members in slicegroup.group_pods(pods).items():
            if not slicegroup.group_ready(
                members, slicegroup.expected_size(members)
            ):
                blocked_groups.add(g)
        observed: dict[str, set[str]] = {}
        roles: dict[str, str] = {}
        versions: dict[str, str] = {}
        for pod in pods:
            g = slicegroup.group_index(pod)
            if g is not None and g in blocked_groups:
                if (
                    slicegroup.host_index(pod) == 0
                    and k8sutils.pod_is_ready(pod)
                    and k8sutils.pod_disruption_reason(pod) is None
                ):
                    # The coordinator alone would have passed the
                    # per-pod filters below: this is a true whole-group
                    # ejection, not a dead endpoint.
                    self.metrics.slicegroup_ejections.inc(model=model)
                continue
            if not k8sutils.pod_is_ready(pod):
                continue
            # Preempted / evicted pods are ejected the moment the watch
            # sees the disruption — a spot reclaim can leave Ready=True
            # stale for seconds, and waiting for the circuit breaker to
            # accumulate connect failures costs real requests.
            if k8sutils.pod_disruption_reason(pod) is not None:
                continue
            # Multi-host worker Pods participate in the mesh but do not
            # serve HTTP; only host-0 is an endpoint.
            if (
                k8sutils.get_annotation(pod, md.MODEL_POD_SERVING_ANNOTATION)
                == "false"
            ):
                continue
            ip = k8sutils.get_annotation(pod, md.MODEL_POD_IP_ANNOTATION) or (
                (pod.get("status") or {}).get("podIP")
            )
            if not ip:
                continue
            port = (
                k8sutils.get_annotation(pod, md.MODEL_POD_PORT_ANNOTATION)
                or "8000"
            )
            adapters = set()
            prefix = md.ADAPTER_LABEL_DOMAIN + "/"
            for k in (pod["metadata"].get("labels") or {}):
                if k.startswith(prefix):
                    adapters.add(k[len(prefix):])
            addr = f"{ip}:{port}"
            observed[addr] = adapters
            role = k8sutils.get_label(pod, md.POD_ROLE_LABEL)
            if role:
                roles[addr] = role
            version = k8sutils.get_label(pod, md.POD_HASH_LABEL)
            if version:
                versions[addr] = version
        self.group(model).reconcile_endpoints(
            observed, roles=roles, versions=versions
        )

    def group(self, model: str) -> Group:
        with self._lock:
            if model not in self._groups:
                group = Group(
                    metrics=self.metrics,
                    model=model,
                    breaker=self.default_breaker,
                )
                group.recorder = self.recorder
                group.gossip = self.gossip
                self._groups[model] = group
            return self._groups[model]

    def set_breaker_policy(self, model: str, policy: BreakerPolicy) -> None:
        """Apply a (CRD-derived) breaker policy to a model's group; cheap
        when unchanged, so the proxy calls it per request."""
        self.group(model).set_breaker_policy(policy)

    def update_kv_holdings(
        self, model: str, holdings: dict[str, Iterable[str]]
    ) -> None:
        """Fleet-aggregator push: the fresh who-holds-which-prefix map
        for one model's endpoints."""
        self.group(model).set_kv_holdings(holdings)

    def kv_holder(
        self, model: str, chain: list[str], exclude: Iterable[str] = ()
    ) -> tuple[str | None, int]:
        """Deepest closed-circuit holder of the chain for X-KV-Source."""
        return self.group(model).kv_holder(chain, exclude)

    def state(self) -> dict:
        """Per-model breaker/in-flight snapshot (admin/debug surface)."""
        with self._lock:
            groups = dict(self._groups)
        return {model: g.snapshot() for model, g in groups.items()}

    # -- API (reference: load_balancer.go:182-204) -----------------------------

    def get_self_ips(self) -> list[str]:
        with self._lock:
            return list(self._self_ips)

    def set_self_ips(self, ips: list[str]) -> None:
        with self._lock:
            self._self_ips = list(ips)

    def await_best_address(
        self,
        model: str,
        adapter: str = "",
        prefix: str = "",
        strategy: str = "LeastLoad",
        timeout: float | None = None,
        exclude: Iterable[str] | None = None,
        role: str = "",
        chain: list[str] | None = None,
    ) -> tuple[str, Callable[..., None]]:
        return self.group(model).get_best_addr(
            strategy, adapter, prefix,
            timeout=self.default_timeout if timeout is None else timeout,
            exclude=exclude,
            role=role,
            chain=chain,
        )
