"""Azure Service Bus messenger driver: AMQP 1.0 on the wire, zero deps.

The reference registers gocloud.dev's azuresb driver (reference:
internal/manager/run.go:47-52). Service Bus speaks AMQP 1.0 — a
different protocol from RabbitMQ's 0-9-1: typed encoding with described
types, SASL layering, sessions, links with credit-based flow control,
and delivery dispositions:

  SASL        PLAIN with the SAS key name/key (or ANONYMOUS for fakes)
  open/begin  one connection, one session
  attach      per queue: a sender link (publish) or receiver link
              (subscribe); receivers grant link-credit bounded to the
              local queue size, so the broker can never overrun the
              reader thread
  transfer    publishes are UNSETTLED and wait for the broker's
              accepted disposition — publish() raising on failure is
              what lets the Messenger nack and redeliver
  disposition accepted = ack, released = nack → immediate redelivery
              (gocloud azuresb parity)

The reader thread reconnects with exponential backoff and re-attaches
every link (the reference's subscription-restart behavior,
internal/messenger/messenger.go:98-127).

URL form (config `messaging.streams`):
  azuresb://NAMESPACE.servicebus.windows.net/queue-name
Credentials: $SERVICEBUS_KEY_NAME / $SERVICEBUS_KEY (SASL PLAIN);
$AZURE_SERVICEBUS_ENDPOINT overrides host:port for fakes/emulators
(plain TCP, SASL ANONYMOUS when no key is set).
"""

from __future__ import annotations

import logging
import os
import queue
import socket
import struct
import threading
import time
import urllib.parse

from kubeai_tpu.routing.brokers import RESTARTS_LOG_EVERY, _backoff
from kubeai_tpu.routing.messenger import Message

logger = logging.getLogger(__name__)

AMQP_HDR = b"AMQP\x00\x01\x00\x00"
SASL_HDR = b"AMQP\x03\x01\x00\x00"

# Performative descriptor codes.
P_OPEN = 0x10
P_BEGIN = 0x11
P_ATTACH = 0x12
P_FLOW = 0x13
P_TRANSFER = 0x14
P_DISPOSITION = 0x15
P_DETACH = 0x16
P_END = 0x17
P_CLOSE = 0x18
SASL_MECHANISMS = 0x40
SASL_INIT = 0x41
SASL_OUTCOME = 0x44
T_SOURCE = 0x28
T_TARGET = 0x29
STATE_ACCEPTED = 0x24
STATE_RELEASED = 0x26
SECTION_DATA = 0x75


# ---- AMQP 1.0 type codec -----------------------------------------------------


class Sym(str):
    """AMQP symbol (encodes 0xa3/0xb3 instead of string 0xa1/0xb1)."""


class Described:
    def __init__(self, code: int, value):
        self.code = code
        self.value = value

    def __repr__(self):
        return f"Described(0x{self.code:02x}, {self.value!r})"


def encode(v) -> bytes:
    if v is None:
        return b"\x40"
    if isinstance(v, Described):
        return b"\x00" + encode(v.code) + encode(v.value)
    if isinstance(v, bool):
        return b"\x41" if v else b"\x42"
    if isinstance(v, Sym):
        b = v.encode()
        if len(b) < 256:
            return b"\xa3" + struct.pack(">B", len(b)) + b
        return b"\xb3" + struct.pack(">I", len(b)) + b
    if isinstance(v, str):
        b = v.encode()
        if len(b) < 256:
            return b"\xa1" + struct.pack(">B", len(b)) + b
        return b"\xb1" + struct.pack(">I", len(b)) + b
    if isinstance(v, (bytes, bytearray)):
        if len(v) < 256:
            return b"\xa0" + struct.pack(">B", len(v)) + bytes(v)
        return b"\xb0" + struct.pack(">I", len(v)) + bytes(v)
    if isinstance(v, int):
        # uint/ulong family; descriptors use smallulong via Described.
        if v == 0:
            return b"\x43"
        if 0 < v < 256:
            return b"\x52" + struct.pack(">B", v)
        return b"\x70" + struct.pack(">I", v)
    if isinstance(v, list):
        body = b"".join(encode(x) for x in v)
        n = len(v)
        if not v:
            return b"\x45"
        if len(body) + 1 < 256 and n < 256:
            return b"\xc0" + struct.pack(">BB", len(body) + 1, n) + body
        return b"\xd0" + struct.pack(">II", len(body) + 4, n) + body
    raise TypeError(f"cannot AMQP-encode {type(v).__name__}")


def decode(buf: bytes, pos: int = 0):
    """-> (value, new_pos). Described values come back as Described with
    an int code when the descriptor is a ulong."""
    c = buf[pos]
    pos += 1
    if c == 0x00:
        desc, pos = decode(buf, pos)
        val, pos = decode(buf, pos)
        code = desc if isinstance(desc, int) else -1
        return Described(code, val), pos
    if c == 0x40:
        return None, pos
    if c == 0x41:
        return True, pos
    if c == 0x42:
        return False, pos
    if c == 0x56:  # boolean byte
        return buf[pos] == 1, pos + 1
    if c == 0x43 or c == 0x44:  # uint0 / ulong0
        return 0, pos
    if c in (0x50, 0x52, 0x53):  # ubyte / smalluint / smallulong
        return buf[pos], pos + 1
    if c == 0x60:  # ushort
        return struct.unpack_from(">H", buf, pos)[0], pos + 2
    if c == 0x70:  # uint
        return struct.unpack_from(">I", buf, pos)[0], pos + 4
    if c == 0x80:  # ulong
        return struct.unpack_from(">Q", buf, pos)[0], pos + 8
    if c in (0x54, 0x55):  # smallint/smalllong (signed byte)
        return struct.unpack_from(">b", buf, pos)[0], pos + 1
    if c == 0x71:  # int
        return struct.unpack_from(">i", buf, pos)[0], pos + 4
    if c in (0xA0, 0xA1, 0xA3):  # bin8/str8/sym8
        n = buf[pos]
        raw = bytes(buf[pos + 1:pos + 1 + n])
        pos += 1 + n
    elif c in (0xB0, 0xB1, 0xB3):  # bin32/str32/sym32
        (n,) = struct.unpack_from(">I", buf, pos)
        raw = bytes(buf[pos + 4:pos + 4 + n])
        pos += 4 + n
    elif c == 0x45:  # empty list
        return [], pos
    elif c == 0xC0:  # list8
        size, count = buf[pos], buf[pos + 1]
        end = pos + 1 + size
        pos += 2
        out = []
        for _ in range(count):
            v, pos = decode(buf, pos)
            out.append(v)
        return out, end
    elif c == 0xD0:  # list32
        size, count = struct.unpack_from(">II", buf, pos)
        end = pos + 4 + size
        pos += 8
        out = []
        for _ in range(count):
            v, pos = decode(buf, pos)
            out.append(v)
        return out, end
    elif c in (0xC1, 0xD1):  # map8/map32 (skipped wholesale)
        if c == 0xC1:
            size = buf[pos]
            return {}, pos + 1 + size
        (size,) = struct.unpack_from(">I", buf, pos)
        return {}, pos + 4 + size
    else:
        raise ValueError(f"unsupported AMQP constructor 0x{c:02x}")
    if c in (0xA1, 0xB1):
        return raw.decode(), pos
    if c in (0xA3, 0xB3):
        return Sym(raw.decode()), pos
    return raw, pos


def frame(channel: int, performative: Described, payload: bytes = b"",
          sasl: bool = False) -> bytes:
    body = encode(performative) + payload
    size = 8 + len(body)
    return struct.pack(">IBBH", size, 2, 1 if sasl else 0, channel) + body


def perf(code: int, fields: list) -> Described:
    return Described(code, fields)


# ---- the broker --------------------------------------------------------------


class _Link:
    def __init__(self, handle: int, qname: str, role_receiver: bool):
        self.handle = handle
        self.qname = qname
        self.receiver = role_receiver
        self.attached = threading.Event()
        self.credit_event = threading.Event()  # sender: credit granted
        self.delivery_count = 0


class AzureSBBroker:
    """Broker-seam driver (publish/receive/close) over AMQP 1.0."""

    def __init__(
        self,
        host: str,
        port: int | None = None,
        key_name: str | None = None,
        key: str | None = None,
        endpoint: str | None = None,
        timeout_s: float = 30.0,
        prefetch: int = 64,
    ):
        endpoint = endpoint or os.environ.get("AZURE_SERVICEBUS_ENDPOINT")
        if endpoint:
            parsed = urllib.parse.urlparse(
                endpoint if "://" in endpoint else "tcp://" + endpoint
            )
            self.host = parsed.hostname or host
            self.port = parsed.port or 5672
        else:
            self.host = host
            self.port = port or 5671
        self.vhost = host  # SASL/open hostname = the namespace
        self.key_name = key_name or os.environ.get("SERVICEBUS_KEY_NAME")
        self.key = key or os.environ.get("SERVICEBUS_KEY")
        self.timeout_s = timeout_s
        self.prefetch = prefetch
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._open_ok = threading.Event()
        self._queues: dict[str, queue.Queue] = {}
        self._links: dict[int, _Link] = {}  # handle -> link
        self._senders: dict[str, _Link] = {}
        self._receivers: dict[str, _Link] = {}
        self._next_handle = 0
        self._next_delivery = 0
        self._next_out_id = 0
        # delivery-id -> Event set when the broker settles it (publish).
        self._pending_disp: dict[int, threading.Event] = {}
        self._gen = 0
        self._stop = threading.Event()
        self._reader: threading.Thread | None = None

    @staticmethod
    def queue_of(url: str) -> str:
        if "://" in url:
            return urllib.parse.urlparse(url).path.strip("/") or "default"
        return url

    # -- connection -------------------------------------------------------------

    def _send(self, data: bytes) -> None:
        with self._wlock:
            sock = self._sock
            if sock is None:
                raise ConnectionError("AMQP1.0 not connected")
            sock.sendall(data)

    def _connect_locked(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        # The connect timeout must NOT become the read timeout: an idle
        # queue would then look like a dead connection every timeout_s
        # and the reader would churn reconnect/re-attach forever.
        sock.settimeout(None)
        sock.sendall(SASL_HDR)
        self._sock = sock
        if self._reader is None or not self._reader.is_alive():
            self._reader = threading.Thread(
                target=self._read_loop, daemon=True
            )
            self._reader.start()

    def _ensure_connected(self) -> None:
        with self._lock:
            if self._sock is None:
                self._open_ok.clear()
                self._connect_locked()
        if not self._open_ok.wait(timeout=self.timeout_s):
            raise ConnectionError("AMQP1.0 handshake timed out")

    # -- Broker interface -------------------------------------------------------

    def publish(self, topic_url: str, body: bytes) -> None:
        qname = self.queue_of(topic_url)
        self._ensure_connected()
        link = self._ensure_sender(qname)
        if not link.credit_event.wait(timeout=self.timeout_s):
            raise ConnectionError("AMQP1.0 sender got no credit")
        with self._lock:
            delivery_id = self._next_delivery
            self._next_delivery += 1
            self._next_out_id += 1
            pending = {"event": threading.Event(), "outcome": None}
            self._pending_disp[delivery_id] = pending
        tag = struct.pack(">I", delivery_id)
        payload = encode(Described(SECTION_DATA, bytes(body)))
        self._send(
            frame(
                0,
                perf(
                    P_TRANSFER,
                    [link.handle, delivery_id, tag, 0, False, False],
                ),
                payload,
            )
        )
        # Unsettled transfer: only the broker's ACCEPTED disposition
        # completes the publish — raising here (timeout, rejected,
        # released) lets the Messenger nack and redeliver.
        if not pending["event"].wait(timeout=self.timeout_s):
            with self._lock:
                self._pending_disp.pop(delivery_id, None)
            raise ConnectionError("AMQP1.0 publish was not settled")
        if pending["outcome"] != STATE_ACCEPTED:
            raise ConnectionError(
                f"AMQP1.0 publish not accepted "
                f"(state 0x{pending['outcome'] or 0:02x})"
            )

    def receive(self, sub_url: str, timeout: float) -> Message | None:
        qname = self.queue_of(sub_url)
        with self._lock:
            known = qname in self._queues
            if not known:
                # 2× prefetch: granted credit tops out at `prefetch`
                # in-flight while the local queue may hold up to
                # `prefetch` consumed-but-unread — the reader's put can
                # then never block (a blocked reader stops ALL frames,
                # including publish dispositions).
                self._queues[qname] = queue.Queue(maxsize=2 * self.prefetch)
        if not known:
            try:
                self._ensure_connected()
                self._ensure_receiver(qname)
            except Exception:
                with self._lock:
                    self._queues.pop(qname, None)
                raise
        try:
            msg = self._queues[qname].get(timeout=timeout)
        except queue.Empty:
            return None
        # Drain-side credit top-up: without it, a consumer that stalls
        # until credit exhausts would never receive again (the
        # transfer-side top-up only fires while transfers still flow).
        with self._lock:
            link = self._receivers.get(qname)
        if (
            link is not None
            and link.attached.is_set()
            and self._queues[qname].qsize() <= self.prefetch // 2
        ):
            try:
                self._grant_credit(link)
            except Exception:
                pass  # reconnect path re-grants on re-attach
        return msg

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # -- links ------------------------------------------------------------------

    def _send_attach(self, link: _Link) -> None:
        """One attach frame construction for BOTH the first attach and
        the reconnect re-attach (diverging copies would silently skew
        reconnect behavior)."""
        name = (
            f"{'recv' if link.receiver else 'send'}-"
            f"{link.qname}-{link.handle}"
        )
        source = Described(T_SOURCE, [link.qname if link.receiver else None])
        target = Described(T_TARGET, [None if link.receiver else link.qname])
        self._send(
            frame(
                0,
                perf(
                    P_ATTACH,
                    [name, link.handle, link.receiver, None, None,
                     source, target],
                ),
            )
        )

    def _attach(self, qname: str, receiver: bool) -> _Link:
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            link = _Link(handle, qname, receiver)
            self._links[handle] = link
            (self._receivers if receiver else self._senders)[qname] = link
        self._send_attach(link)
        if not link.attached.wait(timeout=self.timeout_s):
            raise ConnectionError(f"AMQP1.0 attach timed out for {qname}")
        if receiver:
            self._grant_credit(link)
        return link

    def _grant_credit(self, link: _Link) -> None:
        self._send(
            frame(
                0,
                perf(
                    P_FLOW,
                    [
                        0, 2 ** 16, self._next_out_id, 2 ** 16,
                        link.handle, link.delivery_count, self.prefetch,
                    ],
                ),
            )
        )

    def _ensure_sender(self, qname: str) -> _Link:
        with self._lock:
            link = self._senders.get(qname)
        if link is not None and link.attached.is_set():
            return link
        return self._attach(qname, receiver=False)

    def _ensure_receiver(self, qname: str) -> _Link:
        with self._lock:
            link = self._receivers.get(qname)
        if link is not None and link.attached.is_set():
            return link
        return self._attach(qname, receiver=True)

    # -- reader -----------------------------------------------------------------

    @staticmethod
    def _read_n(sock, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("AMQP1.0 connection closed")
            out += chunk
        return out

    def _read_frame(self, sock):
        hdr = self._read_n(sock, 8)
        size, doff, ftype, channel = struct.unpack(">IBBH", hdr)
        body = self._read_n(sock, size - 8)
        body = body[(doff - 2) * 4:]  # skip extended header
        return ftype, channel, body

    def _read_loop(self) -> None:
        restarts = 0
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                if self._stop.wait(0.2):
                    return
                continue
            try:
                # Protocol headers echo back before frames.
                hdr = self._read_n(sock, 8)
                if hdr == SASL_HDR:
                    self._sasl(sock)
                    hdr = self._read_n(sock, 8)
                if hdr != AMQP_HDR:
                    raise ConnectionError(f"bad AMQP header {hdr!r}")
                self._send(
                    frame(0, perf(P_OPEN, [f"kubeai-{id(self)}", self.vhost]))
                )
                while not self._stop.is_set():
                    ftype, channel, body = self._read_frame(sock)
                    restarts = 0
                    if not body:
                        continue  # keepalive empty frame
                    p, pos = decode(body)
                    self._on_performative(p, body[pos:])
            except Exception as e:
                if self._stop.is_set():
                    return
                restarts += 1
                log = (
                    logger.error
                    if restarts % RESTARTS_LOG_EVERY == 0
                    else logger.warning
                )
                log("AMQP1.0 connection lost (reconnect %d): %s", restarts, e)
                with self._lock:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    self._open_ok.clear()
                    self._gen += 1
                    # Publishes waiting on dispositions will time out and
                    # raise (their deliveries died with the connection).
                    self._pending_disp.clear()
                    links = list(self._links.values())
                    for link in links:
                        link.attached.clear()
                        link.credit_event.clear()
                if self._stop.wait(_backoff(restarts)):
                    return
                try:
                    with self._lock:
                        if self._sock is None:
                            self._connect_locked()
                except Exception:
                    with self._lock:
                        self._sock = None

    def _sasl(self, sock) -> None:
        # mechanisms -> init -> outcome
        while True:
            ftype, channel, body = self._read_frame(sock)
            p, _ = decode(body)
            if not isinstance(p, Described):
                continue
            if p.code == SASL_MECHANISMS:
                if self.key_name and self.key:
                    resp = (
                        b"\x00" + self.key_name.encode()
                        + b"\x00" + self.key.encode()
                    )
                    init = [Sym("PLAIN"), resp, self.vhost]
                else:
                    init = [Sym("ANONYMOUS"), b"", self.vhost]
                self._send(frame(0, perf(SASL_INIT, init), sasl=True))
            elif p.code == SASL_OUTCOME:
                code = p.value[0] if p.value else 1
                if code != 0:
                    raise ConnectionError(f"SASL failed (code {code})")
                self._send(AMQP_HDR)
                return

    def _on_performative(self, p, payload: bytes) -> None:
        if not isinstance(p, Described):
            return
        f = p.value or []

        def field(i, default=None):
            return f[i] if len(f) > i and f[i] is not None else default

        if p.code == P_OPEN:
            self._send(frame(0, perf(P_BEGIN, [None, 0, 2 ** 16, 2 ** 16])))
        elif p.code == P_BEGIN:
            self._open_ok.set()
            # Reconnect path: re-attach every known link.
            with self._lock:
                links = list(self._links.values())
            for link in links:
                if not link.attached.is_set():
                    self._send_attach(link)
        elif p.code == P_ATTACH:
            handle = field(1)
            link = self._links.get(handle)
            if link is not None:
                link.attached.set()
                if link.receiver:
                    self._grant_credit(link)
        elif p.code == P_FLOW:
            handle = field(4)
            link = self._links.get(handle)
            if link is not None and not link.receiver:
                credit = field(6, 0)
                if credit:
                    link.credit_event.set()
        elif p.code == P_TRANSFER:
            handle = field(0)
            delivery_id = field(1, 0)
            link = self._links.get(handle)
            if link is None or not link.receiver:
                return
            link.delivery_count += 1
            body = b""
            pos = 0
            while pos < len(payload):
                section, pos = decode(payload, pos)
                if isinstance(section, Described) and isinstance(
                    section.value, (bytes, bytearray)
                ):
                    body += bytes(section.value)
            gen = self._gen
            msg = Message(
                body,
                on_ack=lambda: self._settle(delivery_id, True, gen),
                on_nack=lambda: self._settle(delivery_id, False, gen),
            )
            q = self._queues.get(link.qname)
            if q is None:
                return
            while not self._stop.is_set():
                try:
                    q.put(msg, timeout=1.0)
                    break
                except queue.Full:
                    continue
            # Top up credit only while the local queue has room for a
            # full grant (receive() handles the drain-side top-up) —
            # unconditional grants would let the broker outrun the
            # consumer and block this reader thread on q.put, stalling
            # every frame including publish dispositions.
            if q.qsize() <= self.prefetch:
                self._grant_credit(link)
        elif p.code == P_DISPOSITION:
            first = field(1, 0)
            last = field(2, first)
            state = field(4)
            outcome = (
                state.code if isinstance(state, Described) else None
            )
            with self._lock:
                for did in range(first, last + 1):
                    pending = self._pending_disp.pop(did, None)
                    if pending is not None:
                        pending["outcome"] = outcome
                        pending["event"].set()
        elif p.code == P_CLOSE:
            raise ConnectionError("server closed the AMQP1.0 connection")

    def _settle(self, delivery_id: int, accept: bool, gen: int) -> None:
        if gen != self._gen:
            return  # connection died; the broker redelivers unsettled
        state = Described(
            STATE_ACCEPTED if accept else STATE_RELEASED, []
        )
        try:
            self._send(
                frame(
                    0,
                    perf(
                        P_DISPOSITION,
                        [True, delivery_id, delivery_id, True, state],
                    ),
                )
            )
        except Exception:
            logger.warning(
                "AMQP1.0 disposition failed (will redeliver)", exc_info=True
            )
