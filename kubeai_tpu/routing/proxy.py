"""Retrying reverse proxy — the synchronous data path
(reference: internal/modelproxy/handler.go).

Flow per request: parse → count active (autoscaling signal) →
scale-from-zero → await endpoint (blocks) → proxy with ≤3 attempts on
502/503/504/500 or transport error, replaying the saved body
(reference: handler.go:50-155, request.go:73-79). 5xx bodies from engines
are replaced with a generic message so internal details don't leak
(reference: request.go:45-63). Streaming (SSE) passes through chunk by
chunk — the body is piped, never buffered.
"""

from __future__ import annotations

import http.client
import logging
import random
import time
from typing import BinaryIO

from kubeai_tpu.crd.model import LB_STRATEGY_PREFIX_HASH
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.metrics import tracing
from kubeai_tpu.routing import apiutils
from kubeai_tpu.routing.loadbalancer import LoadBalancer, LoadBalancerTimeout
from kubeai_tpu.routing.modelclient import (
    AdapterNotFound,
    ModelClient,
    ModelNotFound,
)

logger = logging.getLogger(__name__)

MAX_RETRIES = 3
# 500/502/503/504 per the reference (internal/modelproxy/handler.go:50-55);
# 429 added because our engine sheds with it when its admission queue is
# full — the retry re-runs AwaitBestAddress, which lands on a less-loaded
# replica (body replay already buffered).
RETRY_STATUSES = (429, 500, 502, 503, 504)

# SLO-scheduling headers forwarded to engines (and stamped on spans):
# priority class, admission deadline, WFQ fairness key.
SCHEDULING_HEADERS = ("x-priority", "x-deadline-ms", "x-client-id")

# Jitter source for the Retry-After backoff (monkeypatchable in tests).
_jitter = random.random


class ProxyResult:
    """What the HTTP layer needs to respond: status, headers, body iterator."""

    def __init__(
        self, status: int, headers: list[tuple[str, str]], chunks,
        model: str = "",
    ):
        self.status = status
        self.headers = headers
        self.chunks = chunks  # iterator of bytes
        # Resolved model name ("" when lookup failed) — lets the front
        # door label its duration/TTFT histograms per model.
        self.model = model


class ModelProxy:
    def __init__(
        self,
        lb: LoadBalancer,
        model_client: ModelClient,
        metrics: Metrics = DEFAULT_METRICS,
    ):
        self.lb = lb
        self.model_client = model_client
        self.metrics = metrics

    def handle(
        self, path: str, body: bytes, headers: dict[str, str]
    ) -> ProxyResult:
        """Synchronous proxy entry (reference: modelproxy/handler.go:57-94)."""
        try:
            preq = apiutils.parse_request(body, path, headers)
        except apiutils.APIError as e:
            return _error(e.status, e.message)

        try:
            model = self.model_client.lookup_model(
                preq.model, preq.adapter, preq.selectors
            )
        except ModelNotFound:
            return _error(404, f"model not found: {preq.model}")
        except AdapterNotFound:
            return _error(404, f"adapter not found: {preq.model}_{preq.adapter}")

        self.metrics.inference_requests_active.inc(model=model.name)
        self.metrics.inference_requests_total.inc(model=model.name)
        decremented = [False]

        def _done():
            if not decremented[0]:
                decremented[0] = True
                self.metrics.inference_requests_active.dec(model=model.name)

        try:
            self.model_client.scale_at_least_one_replica(model.name)
            result = self._proxy_with_retries(path, preq, model, headers)
        except LoadBalancerTimeout:
            _done()
            return _error(
                503, "no model endpoints became ready in time",
                model=model.name,
            )
        except Exception:
            _done()
            logger.exception(
                "proxy failure for model %s (request_id=%s)",
                model.name, headers.get("x-request-id", ""),
            )
            return _error(502, "upstream failure", model=model.name)

        # Wrap the body iterator so active-count drops when fully streamed.
        orig = result.chunks
        result.model = model.name

        def wrapped():
            try:
                yield from orig
            finally:
                _done()

        result.chunks = wrapped()
        return result

    def _proxy_with_retries(
        self,
        path: str,
        preq: apiutils.ParsedRequest,
        model,
        headers: dict[str, str],
    ) -> ProxyResult:
        strategy = model.spec.load_balancing.strategy
        prefix_len = model.spec.load_balancing.prefix_hash.prefix_char_length
        prefix = preq.prefix[:prefix_len] if strategy == LB_STRATEGY_PREFIX_HASH else ""

        last_err: Exception | None = None
        request_id = headers.get("x-request-id", "")
        # Parent for every attempt span: the front door's server span
        # (attempts are SIBLINGS — rebinding headers below must not make
        # attempt N+1 a child of attempt N).
        trace_parent = tracing.parse_traceparent(headers.get("traceparent"))
        for attempt in range(MAX_RETRIES):
            if attempt > 0:
                self.metrics.proxy_retries.inc(model=model.name)
            self.metrics.proxy_attempts.inc(model=model.name)
            addr, done = self.lb.await_best_address(
                model.name,
                adapter=preq.adapter,
                prefix=prefix,
                strategy=strategy,
            )
            # One client span per attempt: retries show up as siblings
            # under the front door's server span, each carrying the
            # request id so a slow request is traceable end to end.
            attempt_attrs = {
                "endpoint": addr,
                "attempt": attempt,
                "request.model": model.name,
            }
            if request_id:
                attempt_attrs["request.id"] = request_id
            if headers.get("x-priority"):
                attempt_attrs["request.priority"] = headers["x-priority"]
            if headers.get("x-deadline-ms"):
                attempt_attrs["request.deadline_ms"] = headers["x-deadline-ms"]
            attempt_span = tracing.tracer().start_span(
                "proxy.attempt",
                parent=trace_parent,
                kind=tracing.KIND_CLIENT,
                attributes=attempt_attrs,
            )
            # The engine continues the trace under THIS attempt.
            headers = dict(headers, traceparent=attempt_span.context.traceparent())
            try:
                resp, conn = _send(addr, path, preq, headers)
            except OSError as e:
                attempt_span.end(error=str(e))
                done()
                last_err = e
                logger.warning(
                    "attempt %d: connection to %s failed: %s "
                    "(model=%s request_id=%s)",
                    attempt, addr, e, model.name, request_id,
                )
                continue
            except Exception as e:
                # e.g. http.client.BadStatusLine (engine died mid-response):
                # not retryable here, but the attempt span must export and
                # the endpoint's in-flight count must drop before the
                # generic 502 path takes over.
                attempt_span.end(error=str(e))
                done()
                raise
            if resp.status in RETRY_STATUSES and attempt < MAX_RETRIES - 1:
                attempt_span.set_attribute("http.status_code", resp.status)
                attempt_span.end(error=f"HTTP {resp.status} (retrying)")
                logger.warning(
                    "attempt %d: %s returned HTTP %d, retrying "
                    "(model=%s request_id=%s)",
                    attempt, addr, resp.status, model.name, request_id,
                )
                retry_after = resp.getheader("Retry-After")
                resp.read()
                conn.close()
                done()
                # A shedding replica (429/503 + Retry-After) asked for
                # backoff; under prefix-hash an immediate re-pick can land
                # on the same replica, so honor a short pause (capped).
                # JITTERED: a burst of concurrently-shed requests sleeping
                # the same duration would re-pick in a synchronized
                # stampede and — under prefix-hash — land on the same
                # replica again; spreading each sleep over [0.5, 1.0]× the
                # hint desynchronizes the herd while staying within the
                # backoff the replica asked for.
                if retry_after and resp.status in (429, 503):
                    try:
                        base = min(float(retry_after), 2.0)
                    except ValueError:
                        pass
                    else:
                        time.sleep(base * (0.5 + 0.5 * _jitter()))
                continue
            if resp.status >= 500:
                attempt_span.set_attribute("http.status_code", resp.status)
                attempt_span.end(error=f"HTTP {resp.status}")
                resp.read()
                conn.close()
                done()
                # Strip engine error details (reference: request.go:45-63).
                return _error(resp.status, "upstream model server error")

            attempt_span.set_attribute("http.status_code", resp.status)
            attempt_span.end()
            resp_headers = [
                (k, v)
                for k, v in resp.getheaders()
                if k.lower() not in ("transfer-encoding", "connection")
            ]

            def chunks(resp=resp, conn=conn, done=done):
                # read1 (not read): read(n) on a chunked response BLOCKS
                # until n bytes accumulate, which buffers ~160 small SSE
                # events before anything reaches the client — destroying
                # streaming TTFT/ITL through the proxy. read1 returns as
                # soon as any data is available.
                read = getattr(resp, "read1", resp.read)
                try:
                    while True:
                        chunk = read(16384)
                        if not chunk:
                            break
                        yield chunk
                finally:
                    conn.close()
                    done()

            return ProxyResult(
                resp.status, resp_headers, chunks(), model=model.name
            )
        raise last_err or RuntimeError("retries exhausted")


def _send(addr: str, path: str, preq: apiutils.ParsedRequest, headers: dict):
    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80), timeout=300)
    fwd = {
        "Content-Type": preq.content_type,
        "Content-Length": str(len(preq.body)),
    }
    for k in (
        "authorization", "accept", "x-request-id", "traceparent",
        *SCHEDULING_HEADERS,
    ):
        if k in headers:
            fwd[k] = headers[k]
    conn.request("POST", path, body=preq.body, headers=fwd)
    return conn.getresponse(), conn


def _error(status: int, message: str, model: str = "") -> ProxyResult:
    import json

    body = json.dumps({"error": {"message": message, "code": status}}).encode()
    return ProxyResult(
        status,
        [("Content-Type", "application/json"), ("Content-Length", str(len(body)))],
        iter([body]),
        model=model,
    )
