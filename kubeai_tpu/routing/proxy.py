"""Retrying reverse proxy — the synchronous data path
(reference: internal/modelproxy/handler.go).

Flow per request: parse → count active (autoscaling signal) →
scale-from-zero → await endpoint (blocks) → proxy with ≤3 attempts on
502/503/504/500 or transport error, replaying the saved body
(reference: handler.go:50-155, request.go:73-79). 5xx bodies from engines
are replaced with a generic message so internal details don't leak
(reference: request.go:45-63). Streaming (SSE) passes through chunk by
chunk — the body is piped, never buffered.

Resilience (beyond the reference's blind 3-retry loop):
  * every attempt outcome (success / connect_error / timeout / 5xx /
    midstream / shed) feeds the endpoint's circuit breaker in the load
    balancer, and retries pass the failed addresses as an exclude set so
    an attempt never re-picks the exact endpoint that just failed;
  * timeouts are split (TCP connect vs response header) and come from
    the system config `resilience:` block instead of a hardcoded 300 s;
  * `X-Deadline-Ms` bounds the whole retry budget — the proxy never
    retries (or sleeps a backoff) past the client's deadline, it reports
    the last failure instead;
  * a connection that dies mid-SSE is RESUMED transparently: the proxy
    accumulates each stream's emitted tokens (engine chunks carry a
    `token_ids` field) and re-dispatches a continuation request — prompt
    plus the already-emitted prefix — to a healthy endpoint, stitching
    the new stream so the client sees one uninterrupted response. Seeded
    and greedy streams resume token-identically (the engine's sampler is
    stateless given (seed, position)). Only when the resume budget or
    the endpoint pool is exhausted does the stream fall back to the
    terminal `error` event (+ `finish_reason: "error"` chunk for chat)
    instead of truncating silently.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import random
import time
from typing import Any

from kubeai_tpu.crd import metadata as md_roles
from kubeai_tpu.crd.model import LB_STRATEGY_PREFIX_HASH
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.metrics import tracing
from kubeai_tpu.routing import apiutils
from kubeai_tpu.routing.health import (
    OUTCOME_5XX,
    OUTCOME_CONNECT_ERROR,
    OUTCOME_MIDSTREAM,
    OUTCOME_SHED,
    OUTCOME_SUCCESS,
    OUTCOME_TIMEOUT,
    BreakerPolicy,
)
from kubeai_tpu.routing.loadbalancer import (
    LoadBalancer,
    LoadBalancerTimeout,
    NoHealthyEndpoints,
)
from kubeai_tpu.routing.modelclient import (
    AdapterNotFound,
    ModelClient,
    ModelNotFound,
)
from kubeai_tpu.routing.prefixchain import ChainComputer
from kubeai_tpu.utils import retryafter

logger = logging.getLogger(__name__)

MAX_RETRIES = 3
# 500/502/503/504 per the reference (internal/modelproxy/handler.go:50-55);
# 429 added because our engine sheds with it when its admission queue is
# full — the retry re-runs AwaitBestAddress, which lands on a less-loaded
# replica (body replay already buffered).
RETRY_STATUSES = (429, 500, 502, 503, 504)

# SLO-scheduling headers forwarded to engines (and stamped on spans):
# priority class, admission deadline, WFQ fairness key.
SCHEDULING_HEADERS = ("x-priority", "x-deadline-ms", "x-client-id")

# Disaggregated two-hop flow (kubeai_tpu/disagg): the proxy names the
# decode endpoint the prefill engine must push its KV handoff to, then
# references the handoff on the decode hop.
DISAGG_TRANSFER_HEADER = "X-Disagg-Transfer"
DISAGG_HANDOFF_HEADER = "X-Disagg-Handoff"
# Cluster KV-sharing: the proxy names the deepest closed-circuit holder
# of the request's page-hash chain; the serving replica pulls the
# common-prefix KV pages from it (engine /v1/kv/export) instead of
# recomputing. Purely advisory — the engine verifies every adopted page
# against its own hash chain, so a wrong hint costs a wasted fetch,
# never a wrong token.
KV_SOURCE_HEADER = "X-KV-Source"
# Short non-blocking pick budget for role groups: a disaggregated pool
# either exists now or the request falls back to unified — it must never
# burn the scale-from-zero hold against an empty role group.
DISAGG_PICK_TIMEOUT_S = 0.05

# Jitter source for the Retry-After backoff (monkeypatchable in tests).
_jitter = random.random

# Mid-stream resume: total continuation dispatches one stream may burn
# (every dispatch — successful or not — counts), and the pick budget when
# the client set no deadline. Bounded so a flapping fleet degrades to the
# terminal error tail instead of retrying forever on a held connection.
MAX_STREAM_RESUMES = 3
RESUME_PICK_TIMEOUT_S = 15.0


@dataclasses.dataclass(frozen=True)
class ProxyTimeouts:
    """Attempt timeouts (system config `resilience:` block). Connect
    covers the TCP handshake; response_header covers request write +
    time-to-first-response-byte (an engine legitimately decodes for
    minutes before its unary response, hence the generous default)."""

    connect_s: float = 2.0
    response_header_s: float = 300.0


class ProxyResult:
    """What the HTTP layer needs to respond: status, headers, body iterator."""

    def __init__(
        self, status: int, headers: list[tuple[str, str]], chunks,
        model: str = "",
    ):
        self.status = status
        self.headers = headers
        self.chunks = chunks  # iterator of bytes
        # Resolved model name ("" when lookup failed) — lets the front
        # door label its duration/TTFT histograms per model.
        self.model = model


class _SSEAccumulator:
    """Incremental SSE parser over the proxied byte stream. Feeds on the
    same chunks the client receives and extracts what a continuation
    request needs: the emitted token ids (from the engine chunks'
    `token_ids` field), how many characters of completion text reached
    the client, and whether the stream already finished ([DONE] /
    finish_reason) — a finished stream is never resumed."""

    __slots__ = ("_buf", "token_ids", "emitted_chars", "done_seen",
                 "finished")

    def __init__(self):
        self._buf = b""
        self.token_ids: list[int] = []
        self.emitted_chars = 0
        self.done_seen = False
        self.finished = False

    def feed(self, chunk: bytes) -> None:
        self._buf += chunk
        while True:
            idx = self._buf.find(b"\n\n")
            if idx < 0:
                return
            event, self._buf = self._buf[:idx], self._buf[idx + 2:]
            for line in event.splitlines():
                if not line.startswith(b"data:"):
                    continue
                data = line[5:].strip()
                if data == b"[DONE]":
                    self.done_seen = True
                    continue
                try:
                    obj = json.loads(data)
                except json.JSONDecodeError:
                    continue
                if not isinstance(obj, dict):
                    continue
                for t in obj.get("token_ids") or []:
                    if isinstance(t, int) and not isinstance(t, bool):
                        self.token_ids.append(t)
                for ch in obj.get("choices") or []:
                    if not isinstance(ch, dict):
                        continue
                    if "delta" in ch:
                        txt = (ch.get("delta") or {}).get("content")
                    else:
                        txt = ch.get("text")
                    if isinstance(txt, str):
                        self.emitted_chars += len(txt)
                    if ch.get("finish_reason"):
                        self.finished = True


@dataclasses.dataclass
class _ResumeCtx:
    """Everything a mid-stream continuation dispatch needs, captured at
    attempt time so the body iterator (consumed long after
    _proxy_with_retries returned) can still re-enter the routing path."""

    preq: apiutils.ParsedRequest
    headers: dict
    strategy: str
    prefix: str
    budget_left: Any
    failed: set
    role: str
    trace_parent: Any
    resume_attempts: int = 0


class ModelProxy:
    def __init__(
        self,
        lb: LoadBalancer,
        model_client: ModelClient,
        metrics: Metrics = DEFAULT_METRICS,
        timeouts: ProxyTimeouts | None = None,
        default_breaker: BreakerPolicy | None = None,
    ):
        self.lb = lb
        self.model_client = model_client
        self.metrics = metrics
        self.timeouts = timeouts or ProxyTimeouts()
        self.default_breaker = default_breaker or lb.default_breaker
        # KV-sharing chain computers, one per (pageSize, tokenizerDir)
        # so a spec change mid-run picks up a fresh tokenizer.
        self._chain_computers: dict[tuple[int, str], ChainComputer] = {}

    def handle(
        self, path: str, body: bytes, headers: dict[str, str]
    ) -> ProxyResult:
        """Synchronous proxy entry (reference: modelproxy/handler.go:57-94)."""
        try:
            preq = apiutils.parse_request(body, path, headers)
        except apiutils.APIError as e:
            return _error(e.status, e.message)

        try:
            model = self.model_client.lookup_model(
                preq.model, preq.adapter, preq.selectors
            )
        except ModelNotFound:
            return _error(404, f"model not found: {preq.model}")
        except AdapterNotFound:
            return _error(404, f"adapter not found: {preq.model}_{preq.adapter}")

        # The CRD's circuitBreaker block (merged over the system
        # defaults) configures this model's endpoint breakers.
        self.lb.set_breaker_policy(
            model.name, self._breaker_policy(model)
        )

        self.metrics.inference_requests_active.inc(model=model.name)
        self.metrics.inference_requests_total.inc(model=model.name)
        decremented = [False]

        def _done():
            if not decremented[0]:
                decremented[0] = True
                self.metrics.inference_requests_active.dec(model=model.name)

        try:
            self.model_client.scale_at_least_one_replica(model.name)
            result = self._proxy_with_retries(path, preq, model, headers)
        except NoHealthyEndpoints as e:
            # Fail fast: every endpoint's circuit is open. Surface the
            # last-seen per-endpoint errors so the client (and whoever
            # reads the 503 body) sees WHY, not just "try later".
            _done()
            return _error(
                503,
                f"no healthy model endpoints: {e}",
                model=model.name,
            )
        except LoadBalancerTimeout:
            _done()
            return _error(
                503, "no model endpoints became ready in time",
                model=model.name,
            )
        except Exception:
            _done()
            logger.exception(
                "proxy failure for model %s (request_id=%s)",
                model.name, headers.get("x-request-id", ""),
            )
            return _error(502, "upstream failure", model=model.name)

        # Wrap the body iterator so active-count drops when fully streamed.
        orig = result.chunks
        result.model = model.name

        def wrapped():
            try:
                yield from orig
            finally:
                _done()

        result.chunks = wrapped()
        return result

    def _breaker_policy(self, model) -> BreakerPolicy:
        cb = model.spec.load_balancing.circuit_breaker
        d = self.default_breaker
        if not cb.enabled():
            return d
        return BreakerPolicy(
            window=cb.window or d.window,
            consecutive_failures=(
                cb.consecutive_failures or d.consecutive_failures
            ),
            failure_rate=cb.failure_rate or d.failure_rate,
            min_samples=cb.min_samples or d.min_samples,
            open_seconds=cb.open_seconds or d.open_seconds,
        )

    def _kv_chain(
        self, model, preq: apiutils.ParsedRequest, path: str
    ) -> list[str] | None:
        """The request's page-hash chain for longest-held-prefix routing,
        or None whenever KV sharing doesn't apply (model opted out,
        adapter request — adapter chains are per-replica and
        incomparable — or a non-generate path). Tokenizer trouble
        degrades to classic routing, never to a failed request."""
        kvs = model.spec.kv_sharing
        if not kvs.enabled or preq.adapter:
            return None
        if not path.startswith(("/v1/chat/completions", "/v1/completions")):
            return None
        try:
            body = json.loads(preq.body or b"{}")
            if not isinstance(body, dict):
                return None
            key = (kvs.page_size, kvs.tokenizer_dir)
            cc = self._chain_computers.get(key)
            if cc is None:
                cc = ChainComputer(kvs.page_size, kvs.tokenizer_dir)
                self._chain_computers[key] = cc
            return cc.chain_for_request(
                body, chat=path.startswith("/v1/chat/completions")
            )
        except Exception:
            logger.exception(
                "kv-sharing chain computation failed for model %s; "
                "falling back to classic routing", model.name,
            )
            return None

    def _proxy_with_retries(
        self,
        path: str,
        preq: apiutils.ParsedRequest,
        model,
        headers: dict[str, str],
    ) -> ProxyResult:
        strategy = model.spec.load_balancing.strategy
        prefix_len = model.spec.load_balancing.prefix_hash.prefix_char_length
        prefix = preq.prefix[:prefix_len] if strategy == LB_STRATEGY_PREFIX_HASH else ""
        # Cluster KV sharing: one chain per request, computed up front —
        # every retry routes (and hints X-KV-Source) from the same chain.
        kv_chain = self._kv_chain(model, preq, path)

        last_err: Exception | None = None
        last_desc = ""
        request_id = headers.get("x-request-id", "")
        # Client deadline = the whole request's retry budget: no attempt,
        # retry, or backoff sleep may start past it.
        budget_deadline: float | None = None
        raw_deadline = (headers.get("x-deadline-ms") or "").strip()
        if raw_deadline:
            try:
                ms = float(raw_deadline)
            except ValueError:
                ms = 0.0
            if ms > 0:
                budget_deadline = time.monotonic() + ms / 1000.0

        def budget_left() -> float | None:
            if budget_deadline is None:
                return None
            return budget_deadline - time.monotonic()

        def deadline_exhausted(attempt: int) -> ProxyResult:
            self.metrics.proxy_deadline_exhausted.inc(model=model.name)
            return _error(
                504,
                f"deadline of {raw_deadline}ms exhausted after "
                f"{attempt + 1} attempt(s); last failure: "
                f"{last_desc or 'none'}",
                model=model.name,
            )

        # Addresses that failed THIS request: the retry pick excludes
        # them (unless that would leave nowhere to go), so a retry never
        # lands on the exact endpoint that just failed even before its
        # breaker trips.
        failed_addrs: set[str] = set()
        # Parent for every attempt span: the front door's server span
        # (attempts are SIBLINGS — rebinding headers below must not make
        # attempt N+1 a child of attempt N).
        trace_parent = tracing.parse_traceparent(headers.get("traceparent"))

        # Disaggregated prefill/decode: when the model opted in AND both
        # role pools have routable endpoints, serve via the two-hop flow;
        # ANY failure along it falls back to the loop below (the handoff
        # is recomputed — fallback must never depend on disagg state).
        # The fallback pick is role-restricted: prefill-role engines
        # cannot serve plain generates, so route to the unified pool, or
        # failing that the decode pool (which serves monolithically).
        fallback_role = ""
        if model.spec.disaggregation.enabled:
            result = self._try_disagg(
                path, preq, model, headers, strategy, prefix,
                budget_left, request_id, trace_parent,
            )
            if result is not None:
                return result
            self.metrics.proxy_disagg_fallback.inc(model=model.name)
            group = self.lb.group(model.name)
            fallback_role = (
                md_roles.ROLE_UNIFIED
                if group.has_role(md_roles.ROLE_UNIFIED)
                else md_roles.ROLE_DECODE
            )

        for attempt in range(MAX_RETRIES):
            if attempt > 0:
                self.metrics.proxy_retries.inc(model=model.name)
            self.metrics.proxy_attempts.inc(model=model.name)
            remaining = budget_left()
            if remaining is not None and remaining <= 0:
                return deadline_exhausted(attempt - 1)
            addr, done = self.lb.await_best_address(
                model.name,
                adapter=preq.adapter,
                prefix=prefix,
                strategy=strategy,
                timeout=remaining,
                exclude=failed_addrs,
                role=fallback_role,
                chain=kv_chain,
            )
            # Even the holder itself may serve the request (best case: no
            # fetch at all); the hint only matters when the pick landed
            # elsewhere, so the serving address is excluded from it. An
            # address that already failed this request is excluded too —
            # a flaky serving path is no better as a transfer source.
            kv_extra = None
            if kv_chain:
                holder, _depth = self.lb.kv_holder(
                    model.name, kv_chain,
                    exclude={addr, *failed_addrs},
                )
                if holder:
                    kv_extra = {KV_SOURCE_HEADER: holder}
            # One client span per attempt: retries show up as siblings
            # under the front door's server span, each carrying the
            # request id so a slow request is traceable end to end.
            attempt_attrs = {
                "endpoint": addr,
                "attempt": attempt,
                "request.model": model.name,
            }
            if request_id:
                attempt_attrs["request.id"] = request_id
            if headers.get("x-priority"):
                attempt_attrs["request.priority"] = headers["x-priority"]
            if headers.get("x-deadline-ms"):
                attempt_attrs["request.deadline_ms"] = headers["x-deadline-ms"]
            attempt_span = tracing.tracer().start_span(
                "proxy.attempt",
                parent=trace_parent,
                kind=tracing.KIND_CLIENT,
                attributes=attempt_attrs,
            )
            # The engine continues the trace under THIS attempt.
            headers = dict(headers, traceparent=attempt_span.context.traceparent())
            try:
                resp, conn = _send(
                    addr, path, preq, headers,
                    connect_timeout=self.timeouts.connect_s,
                    read_timeout=self.timeouts.response_header_s,
                    extra_headers=kv_extra,
                )
            except OSError as e:
                fault = (
                    OUTCOME_TIMEOUT if isinstance(e, TimeoutError)
                    else OUTCOME_CONNECT_ERROR
                )
                attempt_span.set_attribute("fault.class", fault)
                attempt_span.end(error=str(e))
                done(outcome=fault, error=f"{fault}: {e}")
                failed_addrs.add(addr)
                last_err = e
                last_desc = f"{addr}: {fault} ({e})"
                logger.warning(
                    "attempt %d: connection to %s failed: %s "
                    "(model=%s request_id=%s)",
                    attempt, addr, e, model.name, request_id,
                )
                continue
            except Exception as e:
                # e.g. http.client.BadStatusLine (engine died mid-response):
                # not retryable here, but the attempt span must export and
                # the endpoint's in-flight count must drop before the
                # generic 502 path takes over.
                attempt_span.set_attribute(
                    "fault.class", OUTCOME_CONNECT_ERROR
                )
                attempt_span.end(error=str(e))
                done(outcome=OUTCOME_CONNECT_ERROR, error=str(e))
                raise
            if resp.status in RETRY_STATUSES and attempt < MAX_RETRIES - 1:
                outcome = OUTCOME_SHED if resp.status == 429 else OUTCOME_5XX
                attempt_span.set_attribute("http.status_code", resp.status)
                attempt_span.set_attribute("fault.class", outcome)
                attempt_span.end(error=f"HTTP {resp.status} (retrying)")
                logger.warning(
                    "attempt %d: %s returned HTTP %d, retrying "
                    "(model=%s request_id=%s)",
                    attempt, addr, resp.status, model.name, request_id,
                )
                retry_after = resp.getheader("Retry-After")
                resp.read()
                conn.close()
                done(
                    outcome=outcome,
                    error=f"HTTP {resp.status}",
                )
                if outcome is OUTCOME_5XX:
                    failed_addrs.add(addr)
                last_desc = f"{addr}: HTTP {resp.status}"
                remaining = budget_left()
                if remaining is not None and remaining <= 0:
                    # Never retry past the client's deadline — report
                    # the last outcome instead.
                    return deadline_exhausted(attempt)
                # A shedding replica (429/503 + Retry-After) asked for
                # backoff; under prefix-hash an immediate re-pick can land
                # on the same replica, so honor a short pause (capped).
                # JITTERED: a burst of concurrently-shed requests sleeping
                # the same duration would re-pick in a synchronized
                # stampede and — under prefix-hash — land on the same
                # replica again; spreading each sleep over [0.5, 1.0]× the
                # hint desynchronizes the herd while staying within the
                # backoff the replica asked for. Non-numeric Retry-After
                # values (RFC 7231 allows HTTP-dates) are ignored rather
                # than parsed: an immediate re-pick beats a crash.
                if retry_after and resp.status in (429, 503):
                    parsed_ra = retryafter.parse_header(retry_after)
                    if parsed_ra is not None:
                        base = min(parsed_ra, 2.0)
                        # Cumulative backoff may not eat the deadline:
                        # cap the sleep at the remaining budget.
                        if remaining is not None:
                            base = min(base, max(0.0, remaining))
                        time.sleep(base * (0.5 + 0.5 * _jitter()))
                continue
            if resp.status >= 500:
                attempt_span.set_attribute("http.status_code", resp.status)
                attempt_span.set_attribute("fault.class", OUTCOME_5XX)
                attempt_span.end(error=f"HTTP {resp.status}")
                resp.read()
                conn.close()
                done(outcome=OUTCOME_5XX, error=f"HTTP {resp.status}")
                # Strip engine error details (reference: request.go:45-63).
                return _error(resp.status, "upstream model server error")

            attempt_span.set_attribute("http.status_code", resp.status)
            attempt_span.end()
            failed_addrs.add(addr)  # a resume must not re-pick this addr
            return self._forward_response(
                resp, conn, done, addr, model.name, path, request_id,
                resume=_ResumeCtx(
                    preq=preq,
                    headers=headers,
                    strategy=strategy,
                    prefix=prefix,
                    budget_left=budget_left,
                    failed=failed_addrs,
                    role=fallback_role,
                    trace_parent=trace_parent,
                ),
            )
        raise last_err or RuntimeError("retries exhausted")

    def _try_disagg(
        self, path, preq, model, headers, strategy, prefix,
        budget_left, request_id, trace_parent,
    ) -> ProxyResult | None:
        """One two-hop prefill→decode attempt. Returns None whenever the
        disaggregated path cannot (or should not) serve this request —
        the caller falls back to the unified retry loop. Circuit-breaker
        discipline is inherited from the role-filtered pick: an open
        decode circuit is never handed a handoff (get_best_addr excludes
        it, and raises NoHealthyEndpoints when the whole role pool is
        open — which we translate into fallback, not failure)."""
        if not path.startswith(("/v1/chat/completions", "/v1/completions")):
            return None
        group = self.lb.group(model.name)
        if not (
            group.has_role(md_roles.ROLE_PREFILL)
            and group.has_role(md_roles.ROLE_DECODE)
        ):
            return None
        try:
            parsed = json.loads(preq.body or b"{}")
        except json.JSONDecodeError:
            return None
        n = parsed.get("n") if isinstance(parsed, dict) else None
        if isinstance(n, int) and not isinstance(n, bool) and n > 1:
            # Multi-choice requests need n sampler states from one
            # prefill; the handoff carries exactly one. Unified serves
            # them.
            return None
        remaining = budget_left()
        if remaining is not None and remaining <= 0:
            return None
        # Decode endpoint FIRST: the prefill engine pushes the handoff
        # to it, so its address is part of the prefill request.
        try:
            d_addr, d_done = group.get_best_addr(
                "LeastLoad", preq.adapter, "",
                timeout=DISAGG_PICK_TIMEOUT_S, role=md_roles.ROLE_DECODE,
            )
        except (NoHealthyEndpoints, LoadBalancerTimeout):
            return None
        try:
            # Prefill keeps the model's configured strategy + prefix so
            # PrefixHash affinity lands shared prefixes on the prefill
            # replica that already has their pages cached.
            p_addr, p_done = group.get_best_addr(
                strategy, preq.adapter, prefix,
                timeout=DISAGG_PICK_TIMEOUT_S, role=md_roles.ROLE_PREFILL,
            )
        except (NoHealthyEndpoints, LoadBalancerTimeout):
            d_done()
            return None

        span_attrs = {
            "request.model": model.name,
            "disagg.prefill_endpoint": p_addr,
            "disagg.decode_endpoint": d_addr,
        }
        if request_id:
            span_attrs["request.id"] = request_id

        # ---- hop 1: prefill + handoff push ------------------------------
        p_span = tracing.tracer().start_span(
            "proxy.disagg.prefill",
            parent=trace_parent,
            kind=tracing.KIND_CLIENT,
            attributes=span_attrs,
        )
        hop_headers = dict(
            headers, traceparent=p_span.context.traceparent()
        )
        try:
            resp, conn = _send(
                p_addr, path, preq, hop_headers,
                connect_timeout=self.timeouts.connect_s,
                read_timeout=self.timeouts.response_header_s,
                extra_headers={DISAGG_TRANSFER_HEADER: d_addr},
            )
        except OSError as e:
            fault = (
                OUTCOME_TIMEOUT if isinstance(e, TimeoutError)
                else OUTCOME_CONNECT_ERROR
            )
            p_span.set_attribute("fault.class", fault)
            p_span.end(error=str(e))
            p_done(outcome=fault, error=f"{fault}: {e}")
            d_done()
            return None
        if resp.status != 200:
            body = resp.read()
            conn.close()
            outcome = (
                OUTCOME_SHED if resp.status == 429
                else OUTCOME_5XX if resp.status >= 500
                else OUTCOME_SUCCESS  # a coherent 4xx answer
            )
            p_span.set_attribute("http.status_code", resp.status)
            p_span.end(error=f"HTTP {resp.status}")
            p_done(outcome=outcome, error=f"HTTP {resp.status}")
            d_done()
            logger.warning(
                "disagg prefill hop to %s returned HTTP %d, falling back "
                "to unified (model=%s request_id=%s body=%r)",
                p_addr, resp.status, model.name, request_id, body[:200],
            )
            return None
        try:
            receipt = json.loads(resp.read() or b"{}")
        except json.JSONDecodeError:
            receipt = {}
        conn.close()
        handoff_id = str(receipt.get("handoff_id") or "")
        p_span.set_attribute("http.status_code", 200)
        if handoff_id:
            p_span.set_attribute("disagg.handoff_id", handoff_id)
        p_span.end()
        p_done(outcome=OUTCOME_SUCCESS)
        if not handoff_id:
            d_done()
            return None

        # ---- hop 2: decode from the handoff -----------------------------
        remaining = budget_left()
        if remaining is not None and remaining <= 0:
            d_done()
            return None
        d_span = tracing.tracer().start_span(
            "proxy.disagg.decode",
            parent=trace_parent,
            kind=tracing.KIND_CLIENT,
            attributes={**span_attrs, "disagg.handoff_id": handoff_id},
        )
        hop_headers = dict(
            headers, traceparent=d_span.context.traceparent()
        )
        try:
            resp, conn = _send(
                d_addr, path, preq, hop_headers,
                connect_timeout=self.timeouts.connect_s,
                read_timeout=self.timeouts.response_header_s,
                extra_headers={DISAGG_HANDOFF_HEADER: handoff_id},
            )
        except OSError as e:
            fault = (
                OUTCOME_TIMEOUT if isinstance(e, TimeoutError)
                else OUTCOME_CONNECT_ERROR
            )
            d_span.set_attribute("fault.class", fault)
            d_span.end(error=str(e))
            d_done(outcome=fault, error=f"{fault}: {e}")
            return None
        if resp.status != 200:
            resp.read()
            conn.close()
            outcome = (
                OUTCOME_SHED if resp.status == 429
                else OUTCOME_5XX if resp.status >= 500
                else OUTCOME_SUCCESS
            )
            d_span.set_attribute("http.status_code", resp.status)
            d_span.end(error=f"HTTP {resp.status}")
            d_done(outcome=outcome, error=f"HTTP {resp.status}")
            logger.warning(
                "disagg decode hop to %s returned HTTP %d, falling back "
                "to unified (model=%s request_id=%s)",
                d_addr, resp.status, model.name, request_id,
            )
            return None
        d_span.set_attribute("http.status_code", resp.status)
        d_span.end()
        self.metrics.proxy_disagg_requests.inc(model=model.name)
        return self._forward_response(
            resp, conn, d_done, d_addr, model.name, path, request_id
        )

    def _forward_response(
        self, resp, conn, done, addr, model_name, path, request_id,
        resume: _ResumeCtx | None = None,
    ) -> ProxyResult:
        """Pipe an accepted upstream response through to the client:
        headers minus hop-by-hop fields, body chunk by chunk, the final
        outcome fed to the endpoint's breaker. Shared by the unified
        attempt loop and the disaggregated decode hop so mid-stream
        fault handling cannot drift between the two paths.

        With `resume` (unified path only), a single-choice SSE stream
        that dies mid-body is transparently continued on another
        endpoint instead of terminated: the accumulated token prefix is
        re-dispatched as a continuation request and the new stream is
        stitched in place — the client sees one response and one [DONE]."""
        if resp.status == 429:
            # Shed on the LAST attempt: the engine's 429 body (per-
            # class queue depths + computed Retry-After) passes
            # through untouched so clients can back off honestly.
            done(outcome=OUTCOME_SHED, error="HTTP 429")
        resp_headers = [
            (k, v)
            for k, v in resp.getheaders()
            if k.lower() not in ("transfer-encoding", "connection")
        ]
        is_sse = any(
            k.lower() == "content-type"
            and v.lower().startswith("text/event-stream")
            for k, v in resp_headers
        )
        is_chat = path.startswith("/v1/chat/")

        # Resume eligibility: a streaming single-choice generate whose
        # body the continuation request can extend. Multi-choice streams
        # interleave per-choice token prefixes, so they keep the
        # terminal-error contract.
        parsed_body = None
        if (
            resume is not None
            and is_sse
            and path.startswith(("/v1/chat/completions", "/v1/completions"))
        ):
            try:
                parsed_body = json.loads(resume.preq.body or b"{}")
            except json.JSONDecodeError:
                parsed_body = None
            if not (
                isinstance(parsed_body, dict)
                and parsed_body.get("stream")
                and parsed_body.get("n") in (None, 1)
            ):
                parsed_body = None

        def chunks(resp=resp, conn=conn, done=done, addr=addr,
                   is_sse=is_sse, is_chat=is_chat):
            acc = _SSEAccumulator() if parsed_body is not None else None
            cur_resp, cur_conn, cur_done, cur_addr = resp, conn, done, addr
            while True:
                # read1 (not read): read(n) on a chunked response BLOCKS
                # until n bytes accumulate, which buffers ~160 small SSE
                # events before anything reaches the client — destroying
                # streaming TTFT/ITL through the proxy. read1 returns as
                # soon as any data is available.
                read = getattr(cur_resp, "read1", cur_resp.read)
                try:
                    while True:
                        chunk = read(16384)
                        if not chunk:
                            cur_conn.close()
                            cur_done(outcome=OUTCOME_SUCCESS)
                            return
                        if acc is not None:
                            acc.feed(chunk)
                        yield chunk
                except GeneratorExit:
                    # Client walked away mid-stream: release the slot
                    # with no health outcome — the endpoint did nothing
                    # wrong.
                    cur_conn.close()
                    cur_done()
                    raise
                except Exception as e:
                    # The engine connection died partway through the
                    # body. Record the fault against the endpoint's
                    # health window, then try to RESUME the stream on
                    # another endpoint; only a dry resume budget (or an
                    # unresumable stream) falls back to the terminal
                    # error tail — never a silent truncation.
                    cur_conn.close()
                    cur_done(
                        outcome=OUTCOME_MIDSTREAM,
                        error=f"mid-stream: {e}",
                    )
                    self.metrics.proxy_midstream_failures.inc(
                        model=model_name
                    )
                    logger.warning(
                        "mid-stream failure from %s: %s "
                        "(model=%s request_id=%s)",
                        cur_addr, e, model_name, request_id,
                    )
                    if not is_sse:
                        raise  # unary body: nothing valid left to send
                    if acc is not None:
                        if acc.done_seen:
                            return  # protocol complete; nothing was lost
                        if acc.finished:
                            # Only [DONE] was lost; complete the protocol.
                            yield b"data: [DONE]\n\n"
                            return
                        resume.failed.add(cur_addr)
                        nxt = self._resume_stream(
                            resume, acc, parsed_body, path, model_name,
                            request_id,
                        )
                        if nxt is not None:
                            cur_resp, cur_conn, cur_done, cur_addr = nxt
                            continue
                        self.metrics.proxy_stream_resume_failures.inc(
                            model=model_name
                        )
                    yield from _sse_error_tail(model_name, is_chat, e)
                    return

        return ProxyResult(
            resp.status, resp_headers, chunks(), model=model_name
        )

    def _resume_stream(
        self, ctx: _ResumeCtx, acc: _SSEAccumulator, parsed_body: dict,
        path: str, model_name: str, request_id: str,
    ):
        """Dispatch a continuation request for a dead stream: pick a
        healthy endpoint (circuit-breaker exclude-set honored), POST the
        original body plus the `kubeai_resume` prefix, and hand back the
        new (resp, conn, done, addr) to stitch into the client's stream.
        Bounded by MAX_STREAM_RESUMES dispatches and the client's
        X-Deadline-Ms budget; returns None when neither allows another
        attempt — the caller falls back to the terminal error tail."""
        while ctx.resume_attempts < MAX_STREAM_RESUMES:
            remaining = ctx.budget_left()
            if remaining is not None and remaining <= 0:
                return None
            timeout = (
                RESUME_PICK_TIMEOUT_S if remaining is None
                else min(remaining, RESUME_PICK_TIMEOUT_S)
            )
            try:
                addr, done = self.lb.await_best_address(
                    model_name,
                    adapter=ctx.preq.adapter,
                    prefix=ctx.prefix,
                    strategy=ctx.strategy,
                    timeout=timeout,
                    exclude=ctx.failed,
                    role=ctx.role,
                )
            except (NoHealthyEndpoints, LoadBalancerTimeout):
                return None
            ctx.resume_attempts += 1
            body = dict(parsed_body)
            body["kubeai_resume"] = {
                "token_ids": list(acc.token_ids),
                "emitted": acc.emitted_chars,
            }
            preq = dataclasses.replace(
                ctx.preq, body=json.dumps(body).encode()
            )
            span_attrs = {
                "endpoint": addr,
                "resume.attempt": ctx.resume_attempts,
                "resume.tokens": len(acc.token_ids),
                "request.model": model_name,
            }
            if request_id:
                span_attrs["request.id"] = request_id
            span = tracing.tracer().start_span(
                "proxy.resume",
                parent=ctx.trace_parent,
                kind=tracing.KIND_CLIENT,
                attributes=span_attrs,
            )
            hop_headers = dict(
                ctx.headers, traceparent=span.context.traceparent()
            )
            try:
                resp, conn = _send(
                    addr, path, preq, hop_headers,
                    connect_timeout=self.timeouts.connect_s,
                    read_timeout=self.timeouts.response_header_s,
                )
            except OSError as e:
                fault = (
                    OUTCOME_TIMEOUT if isinstance(e, TimeoutError)
                    else OUTCOME_CONNECT_ERROR
                )
                span.set_attribute("fault.class", fault)
                span.end(error=str(e))
                done(outcome=fault, error=f"{fault}: {e}")
                ctx.failed.add(addr)
                continue
            if resp.status != 200:
                outcome = (
                    OUTCOME_SHED if resp.status == 429
                    else OUTCOME_5XX if resp.status >= 500
                    else OUTCOME_SUCCESS  # coherent 4xx answer
                )
                span.set_attribute("http.status_code", resp.status)
                span.end(error=f"HTTP {resp.status}")
                resp.read()
                conn.close()
                done(outcome=outcome, error=f"HTTP {resp.status}")
                if 400 <= resp.status < 500 and resp.status != 429:
                    # The continuation itself was rejected (e.g. a
                    # multi-host replica): another endpoint would answer
                    # the same.
                    return None
                ctx.failed.add(addr)
                continue
            span.set_attribute("http.status_code", 200)
            span.end()
            self.metrics.proxy_stream_resumes.inc(model=model_name)
            logger.info(
                "resumed stream on %s after %d emitted token(s) "
                "(attempt %d, model=%s request_id=%s)",
                addr, len(acc.token_ids), ctx.resume_attempts,
                model_name, request_id,
            )
            return resp, conn, done, addr
        return None


def _sse_error_tail(model_name: str, is_chat: bool, exc: Exception):
    """Terminal SSE events for a stream whose upstream died: a final
    chunk with `finish_reason: "error"` for chat streams, then an
    explicit `error` event, then [DONE] — clients see a terminated
    stream, never a silent truncation."""
    if is_chat:
        final = {
            "object": "chat.completion.chunk",
            "model": model_name,
            "choices": [
                {"index": 0, "delta": {}, "finish_reason": "error"}
            ],
        }
        yield f"data: {json.dumps(final)}\n\n".encode()
    err = {
        "error": {
            "message": f"upstream connection lost mid-stream: {exc}",
            "type": "upstream_error",
            "code": 502,
        }
    }
    yield f"event: error\ndata: {json.dumps(err)}\n\n".encode()
    yield b"data: [DONE]\n\n"


def _send(
    addr: str,
    path: str,
    preq: apiutils.ParsedRequest,
    headers: dict,
    connect_timeout: float = 2.0,
    read_timeout: float = 300.0,
    extra_headers: dict | None = None,
):
    """Open a connection with DISTINCT connect / response-header budgets:
    a dead host must fail in ~connect_timeout, while a busy engine still
    gets read_timeout to produce response headers."""
    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(
        host, int(port or 80), timeout=connect_timeout
    )
    conn.connect()
    if conn.sock is not None:
        conn.sock.settimeout(read_timeout)
    fwd = {
        "Content-Type": preq.content_type,
        "Content-Length": str(len(preq.body)),
    }
    for k in (
        "authorization", "accept", "x-request-id", "traceparent",
        *SCHEDULING_HEADERS,
    ):
        if k in headers:
            fwd[k] = headers[k]
    if extra_headers:
        fwd.update(extra_headers)
    conn.request("POST", path, body=preq.body, headers=fwd)
    return conn.getresponse(), conn


def _error(status: int, message: str, model: str = "") -> ProxyResult:
    body = json.dumps({"error": {"message": message, "code": status}}).encode()
    return ProxyResult(
        status,
        [("Content-Type", "application/json"), ("Content-Length", str(len(body)))],
        iter([body]),
        model=model,
    )
