"""Kafka broker driver: the wire protocol, zero dependencies.

The reference registers gocloud.dev's kafkapubsub driver (Sarama
underneath) for kafka:// streams (reference: internal/manager/run.go:50).
This driver speaks the Kafka binary protocol directly over TCP:

  Metadata(v1)         partition leaders per topic
  Produce(v3)          record-batch v2 (magic 2) with CRC32C, acks=all
  Fetch(v4)            record-batch v2 decode, long-poll via max_wait
  FindCoordinator(v0)  group coordinator discovery
  JoinGroup/SyncGroup/Heartbeat/LeaveGroup(v0)
                       consumer-group membership; the elected leader
                       computes a range assignment over the topic's
                       partitions (the standard "consumer" protocol
                       embedded assignment encoding)
  OffsetFetch(v1)/OffsetCommit(v2)
                       committed offsets = delivery cursor

Delivery semantics (gocloud kafkapubsub parity): at-least-once. A
message's ack commits its offset+1 (monotonically — a late ack behind a
newer one is a no-op); nack rewinds the partition's fetch cursor to the
nacked offset so everything from it redelivers. The fetch loop restarts
its session with exponential backoff after transport errors and rejoins
the group on REBALANCE_IN_PROGRESS / UNKNOWN_MEMBER_ID /
ILLEGAL_GENERATION, mirroring the reference's subscription-restart
behavior (reference: internal/messenger/messenger.go:98-127).

URL form (config `messaging.streams`):
  kafka://host:9092/topic        (requestSubscription and responseTopic)
"""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
import time
import urllib.parse

from kubeai_tpu.routing.messenger import Message

logger = logging.getLogger(__name__)

# -- error codes the driver reacts to ------------------------------------------
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC = 3
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27

API_PRODUCE = 0
API_FETCH = 1
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14


# -- CRC32C (Castagnoli), table-based ------------------------------------------

def _crc32c_table():
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC32C_TABLE = _crc32c_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- primitive codec -----------------------------------------------------------


class Writer:
    def __init__(self):
        self.buf = bytearray()

    def i8(self, v):  self.buf += struct.pack(">b", v); return self
    def i16(self, v): self.buf += struct.pack(">h", v); return self
    def i32(self, v): self.buf += struct.pack(">i", v); return self
    def i64(self, v): self.buf += struct.pack(">q", v); return self
    def u32(self, v): self.buf += struct.pack(">I", v); return self

    def string(self, s: str | None):
        if s is None:
            return self.i16(-1)
        b = s.encode()
        self.i16(len(b))
        self.buf += b
        return self

    def bytes_(self, b: bytes | None):
        if b is None:
            return self.i32(-1)
        self.i32(len(b))
        self.buf += b
        return self

    def array(self, items, fn):
        self.i32(len(items))
        for it in items:
            fn(self, it)
        return self

    def varint(self, v: int):
        """Zigzag varint (record encoding)."""
        z = (v << 1) ^ (v >> 63)
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                self.buf.append(b | 0x80)
            else:
                self.buf.append(b)
                return self

    def raw(self, b: bytes):
        self.buf += b
        return self

    def done(self) -> bytes:
        return bytes(self.buf)


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise EOFError("short kafka frame")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def i8(self):  return struct.unpack(">b", self._take(1))[0]
    def i16(self): return struct.unpack(">h", self._take(2))[0]
    def i32(self): return struct.unpack(">i", self._take(4))[0]
    def i64(self): return struct.unpack(">q", self._take(8))[0]
    def u32(self): return struct.unpack(">I", self._take(4))[0]

    def string(self) -> str | None:
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> bytes | None:
        n = self.i32()
        return None if n < 0 else self._take(n)

    def array(self, fn) -> list:
        n = self.i32()
        return [fn(self) for _ in range(max(0, n))]

    def varint(self) -> int:
        shift = z = 0
        while True:
            b = self.data[self.pos]
            self.pos += 1
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (z >> 1) ^ -(z & 1)

    def remaining(self) -> int:
        return len(self.data) - self.pos


# -- record batch v2 -----------------------------------------------------------


def encode_record_batch(values: list[bytes], timestamp_ms: int) -> bytes:
    """One record-batch (magic 2) holding `values` as keyless records."""
    records = Writer()
    for i, v in enumerate(values):
        body = Writer()
        body.i8(0)  # attributes
        body.varint(0)  # timestamp delta
        body.varint(i)  # offset delta
        body.varint(-1)  # null key
        body.varint(len(v))
        body.raw(v)
        body.varint(0)  # no headers
        rec = body.done()
        records.varint(len(rec))
        records.raw(rec)
    recs = records.done()

    # Everything after the CRC field is CRC32C'd.
    after_crc = (
        Writer()
        .i16(0)  # attributes (no compression)
        .i32(len(values) - 1)  # last offset delta
        .i64(timestamp_ms)  # first timestamp
        .i64(timestamp_ms)  # max timestamp
        .i64(-1)  # producer id
        .i16(-1)  # producer epoch
        .i32(-1)  # base sequence
        .i32(len(values))
        .raw(recs)
        .done()
    )
    w = Writer()
    w.i64(0)  # base offset (broker assigns)
    w.i32(4 + 1 + 4 + len(after_crc))  # batch length (after this field)
    w.i32(-1)  # partition leader epoch
    w.i8(2)  # magic
    w.u32(crc32c(after_crc))
    w.raw(after_crc)
    return w.done()


def decode_record_batches(data: bytes) -> list[tuple[int, bytes]]:
    """[(absolute_offset, value), ...] from a fetch response record set.
    Tolerates a trailing partial batch (brokers may truncate)."""
    out = []
    r = Reader(data)
    while r.remaining() >= 61:  # minimal batch header
        try:
            base_offset = r.i64()
            batch_len = r.i32()
            if r.remaining() < batch_len:
                break  # truncated tail
            end = r.pos + batch_len
            r.i32()  # partition leader epoch
            magic = r.i8()
            if magic != 2:
                r.pos = end
                continue
            r.u32()  # crc (trusted: TCP checksums + tests cover encode)
            r.i16()  # attributes
            r.i32()  # last offset delta
            r.i64()  # first timestamp
            r.i64()  # max timestamp
            r.i64()  # producer id
            r.i16()  # producer epoch
            r.i32()  # base sequence
            n = r.i32()
            for _ in range(n):
                rec_len = r.varint()
                rec_end = r.pos + rec_len
                rr = Reader(r.data[r.pos:rec_end])
                rr.i8()  # attributes
                rr.varint()  # timestamp delta
                off_delta = rr.varint()
                klen = rr.varint()
                if klen > 0:
                    rr._take(klen)
                vlen = rr.varint()
                value = rr._take(vlen) if vlen >= 0 else b""
                out.append((base_offset + off_delta, bytes(value)))
                r.pos = rec_end
            r.pos = end
        except (EOFError, IndexError):
            break
    return out


# -- connection ----------------------------------------------------------------


class KafkaConn:
    """One broker connection: framed request/response, synchronous (a
    lock serializes callers — the driver's traffic is low-rate control
    and batched fetches, not a throughput path)."""

    def __init__(self, host: str, port: int, client_id: str, timeout: float):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.client_id = client_id
        self._corr = 0
        self._lock = threading.Lock()

    def call(self, api_key: int, api_version: int, body: bytes) -> Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = (
                Writer()
                .i16(api_key)
                .i16(api_version)
                .i32(corr)
                .string(self.client_id)
                .done()
            )
            frame = header + body
            self.sock.sendall(struct.pack(">i", len(frame)) + frame)
            raw = self._read_frame()
        r = Reader(raw)
        got = r.i32()
        if got != corr:
            raise ConnectionError(
                f"kafka correlation mismatch: sent {corr}, got {got}"
            )
        return r

    def _read_frame(self) -> bytes:
        hdr = self._read_n(4)
        (n,) = struct.unpack(">i", hdr)
        return self._read_n(n)

    def _read_n(self, n: int) -> bytes:
        chunks = []
        while n:
            chunk = self.sock.recv(n)
            if not chunk:
                raise ConnectionError("kafka connection closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# -- the broker ----------------------------------------------------------------

# Shared restart/backoff policy (brokers.py documents the rationale);
# imported rather than copied so the two can't drift. No circular import:
# brokers.py pulls this module in lazily inside make_broker().
from kubeai_tpu.routing.brokers import (  # noqa: E402
    RESTARTS_LOG_EVERY,
    _backoff,
)

API_LIST_OFFSETS = 2
EARLIEST_TIMESTAMP = -2


class _Rebalance(Exception):
    """Group membership changed (REBALANCE_IN_PROGRESS / ILLEGAL_GENERATION
    / UNKNOWN_MEMBER_ID): rejoin NOW on the same connections. Routing this
    through the transport-error restart (new pool + growing backoff) makes
    rebalances slower than the session timeout and live-locks the group."""


class _PartitionCursor:
    def __init__(self, offset: int):
        self.fetch_offset = offset  # next offset to fetch
        self.committed = offset  # next offset to commit
        self.rewind_to: int | None = None  # set by nack
        self.lock = threading.Lock()
        # Serializes OffsetCommit RPCs for this partition: concurrent
        # acks racing their commits could otherwise land out of order
        # and regress the broker-side offset.
        self.commit_lock = threading.Lock()


class _ConnPool:
    """Connections owned by ONE context (the publish path, or one
    consumer loop's session). Pools are never shared across contexts: a
    consumer restart tears down its own pool without injecting transport
    errors into concurrent publishes or other topics' consumers."""

    def __init__(self, client_id: str, timeout_s: float):
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._conns: dict[tuple[str, int], KafkaConn] = {}
        self._lock = threading.Lock()
        self._closed = False

    def get(self, host: str, port: int) -> KafkaConn:
        key = (host, port)
        with self._lock:
            if self._closed:
                raise ConnectionError("kafka pool closed")
            conn = self._conns.get(key)
            if conn is None:
                conn = KafkaConn(host, port, self.client_id, self.timeout_s)
                self._conns[key] = conn
            return conn

    def drop(self, host: str, port: int) -> None:
        with self._lock:
            conn = self._conns.pop((host, port), None)
        if conn:
            conn.close()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.close()


class KafkaBroker:
    """Broker-seam driver (publish/receive/close) over the Kafka wire
    protocol. One instance per stream URL; topics/subscriptions
    multiplex internally. See module docstring for semantics."""

    def __init__(
        self,
        host: str,
        port: int = 9092,
        group: str = "kubeai",
        client_id: str = "kubeai-tpu",
        session_timeout_ms: int = 10000,
        fetch_max_wait_ms: int = 500,
        fetch_max_bytes: int = 4 << 20,
        timeout_s: float = 35.0,
    ):
        self.host, self.port = host, port
        self.group = group
        self.client_id = client_id
        self.session_timeout_ms = session_timeout_ms
        self.fetch_max_wait_ms = fetch_max_wait_ms
        self.fetch_max_bytes = fetch_max_bytes
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._queues: dict[str, queue.Queue] = {}
        self._consumers: dict[str, threading.Thread] = {}
        self._pub_pool = _ConnPool(client_id, timeout_s)
        self._consumer_pools: dict[str, _ConnPool] = {}
        # topic -> (coord host, coord port, member id): live group
        # memberships, so close() can LeaveGroup and trigger an immediate
        # rebalance instead of waiting out the session timeout.
        self._memberships: dict[str, tuple[str, int, str]] = {}
        # topic -> {partition -> (host, port)}: leadership changes rarely,
        # so publish() reuses it and refreshes only on produce/transport
        # errors (a per-message Metadata round-trip would double publish
        # latency).
        self._leader_cache: dict[str, dict[int, tuple[str, int]]] = {}

    @staticmethod
    def topic_of(url: str) -> str:
        if "://" in url:
            return urllib.parse.urlparse(url).path.strip("/") or "default"
        return url

    # -- metadata ---------------------------------------------------------------

    def _metadata(self, topic: str, pool: _ConnPool) -> dict:
        """{partition -> (leader_host, leader_port)} plus partition list."""
        r = pool.get(self.host, self.port).call(
            API_METADATA, 1,
            Writer().array([topic], lambda w, t: w.string(t)).done(),
        )
        brokers = {}
        for _ in range(r.i32()):
            node = r.i32()
            host = r.string()
            port = r.i32()
            r.string()  # rack
            brokers[node] = (host, port)
        r.i32()  # controller id
        leaders: dict[int, tuple[str, int]] = {}
        for _ in range(r.i32()):  # topics
            err = r.i16()
            name = r.string()
            r.i8()  # is_internal
            for _ in range(r.i32()):  # partitions
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                r.array(lambda rr: rr.i32())  # replicas
                r.array(lambda rr: rr.i32())  # isr
                if name == topic and perr == ERR_NONE and leader in brokers:
                    leaders[pid] = brokers[leader]
            if err not in (ERR_NONE,) and name == topic:
                raise RuntimeError(f"kafka metadata for {topic}: error {err}")
        if not leaders:
            raise RuntimeError(f"kafka topic {topic}: no partition leaders")
        with self._lock:
            self._leader_cache[topic] = leaders
        return leaders

    def _cached_leaders(self, topic: str, pool: _ConnPool) -> dict:
        with self._lock:
            cached = self._leader_cache.get(topic)
        return cached if cached else self._metadata(topic, pool)

    def _invalidate_leaders(self, topic: str) -> None:
        with self._lock:
            self._leader_cache.pop(topic, None)

    # -- Broker interface: publish ----------------------------------------------

    def publish(self, topic_url: str, body: bytes) -> None:
        topic = self.topic_of(topic_url)
        leaders = self._cached_leaders(topic, self._pub_pool)
        # Round-robin-by-time across partitions; ordering across requests
        # is not part of the Broker contract (gocloud kafkapubsub also
        # publishes keyless by default).
        pid = sorted(leaders)[int(time.monotonic() * 1000) % len(leaders)]
        host, port = leaders[pid]
        batch = encode_record_batch([body], int(time.time() * 1000))
        req = Writer()
        req.string(None)  # transactional id
        req.i16(-1)  # acks = all
        req.i32(int(self.timeout_s * 1000))

        def part(w, _):
            w.i32(pid)
            w.bytes_(batch)

        def top(w, _):
            w.string(topic)
            w.array([None], part)

        req.array([None], top)
        try:
            r = self._pub_pool.get(host, port).call(
                API_PRODUCE, 3, req.done()
            )
        except OSError as e:
            # Stale leadership is one cause of transport failure; next
            # publish re-resolves it. The caller (Messenger) nacks, so
            # the message redelivers.
            self._invalidate_leaders(topic)
            self._pub_pool.drop(host, port)
            raise ConnectionError(f"kafka produce transport: {e}") from e
        for _ in range(r.i32()):  # topics
            r.string()
            for _ in range(r.i32()):  # partitions
                r.i32()  # partition
                err = r.i16()
                r.i64()  # base offset
                r.i64()  # log append time
                if err != ERR_NONE:
                    self._invalidate_leaders(topic)
                    raise RuntimeError(
                        f"kafka produce {topic}/{pid}: error {err}"
                    )

    # -- Broker interface: receive ----------------------------------------------

    def receive(self, sub_url: str, timeout: float) -> Message | None:
        topic = self.topic_of(sub_url)
        with self._lock:
            if topic not in self._queues:
                self._queues[topic] = queue.Queue(maxsize=64)
                t = threading.Thread(
                    target=self._consume_loop, args=(topic,), daemon=True
                )
                self._consumers[topic] = t
                t.start()
        try:
            return self._queues[topic].get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            memberships = dict(self._memberships)
            self._memberships.clear()
        # Polite departure on fresh connections (the consumer threads may
        # be mid-call on the shared ones): the coordinator rebalances the
        # group immediately instead of waiting out the session timeout.
        for host, port, member_id in memberships.values():
            try:
                conn = KafkaConn(host, port, self.client_id, 5.0)
                conn.call(
                    API_LEAVE_GROUP, 0,
                    Writer().string(self.group).string(member_id).done(),
                )
                conn.close()
            except OSError:
                pass
        self._pub_pool.close()
        with self._lock:
            pools = list(self._consumer_pools.values())
            self._consumer_pools.clear()
        for p in pools:
            p.close()

    # -- consumer group ---------------------------------------------------------

    def _find_coordinator(self, pool: _ConnPool) -> tuple[KafkaConn, str, int]:
        r = pool.get(self.host, self.port).call(
            API_FIND_COORDINATOR, 0, Writer().string(self.group).done()
        )
        err = r.i16()
        node = r.i32()
        host = r.string()
        port = r.i32()
        if err != ERR_NONE:
            raise RuntimeError(f"kafka find coordinator: error {err}")
        return pool.get(host, port), host, port

    def _join_group(
        self, coord: KafkaConn, topic: str, member_id: str, pool: _ConnPool
    ):
        """JoinGroup phase; returns (generation, member_id, leader,
        members). Kept separate from _sync_group so the broker-assigned
        member id SURVIVES a failed sync — rejoining with a fresh id on
        every rebalance creates a new member each time, which itself
        bumps the generation and live-locks the group."""
        meta = (  # consumer protocol subscription: version, topics, userdata
            Writer()
            .i16(0)
            .array([topic], lambda w, t: w.string(t))
            .bytes_(b"")
            .done()
        )
        req = (
            Writer()
            .string(self.group)
            .i32(self.session_timeout_ms)
            .string(member_id)
            .string("consumer")
            .array(
                [("range", meta)],
                lambda w, p: w.string(p[0]).bytes_(p[1]),
            )
            .done()
        )
        r = coord.call(API_JOIN_GROUP, 0, req)
        err = r.i16()
        if err == ERR_UNKNOWN_MEMBER_ID and member_id:
            return self._join_group(coord, topic, "", pool)
        if err != ERR_NONE:
            raise RuntimeError(f"kafka join group: error {err}")
        generation = r.i32()
        r.string()  # protocol
        leader = r.string()
        me = r.string()
        members = [
            (rr_id, rr_meta)
            for rr_id, rr_meta in (
                (r.string(), r.bytes_()) for _ in range(r.i32())
            )
        ]
        return generation, me, leader, members

    def _sync_group(
        self, coord: KafkaConn, topic: str, generation: int, me: str,
        leader: str, members, pool: _ConnPool,
    ) -> list[int]:
        """SyncGroup phase; returns this member's assigned partitions."""
        assignments = []
        if me == leader:
            # Each member's metadata is a consumer-protocol subscription
            # (version, topics, userdata). Range-assign EVERY subscribed
            # topic's partitions among the members subscribed to it — the
            # manager runs one group member per stream topic, so members
            # of the shared group subscribe to different topics and an
            # own-topic-only assignment would park the others forever.
            subscribers: dict[str, list[str]] = {}
            for mid, meta in members:
                rr = Reader(meta or b"")
                try:
                    rr.i16()  # version
                    for t in rr.array(lambda r2: r2.string()):
                        subscribers.setdefault(t, []).append(mid)
                except EOFError:
                    continue
            per_member: dict[str, list[tuple[str, list[int]]]] = {}
            for t, mids in sorted(subscribers.items()):
                parts = sorted(self._metadata(t, pool))
                mids = sorted(mids)
                per = -(-len(parts) // len(mids))
                for i, mid in enumerate(mids):
                    mine = parts[i * per:(i + 1) * per]
                    if mine:
                        per_member.setdefault(mid, []).append((t, mine))
            for mid, _meta in members:
                a = (
                    Writer()
                    .i16(0)
                    .array(
                        per_member.get(mid, []),
                        lambda w, e: w.string(e[0]).array(
                            e[1], lambda w2, p: w2.i32(p)
                        ),
                    )
                    .bytes_(b"")
                    .done()
                )
                assignments.append((mid, a))

        sync = (
            Writer()
            .string(self.group)
            .i32(generation)
            .string(me)
            .array(
                assignments, lambda w, a: w.string(a[0]).bytes_(a[1])
            )
            .done()
        )
        r = coord.call(API_SYNC_GROUP, 0, sync)
        err = r.i16()
        if err in (
            ERR_REBALANCE_IN_PROGRESS,
            ERR_ILLEGAL_GENERATION,
            ERR_UNKNOWN_MEMBER_ID,
        ):
            raise _Rebalance(f"sync group: error {err}")
        if err != ERR_NONE:
            raise RuntimeError(f"kafka sync group: error {err}")
        blob = r.bytes_() or b""
        mine: list[int] = []
        if blob:
            rr = Reader(blob)
            rr.i16()  # version
            for _ in range(rr.i32()):
                t = rr.string()
                ps = rr.array(lambda r2: r2.i32())
                if t == topic:
                    mine.extend(ps)
        return mine

    def _committed_offset(self, coord: KafkaConn, topic: str, pid: int) -> int:
        req = (
            Writer()
            .string(self.group)
            .array(
                [(topic, [pid])],
                lambda w, t: w.string(t[0]).array(
                    t[1], lambda w2, p: w2.i32(p)
                ),
            )
            .done()
        )
        r = coord.call(API_OFFSET_FETCH, 1, req)
        offset = 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                off = r.i64()
                r.string()  # metadata
                r.i16()  # error
                if off >= 0:
                    offset = off
        return offset

    def _commit(
        self, coord: KafkaConn, topic: str, pid: int, offset: int,
        generation: int, member_id: str,
    ) -> None:
        req = (
            Writer()
            .string(self.group)
            .i32(generation)
            .string(member_id)
            .i64(-1)  # retention: broker default
            .array(
                [(topic, pid, offset)],
                lambda w, t: w.string(t[0]).array(
                    [t], lambda w2, tt: w2.i32(tt[1]).i64(tt[2]).string(None)
                ),
            )
            .done()
        )
        r = coord.call(API_OFFSET_COMMIT, 2, req)
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                if err != ERR_NONE:
                    raise RuntimeError(f"kafka offset commit: error {err}")

    # -- fetch loop -------------------------------------------------------------

    def _consume_loop(self, topic: str) -> None:
        restarts = 0
        member_id = ""
        while not self._stop.is_set():
            # A fresh pool per session: the error path tears down only
            # THIS consumer's connections — never the publish path's or
            # another topic's (shared sockets would let one consumer's
            # restart inject transport errors into everyone mid-call).
            pool = _ConnPool(self.client_id, self.timeout_s)
            with self._lock:
                self._consumer_pools[topic] = pool
            progressed: list = []
            try:
                coord, chost, cport = self._find_coordinator(pool)
                # Membership loop: a rebalance rejoins immediately on the
                # SAME session; only transport errors fall out to the
                # backoff restart below.
                while not self._stop.is_set():
                    try:
                        generation, member_id, leader, members = (
                            self._join_group(coord, topic, member_id, pool)
                        )
                        with self._lock:
                            self._memberships[topic] = (
                                chost, cport, member_id
                            )
                        parts = self._sync_group(
                            coord, topic, generation, member_id, leader,
                            members, pool,
                        )
                        if not parts:
                            # Overprovisioned group member: heartbeat
                            # until a rebalance hands us partitions.
                            self._idle_heartbeat(
                                coord, topic, generation, member_id
                            )
                            continue
                        cursors = {
                            pid: _PartitionCursor(
                                self._committed_offset(coord, topic, pid)
                            )
                            for pid in parts
                        }
                        self._fetch_until_error(
                            topic, coord, cursors, generation, member_id,
                            pool, on_progress=progressed.append,
                        )
                    except _Rebalance as e:
                        logger.info(
                            "kafka consumer %s rejoining: %s", topic, e
                        )
                        # Brief pause: the new generation's leader may
                        # not have synced its assignments yet.
                        if self._stop.wait(0.1):
                            return
            except Exception as e:
                if self._stop.is_set():
                    return
                # A session that fetched successfully resets the backoff
                # (brokers.py drivers reset on a successful pull the same
                # way) — otherwise an old outage escalates every future
                # transient blip to the 30 s cap forever.
                restarts = 1 if progressed else restarts + 1
                log = (
                    logger.error
                    if restarts % RESTARTS_LOG_EVERY == 0
                    else logger.warning
                )
                log("kafka consumer %s restart %d: %s", topic, restarts, e)
                self._invalidate_leaders(topic)
                if self._stop.wait(_backoff(restarts)):
                    return
            finally:
                pool.close()

    def _idle_heartbeat(self, coord, topic, generation, member_id):
        while not self._stop.is_set():
            time.sleep(self.session_timeout_ms / 3000.0)
            r = coord.call(
                API_HEARTBEAT, 0,
                Writer()
                .string(self.group).i32(generation).string(member_id)
                .done(),
            )
            if r.i16() != ERR_NONE:
                return  # rejoin

    def _earliest_offset(self, conn: KafkaConn, topic: str, pid: int) -> int:
        """ListOffsets(earliest): the log-start offset — where a consumer
        resumes after its committed offset was retention-truncated
        (resetting to 0 would live-lock on a truncated log)."""
        req = Writer()
        req.i32(-1)  # replica id
        req.array(
            [(topic, pid)],
            lambda w, t: w.string(t[0]).array(
                [t[1]], lambda w2, p: w2.i32(p).i64(EARLIEST_TIMESTAMP)
            ),
        )
        r = conn.call(API_LIST_OFFSETS, 1, req.done())
        earliest = 0
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # timestamp
                off = r.i64()
                if err == ERR_NONE and off >= 0:
                    earliest = off
        return earliest

    def _fetch_until_error(
        self, topic: str, coord: KafkaConn, cursors, generation, member_id,
        pool: _ConnPool, on_progress=lambda x=None: None,
    ) -> None:
        """Fetch/deliver/commit until a transport/membership error bubbles
        up (caller rejoins). Heartbeats ride the same loop: every blocking
        wait (fetch long-poll, full-queue put) is budgeted below the
        heartbeat interval so a large idle assignment or a slow Messenger
        can't starve the session past its timeout."""
        leaders = self._metadata(topic, pool)
        hb_interval = self.session_timeout_ms / 3000.0
        last_hb = time.monotonic()

        def heartbeat_if_due():
            nonlocal last_hb
            if time.monotonic() - last_hb < hb_interval:
                return
            r = coord.call(
                API_HEARTBEAT, 0,
                Writer()
                .string(self.group).i32(generation).string(member_id)
                .done(),
            )
            err = r.i16()
            if err in (
                ERR_REBALANCE_IN_PROGRESS,
                ERR_ILLEGAL_GENERATION,
                ERR_UNKNOWN_MEMBER_ID,
            ):
                raise _Rebalance(f"heartbeat: error {err}")
            if err != ERR_NONE:
                raise RuntimeError(f"kafka heartbeat: error {err}")
            last_hb = time.monotonic()

        # One fetch per LEADER covers all its partitions (per-partition
        # sequential long-polls would take assigned_partitions ×
        # fetch_max_wait per sweep).
        by_leader: dict[tuple[str, int], list[int]] = {}
        for pid in cursors:
            by_leader.setdefault(leaders[pid], []).append(pid)

        while not self._stop.is_set():
            heartbeat_if_due()
            for (host, port), pids in by_leader.items():
                offsets = {}
                for pid in pids:
                    cur = cursors[pid]
                    with cur.lock:
                        if cur.rewind_to is not None:
                            cur.fetch_offset = cur.rewind_to
                            cur.rewind_to = None
                        offsets[pid] = cur.fetch_offset
                hb_budget_ms = int(
                    max(hb_interval - (time.monotonic() - last_hb), 0.05)
                    * 1000
                )
                req = Writer()
                req.i32(-1)  # replica id
                req.i32(min(self.fetch_max_wait_ms, hb_budget_ms))
                req.i32(1)  # min bytes
                req.i32(self.fetch_max_bytes)
                req.i8(0)  # isolation: read uncommitted

                def part(w, pid):
                    w.i32(pid)
                    w.i64(offsets[pid])
                    w.i32(self.fetch_max_bytes)

                def top(w, _):
                    w.string(topic)
                    w.array(pids, part)

                req.array([None], top)
                conn = pool.get(host, port)
                r = conn.call(API_FETCH, 4, req.done())
                on_progress(True)  # healthy session: caller resets backoff
                r.i32()  # throttle
                records: dict[int, list[tuple[int, bytes]]] = {}
                for _ in range(r.i32()):
                    r.string()
                    for _ in range(r.i32()):
                        pid = r.i32()
                        err = r.i16()
                        r.i64()  # high watermark
                        r.i64()  # last stable offset
                        r.array(lambda rr: (rr.i64(), rr.i64()))  # aborted
                        blob = r.bytes_() or b""
                        if err == ERR_OFFSET_OUT_OF_RANGE:
                            start = self._earliest_offset(conn, topic, pid)
                            cur = cursors[pid]
                            with cur.lock:
                                cur.fetch_offset = start
                                cur.committed = start
                            continue
                        if err != ERR_NONE:
                            raise RuntimeError(
                                f"kafka fetch {topic}/{pid}: error {err}"
                            )
                        records[pid] = decode_record_batches(blob)
                for pid, recs in records.items():
                    cur = cursors[pid]
                    for off, value in recs:
                        if off < offsets[pid]:
                            continue  # batch includes already-seen records
                        msg = Message(
                            value,
                            on_ack=self._acker(
                                coord, topic, pid, cur, off, generation,
                                member_id,
                            ),
                            on_nack=self._nacker(cur, off),
                        )
                        while not self._stop.is_set():
                            heartbeat_if_due()
                            try:
                                self._queues[topic].put(msg, timeout=0.5)
                                break
                            except queue.Full:
                                continue
                        with cur.lock:
                            cur.fetch_offset = off + 1

    def _acker(self, coord, topic, pid, cur, off, generation, member_id):
        def ack():
            with cur.lock:
                if off + 1 <= cur.committed:
                    return  # a later ack already covered this offset
                cur.committed = off + 1
            # The RPC is serialized per partition and always sends the
            # LATEST committed value (re-read under the lock), so two
            # concurrent acks can never land their commits out of order
            # and regress the broker-side offset.
            with cur.commit_lock:
                with cur.lock:
                    commit_val = cur.committed
                try:
                    self._commit(
                        coord, topic, pid, commit_val, generation, member_id
                    )
                except Exception:
                    logger.warning(
                        "kafka offset commit failed (will redeliver after "
                        "restart)", exc_info=True,
                    )
        return ack

    def _nacker(self, cur, off):
        def nack():
            with cur.lock:
                if cur.rewind_to is None or off < cur.rewind_to:
                    cur.rewind_to = off
        return nack
