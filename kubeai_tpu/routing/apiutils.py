"""Protocol-independent request parsing (reference: internal/apiutils/request.go).

Handles JSON bodies and multipart/form-data (Whisper uploads), splits
`model_adapter` names, rewrites the body when an adapter is requested
(engines expect the adapter name in the `model` field —
reference: apiutils/request.go:190-199), and computes the CHWBL prefix at
parse time from the first user-message text / prompt
(reference: api/openai/v1/chat_completions.go:525-543, completions.go:134-137).

Unknown-field preservation: bodies are parsed into plain dicts and
re-serialized — every unknown engine-specific field round-trips by
construction (the reference needs go-json-experiment Unknown fields for
this; dicts give it for free).
"""

from __future__ import annotations

import dataclasses
import json
import re
import uuid


class APIError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


@dataclasses.dataclass
class ParsedRequest:
    id: str
    body: bytes
    model: str
    adapter: str
    prefix: str
    selectors: dict[str, str]
    lb_strategy: str | None = None
    content_type: str = "application/json"

    @property
    def model_and_adapter(self) -> str:
        return f"{self.model}_{self.adapter}" if self.adapter else self.model


def split_model_adapter(s: str) -> tuple[str, str]:
    """'model_adapter' → (model, adapter) (reference: apiutils/model.go:19-36)."""
    model, _, adapter = s.partition("_")
    return model, adapter


def merge_model_adapter(model: str, adapter: str) -> str:
    return f"{model}_{adapter}" if adapter else model


def first_n_chars(s: str, n: int) -> str:
    """Rune-safe prefix (reference: apiutils/request.go:227-230). Python
    strings are code points, so the slice can never split a surrogate
    PAIR (json.loads combines valid pairs into one astral code point) —
    but a LONE surrogate that arrived via invalid \\uDxxx JSON escapes
    survives decoding and would crash every downstream utf-8 encode
    (the CHWBL ring hashes the prefix's bytes). Sanitize those to the
    replacement character so hashing is total AND deterministic — both
    sides of the router see the same bytes for the same wire input."""
    cut = s[:n]
    try:
        cut.encode("utf-8")
    except UnicodeEncodeError:
        cut = cut.encode("utf-8", "replace").decode("utf-8")
    return cut


def _message_text(content) -> str:
    """Extract text from an OpenAI message content (string or parts
    list). Empty parts are dropped before joining so ["a"] and
    ["a", ""] — the same rendered prompt — hash to the same prefix."""
    if isinstance(content, str):
        return content
    if isinstance(content, list):
        parts = [
            p.get("text", "") for p in content
            if isinstance(p, dict) and p.get("type") == "text"
        ]
        return " ".join(p for p in parts if p)
    return ""


def extract_prefix(path: str, body: dict, n: int) -> str:
    """First NON-EMPTY user-message text (chat) / first prompt
    (completions), first n chars — the CHWBL hash input. Messages whose
    content renders to "" (empty string, image-only part lists, null
    content) are skipped: they contribute no prompt bytes, so keying the
    route on them would scatter identical prompts across replicas."""
    if n <= 0:
        return ""
    if "chat/completions" in path:
        for msg in body.get("messages") or []:
            if isinstance(msg, dict) and msg.get("role") == "user":
                text = _message_text(msg.get("content"))
                if text:
                    return first_n_chars(text, n)
        return ""
    prompt = body.get("prompt", "")
    if isinstance(prompt, list):
        prompt = prompt[0] if prompt else ""
    if isinstance(prompt, str):
        return first_n_chars(prompt, n)
    return ""


def parse_label_selector(header_value: str | None) -> dict[str, str]:
    """`X-Label-Selector: k1=v1,k2=v2` multitenancy filter
    (reference: apiutils/request.go Selectors, openaiserver/models.go)."""
    if not header_value:
        return {}
    out = {}
    for part in header_value.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise APIError(400, f"invalid selector {part!r}")
        k, v = part.split("=", 1)
        out[k.strip()] = v.strip()
    return out


_MULTIPART_BOUNDARY_RE = re.compile(r'boundary="?([^";]+)"?')


def parse_request(
    body: bytes,
    path: str,
    headers: dict[str, str],
    prefix_char_length: int = 100,
) -> ParsedRequest:
    """(reference: internal/apiutils/request.go:64-165)"""
    content_type = headers.get("content-type", "application/json")
    selectors = parse_label_selector(headers.get("x-label-selector"))
    rid = str(uuid.uuid4())

    if content_type.startswith("multipart/form-data"):
        return _parse_multipart(body, content_type, rid, selectors)

    try:
        parsed = json.loads(body or b"{}")
    except json.JSONDecodeError as e:
        raise APIError(400, f"invalid JSON body: {e}")
    if not isinstance(parsed, dict):
        raise APIError(400, "request body must be a JSON object")
    model_full = parsed.get("model")
    if not model_full or not isinstance(model_full, str):
        raise APIError(400, "missing 'model' field in request body")

    model, adapter = split_model_adapter(model_full)
    if adapter:
        # Engines expect the adapter name in `model`
        # (reference: apiutils/request.go:190-199).
        parsed["model"] = adapter
        body = json.dumps(parsed).encode()

    prefix = extract_prefix(path, parsed, prefix_char_length)
    return ParsedRequest(
        id=rid,
        body=body,
        model=model,
        adapter=adapter,
        prefix=prefix,
        selectors=selectors,
        content_type=content_type,
    )


def _parse_multipart(
    body: bytes, content_type: str, rid: str, selectors: dict[str, str]
) -> ParsedRequest:
    """Extract (and strip) the `model` form field — the Whisper workaround
    (reference: apiutils/request.go:109-165 strips `model` so engines that
    reject unknown names still work; we keep parity by rewriting it to the
    adapter-less name)."""
    m = _MULTIPART_BOUNDARY_RE.search(content_type)
    if not m:
        raise APIError(400, "multipart body missing boundary")
    boundary = b"--" + m.group(1).encode()
    parts = body.split(boundary)
    model_full = None
    kept: list[bytes] = []
    for part in parts:
        if not part or part in (b"--", b"--\r\n", b"\r\n"):
            continue
        headers_block = part.split(b"\r\n\r\n", 1)[0]
        if b'name="model"' in headers_block:
            payload = part.split(b"\r\n\r\n", 1)[1]
            model_full = payload.strip(b"\r\n-").decode()
        else:
            kept.append(part)
    if not model_full:
        raise APIError(400, "missing 'model' form field")
    model, adapter = split_model_adapter(model_full)
    new_body = boundary + boundary.join(kept) + boundary + b"--\r\n"
    return ParsedRequest(
        id=rid,
        body=new_body,
        model=model,
        adapter=adapter,
        prefix="",
        selectors=selectors,
        content_type=content_type,
    )
