"""OpenAI-compatible HTTP front door (reference: internal/openaiserver).

Request tracing: every request gets/propagates an `X-Request-Id` (set on
the response and forwarded upstream), and completions emit a structured
access log line with route/model/status/duration — the lightweight stand-
in for the reference's otelhttp route tagging (reference:
internal/openaiserver/handler.go:28-31; its OTel *tracing* is commented
out upstream too, SURVEY.md §5.1).

Mux:
  POST /openai/v1/chat/completions      → proxy
  POST /openai/v1/completions           → proxy
  POST /openai/v1/embeddings            → proxy
  POST /openai/v1/audio/transcriptions  → proxy (multipart)
  GET  /openai/v1/models                → list Models by feature labels,
        expanding adapters into model ids (reference: openaiserver/models.go:13-109)

Plus operator endpoints:
  GET /metrics        → Prometheus exposition (the autoscaler's transport)
  GET /healthz
  GET /v1/fleet/state   → fleet telemetry snapshot (kubeai_tpu/fleet)
  GET /v1/fleet/history → ring buffer of recent snapshots
  GET /v1/fleet/plan    → latest capacity plan (kubeai_tpu/fleet/planner)
  GET /v1/usage?tenant= → per-tenant usage ledger summary

Tenant attribution: every proxied request is attributed to a tenant
(`X-Client-Id`, API-key principal digest, or `anonymous`) and its token
usage / stream time / shed count is folded into the UsageMeter.

Built on ThreadingHTTPServer: each request thread may block in the load
balancer's scale-from-zero wait without stalling others.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler

from kubeai_tpu.httpserver import DeepBacklogHTTPServer


access_log = logging.getLogger("kubeai.access")

from kubeai_tpu.crd.model import Model
from kubeai_tpu.metrics import DEFAULT_METRICS, Metrics
from kubeai_tpu.metrics import tracing
from kubeai_tpu.routing import apiutils
from kubeai_tpu.routing.modelclient import ModelClient
from kubeai_tpu.routing.proxy import ModelProxy

PROXY_PATHS = (
    "/openai/v1/chat/completions",
    "/openai/v1/completions",
    "/openai/v1/embeddings",
    "/openai/v1/audio/transcriptions",
)

FEATURE_FOR_PATH = {
    "/openai/v1/chat/completions": "TextGeneration",
    "/openai/v1/completions": "TextGeneration",
    "/openai/v1/embeddings": "TextEmbedding",
    "/openai/v1/audio/transcriptions": "SpeechToText",
}


def _models_payload(models: list[Model]) -> dict:
    data = []
    for m in models:
        entry = {
            "id": m.name,
            "object": "model",
            "created": 0,
            "owned_by": m.spec.owner or "kubeai",
            "features": list(m.spec.features),
        }
        data.append(entry)
        for a in m.spec.adapters:
            data.append(
                {
                    "id": apiutils.merge_model_adapter(m.name, a.name),
                    "object": "model",
                    "created": 0,
                    "owned_by": m.spec.owner or "kubeai",
                    "features": list(m.spec.features),
                }
            )
    return {"object": "list", "data": data}


class OpenAIServer:
    def __init__(
        self,
        proxy: ModelProxy,
        model_client: ModelClient,
        host: str = "127.0.0.1",
        port: int = 0,
        metrics: Metrics = DEFAULT_METRICS,
        fleet=None,
        usage=None,
        planner=None,
        governor=None,
    ):
        self.proxy = proxy
        self.model_client = model_client
        self.metrics = metrics
        # Fleet telemetry plane (kubeai_tpu/fleet): the aggregator backs
        # /v1/fleet/*, the usage meter attributes every request to a
        # tenant and backs /v1/usage, the capacity planner backs
        # /v1/fleet/plan, the tenant governor refuses over-limit work
        # before it queues. All optional (embedded tests).
        self.fleet = fleet
        self.usage = usage
        self.planner = planner
        self.governor = governor
        # SLO evaluator (kubeai_tpu/fleet/slo): backs GET /v1/slo with
        # the latest per-objective burn/budget verdicts and the flight
        # recorder's incident index. Wired by the manager when enabled.
        self.slo = None
        # Federation plane (kubeai_tpu/federation): the aggregator backs
        # GET /v1/federation/state, the router spills a chip-exhausted
        # model's requests to a peer cluster's door (cost-ranked, after
        # local admission so the gossiped budget stays global), the
        # planner reports failover state. Wired by the manager when
        # federation is enabled.
        self.federation = None
        self.federation_router = None
        self.federation_planner = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _headers_dict(self) -> dict[str, str]:
                return {k.lower(): v for k, v in self.headers.items()}

            def _respond_json(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0]
                if path in ("/openai/v1/models", "/v1/models"):
                    return self._handle_models()
                if path == "/metrics":
                    body = outer.metrics.registry.expose().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if path == "/healthz":
                    return self._respond_json(200, {"status": "ok"})
                if path in ("/v1/fleet/state", "/openai/v1/fleet/state"):
                    if outer.fleet is None:
                        return self._respond_json(
                            404,
                            {"error": {"message":
                                       "fleet telemetry not configured"}},
                        )
                    return self._respond_json(
                        200, outer.fleet.state_payload()
                    )
                if path in ("/v1/fleet/history", "/openai/v1/fleet/history"):
                    if outer.fleet is None:
                        return self._respond_json(
                            404,
                            {"error": {"message":
                                       "fleet telemetry not configured"}},
                        )
                    return self._respond_json(
                        200,
                        {
                            "object": "fleet.history",
                            "snapshots": outer.fleet.history(),
                        },
                    )
                if path in ("/v1/fleet/plan", "/openai/v1/fleet/plan"):
                    if outer.planner is None:
                        return self._respond_json(
                            404,
                            {"error": {"message":
                                       "capacity planner not configured"}},
                        )
                    return self._respond_json(
                        200, outer.planner.plan_payload()
                    )
                if path in ("/v1/slo", "/openai/v1/slo"):
                    if outer.slo is None:
                        return self._respond_json(
                            404,
                            {"error": {"message":
                                       "slo plane not configured"}},
                        )
                    return self._respond_json(
                        200, outer.slo.state_payload()
                    )
                if path in ("/v1/federation/state",
                            "/openai/v1/federation/state"):
                    if outer.federation is None:
                        return self._respond_json(
                            404,
                            {"error": {"message":
                                       "federation not configured"}},
                        )
                    payload = outer.federation.state_payload()
                    if outer.federation_planner is not None:
                        payload["failovers"] = (
                            outer.federation_planner.state_payload()
                        )
                    return self._respond_json(200, payload)
                if path in ("/v1/usage", "/openai/v1/usage"):
                    if outer.usage is None:
                        return self._respond_json(
                            404,
                            {"error": {"message":
                                       "usage metering not configured"}},
                        )
                    from urllib.parse import parse_qs, urlsplit

                    qs = parse_qs(urlsplit(self.path).query)
                    tenant = (qs.get("tenant") or [None])[0]
                    payload = outer.usage.summary(tenant)
                    if outer.governor is not None:
                        payload["tenancy"] = outer.governor.state_payload()
                    return self._respond_json(200, payload)
                self._respond_json(404, {"error": {"message": "not found"}})

            def _handle_models(self):
                try:
                    selectors = apiutils.parse_label_selector(
                        self._headers_dict().get("x-label-selector")
                    )
                except apiutils.APIError as e:
                    return self._respond_json(e.status, {"error": {"message": e.message}})
                models = outer.model_client.list_all_models(selectors)
                self._respond_json(200, _models_payload(models))

            def do_POST(self):
                path = self.path.split("?")[0]
                t0 = time.monotonic()
                headers = self._headers_dict()
                request_id = headers.get("x-request-id") or f"req-{uuid.uuid4().hex[:16]}"
                headers["x-request-id"] = request_id
                # Accept both /openai/v1/* (reference mux) and bare /v1/*.
                normalized = path
                if normalized.startswith("/v1/"):
                    normalized = "/openai" + normalized
                if normalized not in PROXY_PATHS:
                    return self._respond_json(
                        404, {"error": {"message": f"unknown path {path}"}}
                    )
                # Continue an incoming W3C trace or start one; downstream
                # (proxy → engine Pod) receives THIS span as parent.
                span_attrs = {
                    "http.route": normalized,
                    "request.id": request_id,
                }
                # Scheduling headers ride through to the engine (proxy
                # forwards them) and land on the span so a shed or slow
                # request's class/deadline is visible end to end.
                if headers.get("x-priority"):
                    span_attrs["request.priority"] = headers["x-priority"]
                if headers.get("x-deadline-ms"):
                    span_attrs["request.deadline_ms"] = headers["x-deadline-ms"]
                span = tracing.tracer().start_span(
                    f"POST {normalized}",
                    parent=tracing.parse_traceparent(
                        headers.get("traceparent")
                    ),
                    kind=tracing.KIND_SERVER,
                    attributes=span_attrs,
                )
                headers["traceparent"] = span.context.traceparent()
                # Normally the chunk generator ends the span when the body
                # finishes; this guard covers proxy.handle raising or the
                # client disconnecting before the body loop iterates —
                # otherwise the request never appears in traces. end() is
                # idempotent, so the streamed-body path is unaffected.
                try:
                    self._do_proxied_post(normalized, headers, span, request_id, t0)
                except BaseException as e:
                    span.end(error=str(e) or type(e).__name__)
                    raise
                finally:
                    span.end()

            def _do_proxied_post(self, normalized, headers, span, request_id, t0):
                length = int(self.headers.get("Content-Length", "0") or "0")
                body = self.rfile.read(length) if length else b""
                # Tenant admission (kubeai_tpu/fleet/tenancy) runs before
                # proxy.handle — i.e. before scale-from-zero, the load
                # balancer wait, or any engine queue sees the request. A
                # refusal answers 429 here for unary AND stream requests
                # alike (the stream never starts).
                if outer.governor is not None:
                    refusal = outer.governor.admit_http(headers, body)
                    if refusal is not None:
                        return self._refuse(
                            refusal, normalized, span, request_id, t0
                        )
                # Federation spillover sits between local admission and
                # the local proxy: the tenancy verdict is rendered here
                # (the gossiped budget is global, so spilling cannot
                # launder quota) but a chip-exhausted model's request
                # may be served by a cheaper peer cluster's door.
                result = None
                if outer.federation_router is not None:
                    from kubeai_tpu.federation.router import (
                        FederationRouter,
                    )

                    result = outer.federation_router.maybe_spill(
                        FederationRouter.model_of(body),
                        normalized[len("/openai"):],
                        body,
                        list(headers.items()),
                    )
                if result is None:
                    result = outer.proxy.handle(
                        # strip the /openai prefix when forwarding
                        normalized[len("/openai"):],
                        body,
                        headers,
                    )
                span.set_attribute("http.status_code", result.status)
                # End the span when the BODY finishes, not when headers
                # arrive: for SSE the generation streams long after
                # proxy.handle returns, and a mid-stream failure must
                # mark the root span, not leave it a clean few-ms OK.
                err = (
                    f"HTTP {result.status}" if result.status >= 500 else None
                )
                orig_chunks = result.chunks
                # Per-model lifecycle histograms measured at the SAME
                # boundaries the span attributes record, so traces and
                # histograms agree: TTFT at the first body chunk, e2e
                # duration when the body (streamed or unary) completes.
                model = getattr(result, "model", "") or "unknown"
                # Tenant usage attribution (kubeai_tpu/fleet/metering):
                # unary JSON answers carry an OpenAI `usage` block the
                # meter parses; SSE streams are counted by their engine
                # `token_ids` fields plus stream-open seconds.
                is_sse = any(
                    k.lower() == "content-type"
                    and v.lower().startswith("text/event-stream")
                    for k, v in result.headers
                )
                tenant = ""
                sse_acc = None
                json_buf = None
                if outer.usage is not None:
                    from kubeai_tpu.fleet.metering import tenant_of
                    from kubeai_tpu.routing.proxy import _SSEAccumulator

                    tenant = tenant_of(headers)
                    if is_sse:
                        sse_acc = _SSEAccumulator()
                    elif result.status == 200:
                        json_buf = bytearray()

                def _meter(duration: float) -> None:
                    if outer.usage is None:
                        return
                    usage_block = None
                    completion = None
                    if sse_acc is not None:
                        completion = len(sse_acc.token_ids)
                    elif json_buf:
                        try:
                            usage_block = json.loads(
                                bytes(json_buf)
                            ).get("usage")
                        except (json.JSONDecodeError, AttributeError):
                            usage_block = None
                    outer.usage.record_response(
                        tenant, model, result.status,
                        usage=usage_block,
                        stream_seconds=duration if is_sse else 0.0,
                        completion_tokens=completion,
                    )

                def _finish(error=None):
                    duration = time.monotonic() - t0
                    span.set_attribute("http.duration_s", duration)
                    outer.metrics.request_duration.observe(
                        duration, model=model, exemplar=request_id
                    )
                    _meter(duration)
                    access_log.info(
                        "route=%s request_id=%s model=%s status=%d "
                        "duration_ms=%.1f",
                        normalized, request_id, model, result.status,
                        duration * 1e3,
                    )
                    span.end(error=error)

                def traced_chunks(orig=orig_chunks, span=span, err=err):
                    first = True
                    try:
                        for chunk in orig:
                            if first and chunk:
                                first = False
                                ttft = time.monotonic() - t0
                                span.set_attribute("http.ttft_s", ttft)
                                outer.metrics.request_ttft.observe(
                                    ttft, model=model,
                                    exemplar=request_id,
                                )
                            if sse_acc is not None:
                                sse_acc.feed(chunk)
                            elif (
                                json_buf is not None
                                and len(json_buf) < (1 << 22)
                            ):
                                json_buf.extend(chunk)
                            yield chunk
                    except BaseException as e:
                        _finish(error=str(e) or type(e).__name__)
                        raise
                    else:
                        _finish(error=err)

                result.chunks = traced_chunks()
                self.send_response(result.status)
                self.send_header("X-Request-Id", request_id)
                has_length = any(
                    k.lower() == "content-length" for k, _ in result.headers
                )
                for k, v in result.headers:
                    self.send_header(k, v)
                if not has_length:
                    self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                if has_length:
                    for chunk in result.chunks:
                        self.wfile.write(chunk)
                else:
                    for chunk in result.chunks:
                        if chunk:
                            self.wfile.write(
                                f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                            )
                    self.wfile.write(b"0\r\n\r\n")

            def _refuse(self, refusal, normalized, span, request_id, t0):
                from kubeai_tpu.utils import retryafter

                payload = {
                    "error": {
                        "message": refusal.message,
                        "type": "rate_limit_exceeded",
                        "code": refusal.reason,
                    },
                    "retry_after_s": round(refusal.retry_after_s, 3),
                }
                body = json.dumps(payload).encode()
                # Exactly one shed lands in the ledger per refusal — the
                # normal _meter path never runs for a refused request.
                # Record BEFORE writing: once the body is on the wire the
                # client may act on it, and the ledger must already agree.
                if outer.usage is not None:
                    outer.usage.record_response(
                        refusal.tenant, refusal.model or "unknown",
                        refusal.status,
                    )
                self.send_response(refusal.status)
                self.send_header("X-Request-Id", request_id)
                self.send_header(
                    "Retry-After",
                    retryafter.format_header(refusal.retry_after_s),
                )
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                duration = time.monotonic() - t0
                span.set_attribute("http.status_code", refusal.status)
                span.set_attribute("door.refusal", refusal.reason)
                span.set_attribute("http.duration_s", duration)
                access_log.info(
                    "route=%s request_id=%s model=%s status=%d "
                    "duration_ms=%.1f shed=%s",
                    normalized, request_id, refusal.model or "unknown",
                    refusal.status, duration * 1e3, refusal.reason,
                )

        self.httpd = DeepBacklogHTTPServer((host, port), Handler)
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"{host}:{port}"

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        # shutdown() handshakes with serve_forever; calling it on a
        # never-started (or already-stopped) server waits forever.
        if self._thread is not None:
            self.httpd.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self.httpd.server_close()
