"""Passive endpoint health tracking + circuit breaking for the load
balancer (no reference analog — the reference KubeAI leans entirely on
Kubernetes readiness probes, internal/modelproxy/handler.go retries
blind; here the proxy reports every attempt outcome and the breaker
ejects endpoints faster than kubelet can notice).

State machine per endpoint:

    CLOSED ──(consecutive failures OR failure rate over window)──▶ OPEN
    OPEN ──(open_seconds backoff elapsed)──▶ HALF_OPEN
    HALF_OPEN ──(single probe succeeds)──▶ CLOSED
    HALF_OPEN ──(probe fails)──▶ OPEN (backoff restarts)

Half-open admits exactly ONE probe request: availability requires the
endpoint to have zero requests in flight, so while the probe is on the
wire no second request can be routed there — singularity falls out of
the in-flight accounting instead of a separate token that could leak.

Outcome vocabulary (what the proxy reports):

    success        2xx/4xx response (the endpoint answered coherently)
    connect_error  TCP connect refused/reset/unreachable
    timeout        connect or response-header deadline exceeded
    5xx            HTTP 500/502/503/504 from the engine
    midstream      connection died partway through a streamed body
    shed           HTTP 429 flow control — NOT a breaker failure (the
                   endpoint is healthy, just busy)

All time flows through an injectable clock so the fault-injection sim
and the unit tests drive the breaker deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

OUTCOME_SUCCESS = "success"
OUTCOME_CONNECT_ERROR = "connect_error"
OUTCOME_TIMEOUT = "timeout"
OUTCOME_5XX = "5xx"
OUTCOME_MIDSTREAM = "midstream"
OUTCOME_SHED = "shed"

# Outcomes that count against the breaker. 429 shed is flow control from
# a live engine — tripping on it would eject healthy-but-busy replicas
# and amplify the overload onto the survivors.
FAILURE_OUTCOMES = frozenset(
    (OUTCOME_CONNECT_ERROR, OUTCOME_TIMEOUT, OUTCOME_5XX, OUTCOME_MIDSTREAM)
)


@dataclasses.dataclass(frozen=True)
class BreakerPolicy:
    """Thresholds for one endpoint's breaker. Defaults come from the
    system config `resilience:` block; the Model CRD's
    `loadBalancing.circuitBreaker` overrides per model."""

    # Sliding window of most-recent attempt outcomes considered by the
    # failure-rate rule.
    window: int = 20
    # Trip after this many consecutive failures (0 disables the rule).
    consecutive_failures: int = 3
    # Trip when at least min_samples outcomes are in the window and the
    # failure fraction reaches this rate (>= 1.0 disables the rule).
    failure_rate: float = 0.5
    min_samples: int = 5
    # Seconds an open circuit waits before admitting a half-open probe.
    open_seconds: float = 10.0

    def validate(self) -> None:
        if self.window < 1:
            raise ValueError("breaker window must be >= 1")
        if self.consecutive_failures < 0:
            raise ValueError("breaker consecutiveFailures must be >= 0")
        if not 0.0 < self.failure_rate:
            raise ValueError("breaker failureRate must be > 0")
        if self.min_samples < 1:
            raise ValueError("breaker minSamples must be >= 1")
        if self.open_seconds <= 0:
            raise ValueError("breaker openSeconds must be > 0")


class EndpointHealth:
    """One endpoint's outcome window + breaker state. NOT thread-safe on
    its own — the owning Group serializes access under its condition
    lock (the same lock that guards in-flight accounting, which the
    half-open probe rule reads)."""

    __slots__ = (
        "policy", "clock", "state", "_window", "_consecutive",
        "_opened_at", "ejections", "last_error",
    )

    def __init__(
        self,
        policy: BreakerPolicy | None = None,
        clock=time.monotonic,
    ):
        self.policy = policy or BreakerPolicy()
        self.clock = clock
        # state/_opened_at are CRDT-backed when the door is sharded:
        # open/close transitions publish into the gossiped LWW breaker
        # map and peers adopt them (adopt_open / remote_close below).
        self.state = STATE_CLOSED
        self._window: deque[bool] = deque(maxlen=self.policy.window)  # local-state: this shard's own attempt outcomes; peers see only the verdict
        self._consecutive = 0  # local-state: derived from the local outcome window
        self._opened_at = 0.0
        self.ejections = 0  # local-state: per-shard observability tally
        self.last_error = ""  # local-state: per-shard observability detail

    def set_policy(self, policy: BreakerPolicy) -> None:
        if policy == self.policy:
            return
        self.policy = policy
        # Re-window without losing recent history.
        self._window = deque(self._window, maxlen=policy.window)

    def available(self, in_flight: int = 0) -> bool:
        """May a request be routed here right now? Open circuits whose
        backoff elapsed count as available ONLY while nothing is in
        flight — that one admitted request IS the half-open probe."""
        if self.state == STATE_CLOSED:
            return True
        if self.state == STATE_OPEN:
            if self.clock() - self._opened_at < self.policy.open_seconds:
                return False
            return in_flight == 0
        # HALF_OPEN: the probe is singular.
        return in_flight == 0

    def on_pick(self) -> None:
        """Called when the Group routes a request here. An open circuit
        past its backoff transitions to half-open — the caller verified
        availability (and therefore probe singularity) first."""
        if self.state == STATE_OPEN:
            self.state = STATE_HALF_OPEN

    def record(self, outcome: str, error: str = "") -> bool:
        """Fold one attempt outcome in. Returns True when the state
        CHANGED (the caller refreshes metrics / wakes waiters)."""
        if outcome == OUTCOME_SHED:
            return False  # flow control; no breaker signal either way
        failed = outcome in FAILURE_OUTCOMES
        self._window.append(failed)
        if failed:
            self._consecutive += 1
            self.last_error = error or outcome
        else:
            self._consecutive = 0
        if self.state == STATE_HALF_OPEN:
            # The probe's outcome decides re-admission outright.
            if failed:
                self._trip()
            else:
                self._reset()
            return True
        if self.state == STATE_CLOSED and failed and self._should_trip():
            self._trip()
            return True
        return False

    def _should_trip(self) -> bool:
        p = self.policy
        if p.consecutive_failures and self._consecutive >= p.consecutive_failures:
            return True
        if p.failure_rate < 1.0 and len(self._window) >= p.min_samples:
            rate = sum(self._window) / len(self._window)
            if rate >= p.failure_rate:
                return True
        return False

    def _trip(self) -> None:
        self.state = STATE_OPEN
        self._opened_at = self.clock()
        self.ejections += 1

    def _reset(self) -> None:
        self.state = STATE_CLOSED
        self._consecutive = 0
        self._window.clear()
        self.last_error = ""

    # -- gossip adoption (sharded front door) ----------------------------

    @property
    def opened_at(self) -> float:
        """The open stamp — keys the half-open probe-election window in
        the gossiped state plane."""
        return self._opened_at

    def adopt_open(self, opened_at: float, error: str = "") -> bool:
        """Adopt a peer door shard's open verdict: stop sending before
        this shard pays the failure tax itself. The peer's opened_at is
        kept so every shard's backoff (and therefore the probe-election
        window key) lines up. Not counted as a local ejection — this
        shard observed no failure. Returns True when state changed."""
        if self.state == STATE_OPEN and self._opened_at >= opened_at:
            return False
        self.state = STATE_OPEN
        self._opened_at = float(opened_at)
        self._consecutive = 0
        if error:
            self.last_error = error
        return True

    def remote_close(self) -> bool:
        """Adopt a peer shard's close verdict (its probe succeeded).
        Returns True when state changed."""
        if self.state == STATE_CLOSED:
            return False
        self._reset()
        return True

    def snapshot(self) -> dict:
        return {
            "state": self.state,
            "ejections": self.ejections,
            "consecutive_failures": self._consecutive,
            "window_failure_rate": (
                sum(self._window) / len(self._window) if self._window else 0.0
            ),
            "last_error": self.last_error,
        }
