"""RabbitMQ messenger driver: AMQP 0-9-1 on the wire, zero dependencies.

The reference registers gocloud.dev's rabbitpubsub driver for rabbit://
streams (reference: internal/manager/run.go:47-52). This driver speaks
AMQP 0-9-1 directly over TCP:

  handshake        protocol header → Connection.Start/StartOk (PLAIN) →
                   Tune/TuneOk → Open/OpenOk
  per-queue        its own channel: Queue.Declare (durable), then
                   Basic.Consume; publishes ride channel 1 through the
                   default exchange (routing key = queue name)
  delivery         Basic.Deliver + content header + body frames →
                   bounded local queue (flow control: the broker keeps
                   the backlog; unacked messages redeliver on nack or
                   connection loss)
  ack/nack         Basic.Ack / Basic.Nack(requeue=true) — gocloud
                   rabbitpubsub parity

The reader thread reconnects with exponential backoff and re-declares +
re-consumes every queue (the reference's subscription-restart behavior,
internal/messenger/messenger.go:98-127).

URL forms (config `messaging.streams`):
  rabbit://host:5672/queue-name     (gocloud scheme)
  amqp://host:5672/queue-name
"""

from __future__ import annotations

import logging
import queue
import socket
import struct
import threading
import time
import urllib.parse

from kubeai_tpu.routing.brokers import RESTARTS_LOG_EVERY, _backoff
from kubeai_tpu.routing.messenger import Message

logger = logging.getLogger(__name__)

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE

# (class, method) ids used.
CONN_START = (10, 10)
CONN_START_OK = (10, 11)
CONN_TUNE = (10, 30)
CONN_TUNE_OK = (10, 31)
CONN_OPEN = (10, 40)
CONN_OPEN_OK = (10, 41)
CONN_CLOSE = (10, 50)
CONN_CLOSE_OK = (10, 51)
CHAN_OPEN = (20, 10)
CHAN_OPEN_OK = (20, 11)
CHAN_CLOSE = (20, 40)
CHAN_CLOSE_OK = (20, 41)
BASIC_QOS = (60, 10)
BASIC_QOS_OK = (60, 11)
QUEUE_DECLARE = (50, 10)
QUEUE_DECLARE_OK = (50, 11)
BASIC_CONSUME = (60, 20)
BASIC_CONSUME_OK = (60, 21)
BASIC_PUBLISH = (60, 40)
BASIC_DELIVER = (60, 60)
BASIC_ACK = (60, 80)
BASIC_NACK = (60, 120)


def short_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">B", len(b)) + b


def long_str(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def read_short_str(buf: bytes, pos: int) -> tuple[str, int]:
    n = buf[pos]
    return buf[pos + 1:pos + 1 + n].decode(), pos + 1 + n


def read_long_str(buf: bytes, pos: int) -> tuple[bytes, int]:
    (n,) = struct.unpack_from(">I", buf, pos)
    return buf[pos + 4:pos + 4 + n], pos + 4 + n


def method_frame(channel: int, cls: int, meth: int, args: bytes) -> bytes:
    payload = struct.pack(">HH", cls, meth) + args
    return (
        struct.pack(">BHI", FRAME_METHOD, channel, len(payload))
        + payload
        + bytes([FRAME_END])
    )


def content_frames(channel: int, body: bytes) -> bytes:
    header = struct.pack(">HHQH", 60, 0, len(body), 0)  # no properties
    out = (
        struct.pack(">BHI", FRAME_HEADER, channel, len(header))
        + header
        + bytes([FRAME_END])
    )
    if body:
        out += (
            struct.pack(">BHI", FRAME_BODY, channel, len(body))
            + body
            + bytes([FRAME_END])
        )
    return out


class AMQPBroker:
    """Broker-seam driver (publish/receive/close) over AMQP 0-9-1."""

    def __init__(
        self,
        host: str,
        port: int = 5672,
        username: str = "guest",
        password: str = "guest",
        vhost: str = "/",
        timeout_s: float = 30.0,
    ):
        self.host, self.port = host, port
        self.username, self.password = username, password
        self.vhost = vhost
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._queues: dict[str, queue.Queue] = {}  # queue name -> local q
        self._channels: dict[int, str] = {}  # channel -> consumed queue
        self._next_channel = 2  # 1 is the publish channel
        self._declared: set[str] = set()
        self._pub_channel_open = False  # channel 1, (re)opened per conn
        # Connection generation: bumps on reconnect so ack/nack closures
        # from deliveries of a DEAD connection become no-ops (their
        # delivery tags are meaningless on the new connection; a stale
        # Basic.Ack would draw Channel.Close 406 from a real broker).
        self._gen = 0
        # Per-channel prefetch == the local queue bound, so the broker
        # never pushes more than the local queue can hold and the reader
        # thread's put can't stall the whole connection.
        self.prefetch = 64
        self._stop = threading.Event()
        self._reader: threading.Thread | None = None
        # Pending synchronous replies: (channel, cls, meth) -> Event+args.
        self._replies: dict[tuple[int, int, int], bytes] = {}
        self._reply_cond = threading.Condition(self._lock)

    @staticmethod
    def queue_of(url: str) -> str:
        if "://" in url:
            return urllib.parse.urlparse(url).path.strip("/") or "default"
        return url

    # -- connection -------------------------------------------------------------

    def _send(self, data: bytes) -> None:
        with self._wlock:
            sock = self._sock
            if sock is None:
                raise ConnectionError("AMQP not connected")
            sock.sendall(data)

    def _call(self, channel: int, cls: int, meth: int, args: bytes,
              expect: tuple[int, int]) -> bytes:
        """Send a synchronous method and wait for its reply method."""
        key = (channel, *expect)
        with self._lock:
            self._replies.pop(key, None)
        self._send(method_frame(channel, cls, meth, args))
        end = time.monotonic() + self.timeout_s
        with self._reply_cond:
            # Absolute deadline: notify_all fires for EVERY reply on any
            # channel, and restarting the window per wakeup would let a
            # lost reply block far past timeout_s.
            while key not in self._replies:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError(
                        f"AMQP timeout waiting for {expect}"
                    )
                self._reply_cond.wait(timeout=remaining)
        with self._lock:
            return self._replies.pop(key)

    def _connect_locked(self) -> None:
        sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout_s
        )
        # Connect timeout only — as a read timeout it would make every
        # idle period look like a dead connection and churn reconnects.
        sock.settimeout(None)
        sock.sendall(b"AMQP\x00\x00\x09\x01")
        self._sock = sock
        if self._reader is None or not self._reader.is_alive():
            self._reader = threading.Thread(
                target=self._read_loop, daemon=True
            )
            self._reader.start()

    def _handshake(self) -> None:
        """Runs in the reader thread after Connection.Start arrives."""
        plain = b"\x00" + self.username.encode() + b"\x00" + self.password.encode()
        args = (
            b"\x00\x00\x00\x00"  # empty client-properties table
            + short_str("PLAIN")
            + long_str(plain)
            + short_str("en_US")
        )
        self._send(method_frame(0, *CONN_START_OK, args))

    def _ensure_connected(self) -> None:
        with self._lock:
            if self._sock is None:
                self._connect_locked()
        # Wait for Connection.OpenOk (reader completes the handshake).
        end = time.monotonic() + self.timeout_s
        with self._reply_cond:
            while (0, *CONN_OPEN_OK) not in self._replies:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    raise ConnectionError("AMQP handshake timed out")
                self._reply_cond.wait(timeout=remaining)

    def _ensure_channel(self, channel: int) -> None:
        self._call(channel, *CHAN_OPEN, short_str(""), CHAN_OPEN_OK)

    def _declare(self, channel: int, qname: str) -> None:
        args = (
            struct.pack(">H", 0)  # ticket
            + short_str(qname)
            + bytes([0b00000010])  # durable
            + b"\x00\x00\x00\x00"  # empty arguments table
        )
        self._call(channel, *QUEUE_DECLARE, args, QUEUE_DECLARE_OK)

    # -- Broker interface -------------------------------------------------------

    def publish(self, topic_url: str, body: bytes) -> None:
        qname = self.queue_of(topic_url)
        self._ensure_connected()
        with self._lock:
            chan_open = self._pub_channel_open
        if not chan_open:
            # A real broker treats any method on an unopened channel as
            # a protocol violation — channel 1 must Channel.Open per
            # connection (the flag resets on reconnect).
            self._ensure_channel(1)
            with self._lock:
                self._pub_channel_open = True
        with self._lock:
            declared = qname in self._declared
        if not declared:
            self._declare(1, qname)
            with self._lock:
                self._declared.add(qname)
        args = (
            struct.pack(">H", 0)
            + short_str("")  # default exchange
            + short_str(qname)  # routing key = queue
            + bytes([0])  # mandatory/immediate off
        )
        self._send(
            method_frame(1, *BASIC_PUBLISH, args) + content_frames(1, body)
        )

    def receive(self, sub_url: str, timeout: float) -> Message | None:
        qname = self.queue_of(sub_url)
        with self._lock:
            known = qname in self._queues
            if not known:
                self._queues[qname] = queue.Queue(maxsize=self.prefetch)
        if not known:
            try:
                self._ensure_connected()
                self._start_consumer(qname)
            except Exception:
                # Setup failed: forget the queue so the NEXT receive
                # retries the whole setup — leaving it registered would
                # poll an empty local queue forever (a silently dead
                # subscription).
                with self._lock:
                    self._queues.pop(qname, None)
                    for ch, q in list(self._channels.items()):
                        if q == qname:
                            del self._channels[ch]
                raise
        try:
            return self._queues[qname].get(timeout=timeout)
        except queue.Empty:
            return None

    def _start_consumer(self, qname: str) -> None:
        with self._lock:
            channel = self._next_channel
            self._next_channel += 1
            self._channels[channel] = qname
        self._ensure_channel(channel)
        self._declare(channel, qname)
        # Prefetch bounds the broker's pushes to what the local queue
        # can hold, so a slow consumer can't stall the reader thread.
        self._call(
            channel, *BASIC_QOS,
            struct.pack(">IHB", 0, self.prefetch, 0), BASIC_QOS_OK,
        )
        args = (
            struct.pack(">H", 0)
            + short_str(qname)
            + short_str(f"ctag-{channel}")
            + bytes([0])  # no-local/no-ack/exclusive/no-wait off
            + b"\x00\x00\x00\x00"
        )
        self._call(channel, *BASIC_CONSUME, args, BASIC_CONSUME_OK)

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)  # wake the blocked reader
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    # -- reader -----------------------------------------------------------------

    def _read_frame(self, sock) -> tuple[int, int, bytes]:
        hdr = self._read_n(sock, 7)
        ftype, channel, size = struct.unpack(">BHI", hdr)
        payload = self._read_n(sock, size)
        end = self._read_n(sock, 1)
        if end[0] != FRAME_END:
            raise ConnectionError("AMQP frame desync")
        return ftype, channel, payload

    @staticmethod
    def _read_n(sock, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("AMQP connection closed")
            out += chunk
        return out

    def _read_loop(self) -> None:
        restarts = 0
        pending: dict[int, dict] = {}  # channel -> partial delivery
        while not self._stop.is_set():
            sock = self._sock
            if sock is None:
                if self._stop.wait(0.2):
                    return
                continue
            try:
                ftype, channel, payload = self._read_frame(sock)
                restarts = 0
                if ftype == FRAME_HEARTBEAT:
                    self._send(
                        struct.pack(">BHI", FRAME_HEARTBEAT, 0, 0)
                        + bytes([FRAME_END])
                    )
                    continue
                if ftype == FRAME_METHOD:
                    cls, meth = struct.unpack_from(">HH", payload, 0)
                    args = payload[4:]
                    self._on_method(channel, cls, meth, args, pending)
                elif ftype == FRAME_HEADER:
                    d = pending.get(channel)
                    if d is not None:
                        (d["size"],) = struct.unpack_from(">Q", payload, 4)
                        d["body"] = b""
                        if d["size"] == 0:
                            self._complete_delivery(channel, pending)
                elif ftype == FRAME_BODY:
                    d = pending.get(channel)
                    if d is not None:
                        d["body"] += payload
                        if len(d["body"]) >= d["size"]:
                            self._complete_delivery(channel, pending)
            except Exception as e:
                if self._stop.is_set():
                    return
                restarts += 1
                log = (
                    logger.error
                    if restarts % RESTARTS_LOG_EVERY == 0
                    else logger.warning
                )
                log("AMQP connection lost (reconnect %d): %s", restarts, e)
                with self._lock:
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    self._replies.clear()
                    self._pub_channel_open = False
                    # Old deliveries' ack/nack closures become no-ops:
                    # their tags belong to the dead connection.
                    self._gen += 1
                pending.clear()
                if self._stop.wait(_backoff(restarts)):
                    return
                try:
                    with self._lock:
                        # A publisher's _ensure_connected may have
                        # reconnected during the backoff — opening a
                        # second connection here would leak its socket.
                        if self._sock is None:
                            self._connect_locked()
                    # Redo handshake + consumers from this (reader)
                    # thread's perspective: the new reader loop instance
                    # handles Start; we re-register consumers once
                    # OpenOk lands (driven by _on_method below).
                except Exception:
                    with self._lock:
                        self._sock = None

    def _on_method(
        self, channel: int, cls: int, meth: int, args: bytes, pending
    ) -> None:
        if (cls, meth) == CONN_START:
            self._handshake()
            return
        if (cls, meth) == CONN_TUNE:
            self._send(
                method_frame(
                    0, *CONN_TUNE_OK,
                    struct.pack(">HIH", 0, 0, 0),  # no limits, no heartbeat
                )
            )
            self._send(
                method_frame(
                    0, *CONN_OPEN,
                    short_str(self.vhost) + short_str("") + bytes([0]),
                )
            )
            return
        if (cls, meth) == CONN_OPEN_OK:
            with self._reply_cond:
                self._replies[(0, *CONN_OPEN_OK)] = args
                self._reply_cond.notify_all()
            # Reconnect path: re-open channels + re-consume every queue.
            with self._lock:
                consumers = dict(self._channels)
                self._declared.clear()
            for ch, qname in consumers.items():
                try:
                    self._reconsume(ch, qname)
                except Exception:
                    logger.warning(
                        "AMQP re-consume %s failed", qname, exc_info=True
                    )
            return
        if (cls, meth) == BASIC_DELIVER:
            pos = 0
            _ctag, pos = read_short_str(args, pos)
            (delivery_tag,) = struct.unpack_from(">Q", args, pos)
            pending[channel] = {"tag": delivery_tag, "size": None, "body": b""}
            return
        if (cls, meth) == CONN_CLOSE:
            self._send(method_frame(0, *CONN_CLOSE_OK, b""))
            raise ConnectionError("server closed the AMQP connection")
        if (cls, meth) == CHAN_CLOSE:
            # Channel-level error (e.g. 406 on a stale ack): answer
            # CloseOk, then treat it as a connection restart — the
            # reconnect path re-opens every channel and re-consumes,
            # which is simpler and safer than per-channel repair.
            self._send(method_frame(channel, *CHAN_CLOSE_OK, b""))
            raise ConnectionError(
                f"server closed AMQP channel {channel}: {args[:64]!r}"
            )
        # Synchronous replies (ChannelOpenOk, DeclareOk, ConsumeOk, ...).
        with self._reply_cond:
            self._replies[(channel, cls, meth)] = args
            self._reply_cond.notify_all()

    def _reconsume(self, channel: int, qname: str) -> None:
        """Re-establish one consumer on an existing channel number after
        a reconnect (runs inline in the reader thread — uses the async
        sends only, waiting via the replies map would deadlock the
        reader, so fire-and-forget: the server's -Ok methods land in the
        replies map and are ignored)."""
        self._send(method_frame(channel, *CHAN_OPEN, short_str("")))
        self._send(
            method_frame(
                channel, *QUEUE_DECLARE,
                struct.pack(">H", 0) + short_str(qname)
                + bytes([0b00000010]) + b"\x00\x00\x00\x00",
            )
        )
        self._send(
            method_frame(
                channel, *BASIC_QOS,
                struct.pack(">IHB", 0, self.prefetch, 0),
            )
        )
        self._send(
            method_frame(
                channel, *BASIC_CONSUME,
                struct.pack(">H", 0) + short_str(qname)
                + short_str(f"ctag-{channel}") + bytes([0])
                + b"\x00\x00\x00\x00",
            )
        )

    def _complete_delivery(self, channel: int, pending: dict) -> None:
        d = pending.pop(channel)
        qname = self._channels.get(channel)
        if qname is None:
            return
        tag = d["tag"]
        gen = self._gen
        msg = Message(
            bytes(d["body"]),
            on_ack=lambda: self._ack(channel, tag, gen),
            on_nack=lambda: self._nack(channel, tag, gen),
        )
        q = self._queues.get(qname)
        if q is None:
            return
        while not self._stop.is_set():
            try:
                q.put(msg, timeout=1.0)
                return
            except queue.Full:
                continue

    def _ack(self, channel: int, tag: int, gen: int) -> None:
        if gen != self._gen:
            return  # stale tag from a dead connection; it redelivers
        try:
            self._send(
                method_frame(
                    channel, *BASIC_ACK, struct.pack(">QB", tag, 0)
                )
            )
        except Exception:
            logger.warning("AMQP ack failed (will redeliver)", exc_info=True)

    def _nack(self, channel: int, tag: int, gen: int) -> None:
        if gen != self._gen:
            return  # connection died: the broker requeued it already
        try:
            # requeue=true -> immediate redelivery (gocloud parity).
            self._send(
                method_frame(
                    channel, *BASIC_NACK,
                    struct.pack(">QB", tag, 0b00000010),
                )
            )
        except Exception:
            logger.warning("AMQP nack failed", exc_info=True)
