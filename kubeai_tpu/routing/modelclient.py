"""Model lookup and scale operations (reference: internal/modelclient).

Scale-from-zero is the signature move: the proxy calls
`scale_at_least_one_replica` before waiting on the load balancer
(reference: internal/modelclient/scale.go:14-39, modelproxy/handler.go:84).
"""

from __future__ import annotations

import threading

from kubeai_tpu.crd.model import Model
from kubeai_tpu.operator.k8s.store import Conflict, KubeStore, NotFound


class ModelNotFound(Exception):
    pass


class AdapterNotFound(Exception):
    pass


class ModelClient:
    def __init__(self, store: KubeStore, namespace: str = "default"):
        self.store = store
        self.namespace = namespace
        self._scale_lock = threading.Lock()
        # model -> consecutive scale-down requests (hysteresis;
        # reference: modelclient/scale.go:43-100).
        self._consecutive_scale_downs: dict[str, int] = {}
        # Actuation governor (operator/governor): when wired by the
        # manager, every scale-DOWN about to be written is fenced on
        # lease validity and gated on telemetry coverage (scale-ups and
        # scale-from-zero stay ungated — any replica may wake a model).
        self.governor = None

    def lookup_model(
        self, name: str, adapter: str = "", selectors: dict[str, str] | None = None
    ) -> Model:
        """(reference: internal/modelclient/client.go:27-64)"""
        try:
            obj = self.store.get("Model", self.namespace, name)
        except NotFound:
            raise ModelNotFound(name)
        model = Model.from_dict(obj)
        for k, v in (selectors or {}).items():
            if model.labels.get(k) != v:
                raise ModelNotFound(name)  # selector mismatch = invisible
        if adapter and not any(a.name == adapter for a in model.spec.adapters):
            raise AdapterNotFound(f"{name}_{adapter}")
        return model

    def list_all_models(self, selectors: dict[str, str] | None = None) -> list[Model]:
        return [
            Model.from_dict(o)
            for o in self.store.list("Model", self.namespace, selectors or None)
        ]

    def scale_at_least_one_replica(self, name: str) -> None:
        """0 → 1 via the scale subresource (reference: scale.go:14-39)."""
        with self._scale_lock:
            for _ in range(3):
                try:
                    obj = self.store.get("Model", self.namespace, name)
                except NotFound:
                    raise ModelNotFound(name)
                spec = obj.get("spec", {})
                if spec.get("autoscalingDisabled"):
                    return
                if (spec.get("replicas") or 0) > 0:
                    return
                # ungoverned: scale-from-zero wake-up — adds capacity,
                # any replica may issue it (check_actuation_paths.py)
                spec["replicas"] = 1
                try:
                    self.store.update(obj)
                    return
                except Conflict:
                    continue

    def scale(self, name: str, replicas: int) -> int:
        """Bounded scale with consecutive-scale-down hysteresis
        (reference: scale.go:43-100). Returns the replica count in effect
        AFTER the call (current when hysteresis suppressed the change) —
        the autoscaler's decision log records computed vs. applied."""
        with self._scale_lock:
            try:
                obj = self.store.get("Model", self.namespace, name)
            except NotFound:
                raise ModelNotFound(name)
            spec = obj.get("spec", {})
            mn = int(spec.get("minReplicas", 0) or 0)
            mx = spec.get("maxReplicas")
            replicas = max(replicas, mn)
            if mx is not None:
                replicas = min(replicas, mx)
            current = spec.get("replicas") or 0
            if replicas == current:
                self._consecutive_scale_downs[name] = 0
                return current
            if replicas < current:
                model = Model.from_dict(obj)
                required = self._required_consecutive(model)
                self._consecutive_scale_downs[name] = (
                    self._consecutive_scale_downs.get(name, 0) + 1
                )
                if self._consecutive_scale_downs[name] < required:
                    return current
                if self.governor is not None:
                    replicas, _denied = self.governor.govern_scale(
                        name, current, replicas
                    )
                    if replicas >= current:
                        return current  # held (stale telemetry / fence)
            self._consecutive_scale_downs[name] = 0
            # governed: scale-downs passed ActuationGovernor.govern_scale
            spec["replicas"] = replicas
            try:
                self.store.update(obj)
            except Conflict:
                return current  # next tick retries
            if self.governor is not None:
                self.governor.note_applied(name, replicas=replicas)
            return replicas

    def scale_role(self, name: str, role: str, replicas: int) -> int:
        """Per-role scaling for disaggregated pod groups: writes the
        role's replica annotation (the controller's _plan_disagg reads
        it), clamped to the CRD disaggregation bounds, with the same
        consecutive-scale-down hysteresis as unified scaling. Returns
        the count in effect after the call."""
        from kubeai_tpu.crd import metadata as md
        from kubeai_tpu.crd.model import disagg_role_replicas

        key = f"{name}/{role}"
        with self._scale_lock:
            try:
                obj = self.store.get("Model", self.namespace, name)
            except NotFound:
                raise ModelNotFound(name)
            model = Model.from_dict(obj)
            rs = model.spec.disaggregation.role(role)
            replicas = max(replicas, rs.min_replicas, 1)
            if rs.max_replicas is not None:
                replicas = min(replicas, rs.max_replicas)
            current = disagg_role_replicas(model, role)
            if replicas == current:
                self._consecutive_scale_downs[key] = 0
                return current
            if replicas < current:
                required = self._required_consecutive(model)
                self._consecutive_scale_downs[key] = (
                    self._consecutive_scale_downs.get(key, 0) + 1
                )
                if self._consecutive_scale_downs[key] < required:
                    return current
                if self.governor is not None:
                    replicas, _denied = self.governor.govern_scale(
                        name, current, replicas
                    )
                    if replicas >= current:
                        return current  # held (stale telemetry / fence)
            self._consecutive_scale_downs[key] = 0
            ann = obj["metadata"].setdefault("annotations", {})
            ann[md.role_replicas_annotation(role)] = str(replicas)
            try:
                self.store.update(obj)
            except Conflict:
                return current  # next tick retries
            if self.governor is not None:
                self.governor.note_applied(name, roles={role: replicas})
            return replicas

    def consecutive_scale_downs(self, name: str) -> int:
        """Pending scale-down votes for a model (hysteresis state; 0 when
        the last tick held or scaled up)."""
        with self._scale_lock:
            return self._consecutive_scale_downs.get(name, 0)

    # injected by the autoscaler (interval-dependent); default 1 = immediate.
    required_consecutive_scale_downs_fn = None

    def _required_consecutive(self, model: Model) -> int:
        if self.required_consecutive_scale_downs_fn is not None:
            return self.required_consecutive_scale_downs_fn(model)
        return 1
