"""AWS SQS messenger driver: the JSON wire protocol, zero dependencies.

The reference registers gocloud.dev's awssnssqs driver for sqs://
streams (reference: internal/manager/run.go:47-48). This driver speaks
the SQS JSON protocol (Content-Type application/x-amz-json-1.0 +
X-Amz-Target) directly, signed with the shared SigV4 implementation
(kubeai_tpu.objstore.sigv4_sign — same algorithm the S3 client uses):

  SendMessage                publish (bodies base64-encoded, binary-safe
                             — gocloud's default encoding; receive
                             decodes base64 and falls back to raw for
                             foreign producers)
  ReceiveMessage             long-poll pull into a BOUNDED local queue
                             (backlog stays server-side where visibility
                             timeouts manage redelivery)
  DeleteMessage              ack
  ChangeMessageVisibility(0) nack → immediate redelivery
                             (gocloud awssnssqs parity)

The pull loop restarts with exponential backoff after transport errors
(reference: internal/messenger/messenger.go:98-127 recreates the
subscription with backoff).

URL form (config `messaging.streams`):
  sqs://sqs.us-east-1.amazonaws.com/123456789/queue-name
The queue URL is the sqs:// URL with https:// substituted, or
$AWS_ENDPOINT_URL_SQS + path when set (localstack / the test fake, no
TLS, unsigned when credentials are absent).
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import http.client
import json
import logging
import os
import queue
import threading
import urllib.parse

from kubeai_tpu.routing.brokers import RESTARTS_LOG_EVERY, _backoff
from kubeai_tpu.routing.messenger import Message

logger = logging.getLogger(__name__)

_JSON_CT = "application/x-amz-json-1.0"


class SQSBroker:
    """Broker-seam driver (publish/receive/close) over the SQS JSON
    protocol. One instance per stream URL; queues multiplex internally."""

    def __init__(
        self,
        endpoint: str | None = None,
        access_key: str | None = None,
        secret_key: str | None = None,
        region: str | None = None,
        pull_batch: int = 10,
        wait_seconds: int = 10,
        timeout_s: float = 35.0,
    ):
        self.endpoint = endpoint or os.environ.get("AWS_ENDPOINT_URL_SQS")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY"
        )
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")
        self.pull_batch = pull_batch
        self.wait_seconds = wait_seconds
        self.timeout_s = timeout_s
        self._queues: dict[str, queue.Queue] = {}
        self._pullers: dict[str, threading.Thread] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()

    # -- transport ------------------------------------------------------------

    def queue_url(self, stream_url: str) -> str:
        """sqs://host/account/queue → https://host/account/queue, or the
        endpoint override + path for fakes/localstack."""
        if "://" not in stream_url:
            stream_url = "sqs://" + stream_url
        parsed = urllib.parse.urlparse(stream_url)
        if self.endpoint:
            base = self.endpoint
            if "://" not in base:
                base = "http://" + base
            return base.rstrip("/") + parsed.path
        return f"https://{parsed.netloc}{parsed.path}"

    def _call(self, action: str, payload: dict) -> dict:
        qurl = urllib.parse.urlparse(payload["QueueUrl"])
        host = qurl.netloc
        body = json.dumps(payload).encode()
        if self.access_key and self.secret_key:
            from kubeai_tpu.objstore import sigv4_sign

            # The signer's output IS the complete header set (it echoes
            # the signed extra headers) — seeding mixed-case duplicates
            # here would make AWS's canonicalization join them as
            # "value,value" and fail signature verification.
            headers = sigv4_sign(
                "POST", "/", "",
                {
                    "content-type": _JSON_CT,
                    "x-amz-target": f"AmazonSQS.{action}",
                },
                hashlib.sha256(body).hexdigest(),
                service="sqs", region=self.region, host=host,
                access_key=self.access_key, secret_key=self.secret_key,
            )
        else:
            headers = {
                "Content-Type": _JSON_CT,
                "X-Amz-Target": f"AmazonSQS.{action}",
            }
        conn_cls = (
            http.client.HTTPSConnection
            if qurl.scheme == "https" else http.client.HTTPConnection
        )
        conn = conn_cls(host, timeout=self.timeout_s)
        try:
            conn.request("POST", "/", body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                raise RuntimeError(
                    f"sqs {action} -> {resp.status}: {data[:200]!r}"
                )
            return json.loads(data) if data else {}
        finally:
            conn.close()

    # -- Broker interface -------------------------------------------------------

    def publish(self, topic_url: str, body: bytes) -> None:
        self._call(
            "SendMessage",
            {
                "QueueUrl": self.queue_url(topic_url),
                "MessageBody": base64.b64encode(body).decode(),
            },
        )

    def receive(self, sub_url: str, timeout: float) -> Message | None:
        qurl = self.queue_url(sub_url)
        with self._lock:
            if qurl not in self._queues:
                self._queues[qurl] = queue.Queue(maxsize=2 * self.pull_batch)
                t = threading.Thread(
                    target=self._pull_loop, args=(qurl,), daemon=True
                )
                self._pullers[qurl] = t
                t.start()
        try:
            return self._queues[qurl].get(timeout=timeout)
        except queue.Empty:
            return None

    def close(self) -> None:
        self._stop.set()

    # -- pull loop --------------------------------------------------------------

    @staticmethod
    def _decode_body(text: str) -> bytes:
        try:
            return base64.b64decode(text, validate=True)
        except (binascii.Error, ValueError):
            return text.encode()  # foreign producer sent raw text

    def _pull_loop(self, qurl: str) -> None:
        restarts = 0
        while not self._stop.is_set():
            try:
                out = self._call(
                    "ReceiveMessage",
                    {
                        "QueueUrl": qurl,
                        "MaxNumberOfMessages": self.pull_batch,
                        "WaitTimeSeconds": self.wait_seconds,
                    },
                )
                restarts = 0
            except Exception as e:
                # Includes socket timeouts: wait_seconds (10) is well
                # under timeout_s (35), so a healthy quiet queue returns
                # an empty 200 long before the socket times out — a
                # timeout here is a transport failure and must back off
                # loudly like any other (a deaf subscription is worse
                # than a noisy one).
                restarts += 1
                log = (
                    logger.error
                    if restarts % RESTARTS_LOG_EVERY == 0
                    else logger.warning
                )
                log("sqs pull %s failed (restart %d): %s", qurl, restarts, e)
                if self._stop.wait(_backoff(restarts)):
                    return
                continue
            for m in out.get("Messages") or []:
                handle = m["ReceiptHandle"]
                msg = Message(
                    self._decode_body(m.get("Body", "")),
                    on_ack=lambda h=handle: self._ack(qurl, h),
                    on_nack=lambda h=handle: self._nack(qurl, h),
                )
                # Bounded put: blocks (flow control) until the Messenger
                # drains; poll so stop() still wins.
                while not self._stop.is_set():
                    try:
                        self._queues[qurl].put(msg, timeout=1.0)
                        break
                    except queue.Full:
                        continue

    def _ack(self, qurl: str, handle: str) -> None:
        try:
            self._call(
                "DeleteMessage",
                {"QueueUrl": qurl, "ReceiptHandle": handle},
            )
        except Exception:
            logger.warning(
                "sqs delete failed (message will redeliver)", exc_info=True
            )

    def _nack(self, qurl: str, handle: str) -> None:
        # Visibility 0 = immediate redelivery (gocloud parity).
        try:
            self._call(
                "ChangeMessageVisibility",
                {
                    "QueueUrl": qurl,
                    "ReceiptHandle": handle,
                    "VisibilityTimeout": 0,
                },
            )
        except Exception:
            logger.warning("sqs nack failed", exc_info=True)
