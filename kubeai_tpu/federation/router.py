"""Cost-ranked request spillover across cluster front doors.

The front door consults the `FederationRouter` AFTER local admission
(tenancy verdicts are rendered where the request arrived — the gossiped
budget is global, so a tenant cannot launder quota by hopping doors)
and BEFORE the local proxy. Spillover fires only when the local
capacity planner reports chip exhaustion for the model
(`throttled_replicas > 0`: demand the local budget cannot seat), and
only when a peer is genuinely cheaper:

    local cost   = oldest queue wait + depth x per-request wait
    remote cost  = peer RTT (+ the model's MEASURED boot cost from the
                   plan record when the peer has no live replica)

The boot cost is the `coldstart_cost_s` the planner already prices
demand with — observed boots, not config guesses — so a 70B model with
a four-minute cold start never spills to a cluster that would have to
boot it for one request. Tenancy headers are forwarded intact.
"""

from __future__ import annotations

import json
import logging
import time
import urllib.request

from kubeai_tpu.routing.proxy import ProxyResult

logger = logging.getLogger(__name__)

DISPATCH_TIMEOUT_S = 30.0
# Stamped on spilled responses so callers can see which cluster served.
SERVED_BY_HEADER = "x-kubeai-served-by-cluster"
# Stamped on the spilled request so the peer door never re-spills it
# (a two-cluster mutual-exhaustion loop would otherwise ping-pong).
SPILLED_HEADER = "x-kubeai-federation-spilled"


def _http_dispatch(peer, path: str, body: bytes, headers) -> ProxyResult:
    """Default dispatch: POST the request to the peer cluster's door.

    The peer door runs the full stack — tenancy, breakers, prefix
    routing — so the spilled request is an ordinary request there."""
    url = peer.door_url.rstrip("/") + path
    req = urllib.request.Request(url, data=body, method="POST")
    for k, v in headers:
        req.add_header(k, v)
    resp = urllib.request.urlopen(req, timeout=DISPATCH_TIMEOUT_S)  # noqa: S310
    out_headers = [(k.lower(), v) for k, v in resp.getheaders()]

    def chunks(r=resp):
        try:
            while True:
                chunk = r.read(65536)
                if not chunk:
                    return
                yield chunk
        finally:
            r.close()

    return ProxyResult(resp.status, out_headers, chunks())


class FederationRouter:
    """Exhaustion-gated, cost-ranked spillover to peer cluster doors."""

    def __init__(
        self,
        cfg,
        *,
        planner,
        federation,
        metrics,
        clock=time.monotonic,
        dispatch=None,
    ):
        self.cfg = cfg
        self.peers = tuple(cfg.cluster.peers)
        self.planner = planner
        self.federation = federation
        self.metrics = metrics
        self._clock = clock
        self.dispatch = dispatch or _http_dispatch
        self.queue_wait_per_request_s = (
            cfg.federation.queue_wait_per_request_seconds
        )

    # -- cost model ------------------------------------------------------

    @staticmethod
    def local_cost(record: dict, per_request_s: float) -> float:
        """Expected wait behind the local queue for this model."""
        return (
            float(record.get("queue_oldest_wait_s") or 0.0)
            + float(record.get("queue_depth") or 0) * per_request_s
        )

    @staticmethod
    def remote_cost(peer, record: dict, peer_entry: dict | None) -> float:
        """RTT to the peer door, plus the model's measured boot cost
        when the peer holds no live replica of it (the request would
        wait out a cold start there)."""
        cost = float(peer.rtt_seconds)
        live = 0
        if peer_entry:
            live = sum((peer_entry.get("replicas") or {}).values())
        if live <= 0:
            cost += float(record.get("coldstart_cost_s") or 0.0)
        return cost

    def rank(self, model: str, record: dict) -> list[tuple[float, object]]:
        """Fresh peers ranked by remote cost (ties broken by name so
        the ranking is deterministic under equal RTTs)."""
        ranked = []
        for peer in self.peers:
            if self.federation.cluster_stale(peer.name):
                continue  # a flagged cluster is not a spill target
            entry = self.federation.peer_models(peer.name).get(model)
            ranked.append(
                (self.remote_cost(peer, record, entry), peer.name, peer)
            )
        ranked.sort(key=lambda t: (t[0], t[1]))
        return [(cost, peer) for cost, _name, peer in ranked]

    # -- the spill decision ---------------------------------------------

    def maybe_spill(self, model, path, body, headers):
        """Return a peer door's ProxyResult when spilling wins, else
        None (serve locally). Every failure path degrades to None — the
        local queue is always a valid answer."""
        if not model or not self.peers:
            return None
        hdr_map = {str(k).lower(): v for k, v in (headers or [])}
        if hdr_map.get(SPILLED_HEADER):
            return None  # one hop only — never re-spill a spilled request
        plan = self.planner.current_plan() if self.planner else None
        if plan is None:
            return None
        record = (plan.get("models") or {}).get(model)
        if record is None:
            return None
        if int(record.get("throttled_replicas") or 0) <= 0:
            return None  # local capacity can seat the demand: stay home
        local = self.local_cost(record, self.queue_wait_per_request_s)
        ranked = self.rank(model, record)
        if not ranked:
            return None
        best_cost, peer = ranked[0]
        if best_cost >= local:
            return None  # waiting here is cheaper than going there
        fwd = list(headers or [])
        fwd.append((SPILLED_HEADER, self.federation.cluster))
        try:
            result = self.dispatch(peer, path, body, fwd)
        except Exception as e:  # noqa: BLE001 — peer loss degrades to local
            self.metrics.federation_spill_errors.inc(cluster=peer.name)
            logger.warning(
                "spillover of %s to %s failed (%s); serving locally",
                model, peer.name, e,
            )
            return None
        if result is None:
            return None
        result.headers = list(result.headers) + [
            (SERVED_BY_HEADER, peer.name)
        ]
        self.metrics.federation_spillovers.inc(
            model=model, cluster=peer.name
        )
        return result

    @staticmethod
    def model_of(body: bytes) -> str:
        """Best-effort model extraction from an OpenAI-shaped body."""
        try:
            return str(json.loads(body or b"{}").get("model") or "")
        except (ValueError, TypeError):
            return ""
