"""Join per-cluster fleet snapshots into one federation snapshot.

Each cluster already runs a `FleetStateAggregator` whose snapshot is
stamped with the cluster's validated identity (`cluster:` config
block). The `FederationAggregator` polls every peer's front door for
that snapshot and joins them into a federation view keyed by cluster
name. The cardinal rule: a stale or unreachable peer is FLAGGED, never
merged — its last-good snapshot stays visible (the failover planner
needs to know what the lost cluster was serving) but every consumer
sees `stale: true` and the age, so nobody mistakes a partitioned
cluster's past for the present.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request

logger = logging.getLogger(__name__)

# Bound on the blocking peer fetch; peers are remote clusters, so this
# is generous relative to the intra-cluster scrape timeout.
PEER_FETCH_TIMEOUT_S = 5.0


def _http_fetch_snapshot(peer, timeout: float = PEER_FETCH_TIMEOUT_S) -> dict:
    """Default peer fetch: GET the peer door's fleet-state endpoint."""
    url = peer.door_url.rstrip("/") + "/v1/fleet/state"
    with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
        return json.loads(resp.read().decode("utf-8"))


class FederationAggregator:
    """The federation state plane: local snapshot + flagged peer views.

    `fetch_snapshot` is injectable (tests and the federation sim hand
    in a closure over the peer cluster's in-process aggregator); the
    default speaks HTTP to the peer door. All clock reads go through
    the injected clock so the sim can drive staleness deterministically.
    """

    def __init__(
        self,
        cfg,
        local,
        *,
        metrics,
        clock=time.monotonic,
        fetch_snapshot=None,
    ):
        self.cfg = cfg
        self.cluster = cfg.cluster.name
        self.peers = tuple(cfg.cluster.peers)
        self.local = local
        self.metrics = metrics
        self._clock = clock
        self.fetch_snapshot = fetch_snapshot or _http_fetch_snapshot
        fed = cfg.federation
        self.staleness_s = (
            fed.staleness_seconds
            or (3 * fed.interval_seconds)
            or 15.0
        )
        self._lock = threading.Lock()
        # peer name -> {"snapshot": last-good dict|None, "fetched_at":
        # local-clock ts|None, "stale_since": ts|None, "error": str}
        self._peer_state: dict[str, dict] = {
            p.name: {
                "snapshot": None,
                "fetched_at": None,
                "stale_since": None,
                "error": "",
            }
            for p in self.peers
        }
        self._snapshot: dict | None = None

    # -- collection ------------------------------------------------------

    def join(self) -> dict:
        """One federation sweep: refresh every peer view, join with the
        local snapshot, publish. Peer staleness is judged on the LOCAL
        clock (time since a successful fetch), never on the peer's own
        timestamps — a partitioned peer's clock is exactly what we
        cannot trust."""
        now = self._clock()
        clusters: dict[str, dict] = {}
        local_snap = self.local.snapshot()
        if local_snap is None:
            local_snap = self.local.collect()
        clusters[self.cluster] = {
            "snapshot": local_snap,
            "stale": False,
            "age_s": round(max(0.0, now - local_snap["ts"]), 3),
            "error": "",
            "local": True,
        }
        for peer in self.peers:
            st = self._peer_state[peer.name]
            try:
                snap = self.fetch_snapshot(peer)
                if not isinstance(snap, dict):
                    raise TypeError(
                        f"peer snapshot is {type(snap).__name__}, not dict"
                    )
                st["snapshot"] = snap
                st["fetched_at"] = now
                st["error"] = ""
            except Exception as e:  # noqa: BLE001 — peer loss is routine
                st["error"] = str(e) or type(e).__name__
                logger.debug(
                    "federation fetch from %s failed: %s", peer.name, e
                )
            stale = self._is_stale(st, now)
            if stale:
                if st["stale_since"] is None:
                    st["stale_since"] = now
            else:
                st["stale_since"] = None
            age = (
                round(max(0.0, now - st["fetched_at"]), 3)
                if st["fetched_at"] is not None
                else None
            )
            clusters[peer.name] = {
                "snapshot": st["snapshot"],
                "stale": stale,
                "age_s": age,
                "error": st["error"],
                "local": False,
            }
            self.metrics.federation_cluster_stale.set(
                1.0 if stale else 0.0, cluster=peer.name
            )
        snapshot = {"ts": now, "cluster": self.cluster, "clusters": clusters}
        with self._lock:
            self._snapshot = snapshot
        self.metrics.federation_joins.inc()
        self.metrics.federation_snapshot_ts.set(now)
        return snapshot

    def _is_stale(self, st: dict, now: float) -> bool:
        if st["fetched_at"] is None:
            return True
        return now - st["fetched_at"] > self.staleness_s

    # -- reads -----------------------------------------------------------

    def snapshot(self) -> dict | None:
        with self._lock:
            return self._snapshot

    def cluster_stale(self, name: str) -> bool:
        """Is the named peer's view currently flagged stale? Unknown
        clusters are stale by definition (no view at all)."""
        st = self._peer_state.get(name)
        if st is None:
            return True
        return self._is_stale(st, self._clock())

    def stale_since(self, name: str) -> float | None:
        """Local-clock instant the named peer's view went stale (the
        failover planner's window input), or None while fresh."""
        st = self._peer_state.get(name)
        if st is None:
            return None
        return st["stale_since"]

    def peer_models(self, name: str) -> dict:
        """The named peer's last-good model map — the failover
        planner's read of what a lost cluster was serving. Empty when
        no snapshot was ever fetched."""
        st = self._peer_state.get(name)
        if st is None or st["snapshot"] is None:
            return {}
        return st["snapshot"].get("models") or {}

    def state_payload(self) -> dict:
        """`GET /v1/federation/state`: the latest federation snapshot,
        joined anew when none exists."""
        snap = self.snapshot()
        if snap is None:
            snap = self.join()
        payload = {"object": "federation.state"}
        payload.update(snap)
        return payload
