"""Whole-model failover when a peer cluster partitions.

The intra-cluster chaos plane already drills `api_partition` (one
cluster's API server going dark). The federation planner promotes that
to the cluster level: when a peer's federation view has been flagged
stale for a full `failover window` — one blip never moves a model —
every model the lost cluster was serving (live replicas in its
last-good snapshot) is failed over to this cluster by stamping
`FEDERATION_FAILOVER_ANNOTATION` on the local Model — the durable
record of the takeover that downstream capacity consumers can honor
as extra demand. When the peer heals, the takeover is reversed.

Every actuation — failover AND failback — routes through
`ActuationGovernor.allow_federation_failover`: a fenced leader or
blind telemetry cannot move models between clusters, and the static
gate (scripts/check_actuation_paths.py) pins the annotation write to
this module, inside a gate-consulting function, so no future caller
can bypass the governor.
"""

from __future__ import annotations

import logging
import time

from kubeai_tpu.crd import metadata as md

logger = logging.getLogger(__name__)


class FederationPlanner:
    """Bounded-window cluster failover, governor-gated end to end."""

    def __init__(
        self,
        cfg,
        *,
        federation,
        store,
        governor,
        metrics,
        clock=time.monotonic,
        namespace: str = "default",
    ):
        self.cfg = cfg
        self.peers = tuple(cfg.cluster.peers)
        self.federation = federation
        self.store = store
        self.governor = governor
        self.metrics = metrics
        self._clock = clock
        self.namespace = namespace
        self.window_s = cfg.federation.failover_window_seconds
        # model -> source cluster name we took it over from. Only
        # takeovers this planner owns are ever failed back.
        self.failed_over: dict[str, str] = {}

    def tick(self, now: float | None = None) -> dict:
        """One pass: fail over models of peers stale past the window,
        fail back models of peers that healed. Returns a summary for
        the sim's invariant checks."""
        now = self._clock() if now is None else now
        actions = {"failed_over": [], "failed_back": [], "denied": []}
        for peer in self.peers:
            since = self.federation.stale_since(peer.name)
            if since is not None and now - since >= self.window_s:
                self._fail_over_peer(peer, actions)
            elif since is None and not self.federation.cluster_stale(
                peer.name
            ):
                self._fail_back_peer(peer, actions)
        return actions

    # -- failover --------------------------------------------------------

    def _fail_over_peer(self, peer, actions: dict) -> None:
        for model, entry in sorted(
            self.federation.peer_models(peer.name).items()
        ):
            if self.failed_over.get(model):
                continue
            live = sum((entry.get("replicas") or {}).values())
            if live <= 0:
                continue  # the peer wasn't serving it; nothing to save
            if not self._local_model_exists(model):
                continue  # can't serve what this cluster never deployed
            verdict = self._actuate_failover(model, peer.name)
            if verdict == "denied":
                actions["denied"].append(model)
                continue
            if verdict != "ok":
                continue  # write failed; retried next tick
            self.failed_over[model] = peer.name
            self.metrics.federation_failovers.inc(
                model=model, cluster=peer.name
            )
            actions["failed_over"].append(model)
            logger.warning(
                "federation failover: %s taken over from partitioned "
                "cluster %s", model, peer.name,
            )

    def _fail_back_peer(self, peer, actions: dict) -> None:
        for model, src in sorted(self.failed_over.items()):
            if src != peer.name:
                continue
            verdict = self._actuate_failback(model)
            if verdict == "denied":
                actions["denied"].append(model)
                continue
            if verdict != "ok":
                continue  # write failed; retried next tick
            del self.failed_over[model]
            self.metrics.federation_failbacks.inc(
                model=model, cluster=peer.name
            )
            actions["failed_back"].append(model)
            logger.info(
                "federation failback: %s returned to healed cluster %s",
                model, peer.name,
            )

    # -- actuation (the ONLY writers of the failover annotation) ---------

    def _local_model_exists(self, model: str) -> bool:
        try:
            self.store.get("Model", self.namespace, model)
            return True
        except Exception:  # noqa: BLE001 — absent or unreachable: skip
            return False

    def _actuate_failover(self, model: str, source: str) -> str:
        """Gate, then stamp the takeover on the local Model. Returns
        "ok" | "denied" | "error". The static gate requires the write
        and the governor consult to share this function."""
        if not self.governor.allow_federation_failover(model):
            self.metrics.federation_failover_denied.inc(model=model)
            return "denied"
        try:
            self.store.patch_merge(
                "Model",
                self.namespace,
                model,
                {
                    "metadata": {
                        "annotations": {
                            md.FEDERATION_FAILOVER_ANNOTATION: source
                        }
                    }
                },
            )
            return "ok"
        except Exception as e:  # noqa: BLE001 — retried next tick
            logger.warning(
                "federation failover write for %s failed: %s", model, e
            )
            return "error"

    def _actuate_failback(self, model: str) -> str:
        """Gate, then clear the takeover (merge-patch None deletes the
        key). Returns "ok" | "denied" | "error"."""
        if not self.governor.allow_federation_failover(model):
            self.metrics.federation_failover_denied.inc(model=model)
            return "denied"
        try:
            self.store.patch_merge(
                "Model",
                self.namespace,
                model,
                {
                    "metadata": {
                        "annotations": {
                            md.FEDERATION_FAILOVER_ANNOTATION: None
                        }
                    }
                },
            )
            return "ok"
        except Exception as e:  # noqa: BLE001 — retried next tick
            logger.warning(
                "federation failback write for %s failed: %s", model, e
            )
            return "error"

    def state_payload(self) -> dict:
        return {
            "object": "federation.failovers",
            "window_s": self.window_s,
            "failed_over": dict(sorted(self.failed_over.items())),
        }
