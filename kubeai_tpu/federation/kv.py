"""Cross-cluster KV prefix fills through peer spill stores.

`KVSpillStore` already gives each cluster an objstore leg for evicted
hot-prefix pages. Federation reuses that medium across clusters: when
a local fill misses, peers' spill URLs are tried in config order. A
filled blob is still a KVP1 `KVPageExport`, so the full handoff
protocol applies unchanged — in particular the quant-header refusal:
a dtype or kv_quant-scheme mismatch between clusters (one runs int8
KV, the other bf16) REFUSES the fill and recomputes; it never casts.
Every failure mode — miss, refusal, mid-transfer death — degrades to a
counted local recompute; cross-cluster fill is an optimization, never
a correctness dependency.
"""

from __future__ import annotations

import logging

from kubeai_tpu.disagg import handoff
from kubeai_tpu.objstore import KVSpillStore

logger = logging.getLogger(__name__)


class FederationKVFiller:
    """Fill evicted prefixes from peer clusters' spill stores."""

    def __init__(self, cfg, *, metrics, stores=None):
        self.metrics = metrics
        self.fills = 0
        self.refusals = 0
        self.misses = 0
        if stores is not None:
            # Injected peer-name -> KVSpillStore map (tests, sim).
            self.stores = dict(stores)
            return
        self.stores = {
            p.name: KVSpillStore(p.spill_url)
            for p in cfg.cluster.peers
            if p.spill_url
        }

    def fill(self, hash_hex: str, expect_dtype: str | None = None):
        """Try each peer's spill store for the page run keyed by
        `hash_hex`. Returns a verified `KVPageExport` or None (miss —
        the caller recomputes; its recompute counter is the ledger).

        A malformed or quant-incompatible blob from one peer does not
        stop the sweep: another peer may hold a compatible copy."""
        for cluster, store in self.stores.items():
            try:
                blob = store.get(hash_hex)
            except Exception as e:  # noqa: BLE001 — peer loss is a miss
                logger.debug(
                    "federation KV fetch from %s failed: %s", cluster, e
                )
                continue
            if blob is None:
                continue
            try:
                export = handoff.deserialize_pages(blob)
            except handoff.HandoffError as e:
                # Truncated (mid-transfer death) or quant-header
                # mismatch: refuse, never cast or guess.
                self.refusals += 1
                self.metrics.federation_kv_refusals.inc(cluster=cluster)
                logger.warning(
                    "federation KV fill from %s refused: %s", cluster, e
                )
                continue
            if expect_dtype and export.dtype != expect_dtype:
                self.refusals += 1
                self.metrics.federation_kv_refusals.inc(cluster=cluster)
                logger.warning(
                    "federation KV fill from %s refused: dtype %s, "
                    "expected %s", cluster, export.dtype, expect_dtype,
                )
                continue
            self.fills += 1
            self.metrics.federation_kv_fills.inc(cluster=cluster)
            return export
        self.misses += 1
        return None
