"""Multi-cluster federation plane.

One cluster is one failure domain. The federation plane joins
per-cluster fleet snapshots into a single view (staleness flagged per
cluster, never silently merged), spills requests to a peer cluster's
front door when the local planner reports chip exhaustion (cost-ranked
by measured boot cost vs queue wait), fails whole models over when a
peer cluster partitions (every actuation routed through the
ActuationGovernor), and fills evicted KV prefixes from a peer
cluster's spill store with the quant-header refusal protocol intact.
"""

from kubeai_tpu.federation.aggregator import FederationAggregator
from kubeai_tpu.federation.kv import FederationKVFiller
from kubeai_tpu.federation.planner import FederationPlanner
from kubeai_tpu.federation.router import FederationRouter

__all__ = [
    "FederationAggregator",
    "FederationKVFiller",
    "FederationPlanner",
    "FederationRouter",
]
