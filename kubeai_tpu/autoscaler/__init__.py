"""Metrics-driven autoscaler with leader election and persisted state
(reference: internal/modelautoscaler, internal/leader, internal/movingaverage).
"""

from kubeai_tpu.autoscaler.movingaverage import SimpleMovingAverage
from kubeai_tpu.autoscaler.leader import LeaderElection
from kubeai_tpu.autoscaler.autoscaler import Autoscaler
