"""Lease-based leader election (reference: internal/leader/election.go).

A `Lease` object in the store records holder + renew time; candidates
race to acquire/renew it. `is_leader` is the atomic flag the autoscaler
checks each tick (reference: autoscaler.go:101).

Beyond the reference, leadership here also FENCES actuation: every
destructive write the operator issues (pod create/delete, scale-down,
preemption marks — see kubeai_tpu/operator/governor.py) first checks
`fence_valid()`, which requires the lease to be held AND to have been
renewed within `renew_deadline` seconds of local monotonic time. A
leader that loses the API server (or is partitioned away while another
replica takes the lease) therefore stops actuating on its own clock,
before its stale writes can fight the new leader's — the classic
fencing-token discipline, applied with local renew-recency because the
store interface carries no token the server would check.
"""

from __future__ import annotations

import threading
import time

from kubeai_tpu.metrics.registry import DEFAULT_METRICS, Metrics
from kubeai_tpu.operator.k8s.store import Conflict, KubeStore, NotFound

LEASE_NAME = "kubeai.org.leader"


class LeaderElection:
    def __init__(
        self,
        store: KubeStore,
        identity: str,
        namespace: str = "default",
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
        renew_deadline: float | None = None,
        metrics: Metrics = DEFAULT_METRICS,
        clock=time.monotonic,
        wall=time.time,
    ):
        self.store = store
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        # How long past the last successful renew an actuation fence
        # stays valid. Strictly shorter than lease_duration: this
        # replica must stop actuating BEFORE another replica can
        # legitimately take the lease over.
        self.renew_deadline = (
            renew_deadline if renew_deadline is not None
            else lease_duration * 2.0 / 3.0
        )
        self.metrics = metrics
        self._clock = clock
        self._wall = wall
        self._is_leader = threading.Event()
        self._last_renew: float | None = None  # local monotonic time
        self._listeners: list = []  # fn(is_leader: bool)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def fence_valid(self) -> bool:
        """True while actuation writes are safe: the lease is held and
        was renewed recently enough that no other replica can have
        acquired it yet. The governor consults this before every
        destructive batch; an expired leader's writes are dropped."""
        if not self._is_leader.is_set():
            return False
        last = self._last_renew
        return last is not None and self._clock() - last <= self.renew_deadline

    def add_listener(self, fn) -> None:
        """Register fn(is_leader) called on every leadership transition
        (the manager wires a controller resync on acquisition so work
        enqueued while not leader converges immediately)."""
        self._listeners.append(fn)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self.is_leader:
            self._release()
            self._set_leader(False)

    def _set_leader(self, leader: bool) -> None:
        was = self._is_leader.is_set()
        if leader:
            self._last_renew = self._clock()
            self._is_leader.set()
        else:
            self._is_leader.clear()
        if was == leader:
            return
        self.metrics.leader_is_leader.set(1.0 if leader else 0.0)
        self.metrics.leader_transitions.inc(
            direction="acquired" if leader else "lost"
        )
        for fn in list(self._listeners):
            try:
                fn(leader)
            except Exception:  # noqa: BLE001 — listeners are advisory
                pass

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._try_acquire_or_renew()
            except Exception:
                self._set_leader(False)
            self._stop.wait(self.retry_period)

    def _try_acquire_or_renew(self) -> None:
        now = self._wall()
        try:
            lease = self.store.get("Lease", self.namespace, LEASE_NAME)
        except NotFound:
            try:
                self.store.create(
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {
                            "name": LEASE_NAME,
                            "namespace": self.namespace,
                        },
                        "spec": {
                            "holderIdentity": self.identity,
                            "renewTime": now,
                        },
                    }
                )
                self._set_leader(True)
            except Conflict:
                self._set_leader(False)
            return

        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        renew = float(spec.get("renewTime") or 0)
        expired = now - renew > self.lease_duration

        if holder == self.identity or expired or not holder:
            spec["holderIdentity"] = self.identity
            spec["renewTime"] = now
            try:
                self.store.update(lease)
                self._set_leader(True)
            except Conflict:
                self._set_leader(False)
        else:
            self._set_leader(False)

    def _release(self) -> None:
        try:
            lease = self.store.get("Lease", self.namespace, LEASE_NAME)
            if lease.get("spec", {}).get("holderIdentity") == self.identity:
                lease["spec"]["holderIdentity"] = ""
                self.store.update(lease)
        except (NotFound, Conflict):
            pass
