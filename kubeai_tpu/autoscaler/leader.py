"""Lease-based leader election (reference: internal/leader/election.go).

A `Lease` object in the store records holder + renew time; candidates
race to acquire/renew it. `is_leader` is the atomic flag the autoscaler
checks each tick (reference: autoscaler.go:101)."""

from __future__ import annotations

import threading
import time

from kubeai_tpu.operator.k8s.store import Conflict, KubeStore, NotFound

LEASE_NAME = "kubeai.org.leader"


class LeaderElection:
    def __init__(
        self,
        store: KubeStore,
        identity: str,
        namespace: str = "default",
        lease_duration: float = 15.0,
        retry_period: float = 2.0,
    ):
        self.store = store
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self._is_leader = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader.is_set()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self.is_leader:
            self._release()
            self._is_leader.clear()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._try_acquire_or_renew()
            except Exception:
                self._is_leader.clear()
            self._stop.wait(self.retry_period)

    def _try_acquire_or_renew(self) -> None:
        now = time.time()
        try:
            lease = self.store.get("Lease", self.namespace, LEASE_NAME)
        except NotFound:
            try:
                self.store.create(
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {
                            "name": LEASE_NAME,
                            "namespace": self.namespace,
                        },
                        "spec": {
                            "holderIdentity": self.identity,
                            "renewTime": now,
                        },
                    }
                )
                self._is_leader.set()
            except Conflict:
                self._is_leader.clear()
            return

        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        renew = float(spec.get("renewTime") or 0)
        expired = now - renew > self.lease_duration

        if holder == self.identity or expired or not holder:
            spec["holderIdentity"] = self.identity
            spec["renewTime"] = now
            try:
                self.store.update(lease)
                self._is_leader.set()
            except Conflict:
                self._is_leader.clear()
        else:
            self._is_leader.clear()

    def _release(self) -> None:
        try:
            lease = self.store.get("Lease", self.namespace, LEASE_NAME)
            if lease.get("spec", {}).get("holderIdentity") == self.identity:
                lease["spec"]["holderIdentity"] = ""
                self.store.update(lease)
        except (NotFound, Conflict):
            pass
