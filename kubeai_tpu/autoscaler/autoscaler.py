"""The autoscaling loop (reference: internal/modelautoscaler/autoscaler.go).

Leader-only, every `interval`:
  list Models → scrape `/metrics` of EVERY operator replica (self-IPs from
  the LB, or `fixedSelfMetricAddrs` in tests) → sum
  `kubeai_inference_requests_active` per model → moving average over
  timeWindow/interval buckets → ceil(avg / targetRequests) → scale with
  consecutive-scale-down hysteresis → persist averages to a ConfigMap so a
  restarted operator resumes mid-window (reference: state.go:32-65).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request

from kubeai_tpu.autoscaler.leader import LeaderElection
from kubeai_tpu.autoscaler.movingaverage import SimpleMovingAverage
from kubeai_tpu.config import System
from kubeai_tpu.crd.model import Model
from kubeai_tpu.metrics import tracing
from kubeai_tpu.metrics.registry import (
    DEFAULT_METRICS,
    Metrics,
    parse_prometheus_text,
)
from kubeai_tpu.operator.k8s.store import KubeStore, NotFound
from kubeai_tpu.routing.loadbalancer import LoadBalancer
from kubeai_tpu.routing.modelclient import ModelClient

logger = logging.getLogger(__name__)

# One structured JSON record per (tick, model): the autoscaler's decision
# trail. Ship this logger to your aggregator to answer "why did model X
# scale at 14:03" without replaying metrics.
decision_log = logging.getLogger("kubeai.autoscaler.decisions")

ACTIVE_METRIC = "kubeai_inference_requests_active"

# Engine scheduler queue-pressure gauges (kubeai_tpu/engine/server.py
# EngineMetrics), scraped off each model's engine endpoints.
QUEUE_DEPTH_METRIC = "kubeai_engine_queue_depth"
QUEUE_OLDEST_WAIT_METRIC = "kubeai_engine_queue_oldest_wait_seconds"
# Per-role scaling signals (disaggregated serving).
KV_UTILIZATION_METRIC = "kubeai_engine_kv_cache_utilization"
SLOTS_ACTIVE_METRIC = "kubeai_engine_slots_active"
SLOT_CAPACITY_METRIC = "kubeai_engine_slot_capacity"
TTFT_SUM_METRIC = "kubeai_engine_ttft_seconds_sum"
TTFT_COUNT_METRIC = "kubeai_engine_ttft_seconds_count"


def ceil_div(x: float, y: float) -> int:
    """Ceiling division as an int — the autoscaler's replicas-from-signal
    idiom (`ceil(signal / target)`), shared by the per-model path, the
    per-role disagg path, and the fleet capacity planner. The divisor is
    a *target* (requests per replica, utilization fraction): zero or
    negative targets are configuration bugs, not demand, so they raise
    instead of silently returning garbage."""
    if y <= 0:
        raise ValueError(f"ceil_div divisor must be > 0, got {y!r}")
    return int(-(-x // y))


def desired_unified_replicas(
    avg: float,
    queue: dict,
    target_requests: int,
    queue_pressure_max_wait_s: float,
) -> int:
    """One unified model's unconstrained desired replicas: the active-
    request average over its per-replica target, boosted by queued depth
    once the oldest waiter has aged past the configured bound (queued
    requests are demand the active gauge cannot see). Shared by
    Autoscaler.tick and the fleet capacity planner so a planner-fed tick
    wants exactly what a direct tick would."""
    desired = ceil_div(avg, target_requests)
    if (
        queue_pressure_max_wait_s > 0
        and queue["oldest_wait_s"] >= queue_pressure_max_wait_s
    ):
        desired = max(
            desired, ceil_div(avg + queue["depth"], target_requests)
        )
    return desired


def desired_prefill_replicas(
    sig: dict,
    n_endpoints: int,
    dis,
    queue_pressure_max_wait_s: float,
) -> int:
    """Prefill-role desire: scale for the prefills WAITING (depth over
    the per-replica queue target), +1 replica past the current pool when
    the oldest waiter or the mean TTFT has aged past bounds — by then
    every queued request is eating TTFT budget."""
    n_pre = max(1, n_endpoints)
    desired = ceil_div(sig["depth"], max(1, dis.prefill_target_queue))
    if (
        queue_pressure_max_wait_s > 0
        and sig["oldest_wait_s"] >= queue_pressure_max_wait_s
    ):
        desired = max(desired, n_pre + 1)
    if (
        dis.prefill_target_ttft_seconds > 0
        and sig["ttft_mean_s"] > dis.prefill_target_ttft_seconds
    ):
        desired = max(desired, n_pre + 1)
    return desired


def desired_decode_replicas(
    sig: dict, n_endpoints: int, dis
) -> tuple[int, float, float]:
    """Decode-role desire: keep max(KV-pool utilization, slot occupancy)
    at the target fraction — decode replicas die by running out of
    pages/slots, not by queue depth. Returns (desired, slot_occupancy,
    utilization) so callers can log the raw signal."""
    n_dec = max(1, n_endpoints)
    slot_occ = (
        sig["slots_active"] / sig["slot_capacity"]
        if sig["slot_capacity"] > 0 else 0.0
    )
    util = max(sig["kv_utilization"], slot_occ)
    desired = (
        ceil_div(n_dec * util, dis.decode_target_utilization)
        if util > 0 else 1
    )
    return max(1, desired), slot_occ, util


def _fetch_metrics(addr: str, timeout: float) -> str:
    with urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=timeout
    ) as resp:
        return resp.read().decode()


def _scrape_all(
    addrs: list[str], timeout: float, fetch=None
) -> dict[str, "str | Exception"]:
    """Fetch every address CONCURRENTLY. Each endpoint gets the full
    per-request timeout, but the wall cost of the whole sweep is one
    slow endpoint, not their sum — serial scraping let a few dead
    endpoints eat most of the tick interval. Returns
    {addr: exposition text | the exception that fetch raised}."""
    fetch = fetch or _fetch_metrics
    results: dict[str, str | Exception] = {}
    if not addrs:
        return results
    if len(addrs) == 1:
        try:
            results[addrs[0]] = fetch(addrs[0], timeout)
        except Exception as e:  # noqa: BLE001 — classified by callers
            results[addrs[0]] = e
        return results
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(16, len(addrs))) as pool:
        futures = {addr: pool.submit(fetch, addr, timeout) for addr in addrs}
        for addr, fut in futures.items():
            try:
                results[addr] = fut.result()
            except Exception as e:  # noqa: BLE001
                results[addr] = e
    return results


def scrape_active_requests(
    addrs: list[str], timeout: float = 5.0, fetch=None
) -> dict[str, float]:
    """Aggregate the active-request gauge across operator replicas
    (reference: modelautoscaler/metrics.go:15-71). Endpoints are scraped
    concurrently; ANY failure still fails the tick (a missing replica
    must not silently zero the signal)."""
    totals: dict[str, float] = {}
    for addr, text in _scrape_all(addrs, timeout, fetch).items():
        if isinstance(text, Exception):
            raise RuntimeError(
                f"scraping http://{addr}/metrics: {text}"
            ) from text
        for (name, labels), value in parse_prometheus_text(text).items():
            if name != ACTIVE_METRIC:
                continue
            model = dict(labels).get("model", "")
            if model:
                totals[model] = totals.get(model, 0.0) + value
    return totals


def aggregate_queue_pressure(
    parsed_by_addr: dict[str, dict],
) -> dict:
    """Fold per-endpoint parsed `/metrics` into the queue-pressure
    signal: ``{"depth": total, "oldest_wait_s": max, "per_class":
    {class: depth}}``. Shared by the direct scraper below AND the fleet
    aggregator (kubeai_tpu/fleet) — one aggregation, so an
    aggregator-fed tick decides exactly what a direct-scrape tick
    would."""
    depth = 0.0
    oldest = 0.0
    per_class: dict[str, float] = {}
    for parsed in parsed_by_addr.values():
        for (name, labels), value in parsed.items():
            if name == QUEUE_DEPTH_METRIC:
                depth += value
                cls = dict(labels).get("class", "")
                if cls:
                    per_class[cls] = per_class.get(cls, 0.0) + value
            elif name == QUEUE_OLDEST_WAIT_METRIC:
                oldest = max(oldest, value)
    return {"depth": depth, "oldest_wait_s": oldest, "per_class": per_class}


def aggregate_role_signals(parsed_by_addr: dict[str, dict]) -> dict:
    """Fold per-endpoint parsed `/metrics` into one role's scaling
    signals (queue/TTFT pressure for prefill, KV/slot occupancy for
    decode). Shared by the direct scraper and the fleet aggregator."""
    out = {
        "endpoints": 0,
        "depth": 0.0,
        "oldest_wait_s": 0.0,
        "kv_utilization": 0.0,
        "slots_active": 0.0,
        "slot_capacity": 0.0,
        "ttft_mean_s": 0.0,
    }
    kv_samples: list[float] = []
    ttft_sum = ttft_count = 0.0
    for parsed in parsed_by_addr.values():
        out["endpoints"] += 1
        for (name, labels), value in parsed.items():
            if name == QUEUE_DEPTH_METRIC:
                out["depth"] += value
            elif name == QUEUE_OLDEST_WAIT_METRIC:
                out["oldest_wait_s"] = max(out["oldest_wait_s"], value)
            elif name == KV_UTILIZATION_METRIC:
                kv_samples.append(value)
            elif name == SLOTS_ACTIVE_METRIC:
                out["slots_active"] += value
            elif name == SLOT_CAPACITY_METRIC:
                out["slot_capacity"] += value
            elif name == TTFT_SUM_METRIC:
                ttft_sum += value
            elif name == TTFT_COUNT_METRIC:
                ttft_count += value
    if kv_samples:
        out["kv_utilization"] = sum(kv_samples) / len(kv_samples)
    if ttft_count > 0:
        out["ttft_mean_s"] = ttft_sum / ttft_count
    return out


def _parse_reachable(
    addrs: list[str], timeout: float, fetch, what: str
) -> dict[str, dict]:
    """Scrape + parse, skipping unreachable endpoints (engine pools
    churn by design while the autoscaler acts on them — the signal
    degrades conservatively instead of failing the tick)."""
    parsed: dict[str, dict] = {}
    for addr, text in _scrape_all(addrs, timeout, fetch).items():
        if isinstance(text, Exception):
            logger.debug("%s scrape skipped %s: %s", what, addr, text)
            continue
        parsed[addr] = parse_prometheus_text(text)
    return parsed


def scrape_queue_pressure(
    addrs: list[str], timeout: float = 5.0, fetch=None
) -> dict:
    """Best-effort CONCURRENT scrape of one model's ENGINE endpoints for
    the scheduler's queue-pressure gauges (the aggregator-miss fallback
    path)."""
    return aggregate_queue_pressure(
        _parse_reachable(addrs, timeout, fetch, "queue-pressure")
    )


def scrape_role_signals(
    addrs: list[str], timeout: float = 5.0, fetch=None
) -> dict:
    """Concurrent best-effort scrape of one ROLE's engine endpoints for
    the disaggregated scaling signals (the aggregator-miss fallback
    path)."""
    return aggregate_role_signals(
        _parse_reachable(addrs, timeout, fetch, "role")
    )


class Autoscaler:
    def __init__(
        self,
        store: KubeStore,
        cfg: System,
        model_client: ModelClient,
        lb: LoadBalancer,
        leader: LeaderElection,
        namespace: str = "default",
        metrics: Metrics = DEFAULT_METRICS,
    ):
        self.store = store
        self.cfg = cfg
        self.model_client = model_client
        self.lb = lb
        self.leader = leader
        self.namespace = namespace
        self.metrics = metrics
        # Most recent tick's decision records (one dict per model) — the
        # in-process view of what decision_log just emitted.
        self.last_decisions: list[dict] = []
        # Injectable for tests (fake engine endpoints without sockets).
        self.queue_scraper = scrape_queue_pressure
        self.role_scraper = scrape_role_signals
        self.active_scraper = scrape_active_requests
        # Fleet telemetry plane (kubeai_tpu/fleet): when wired, per-model
        # engine signals come from the aggregator's snapshot instead of
        # a fresh scrape per model per tick; a stale/missing snapshot
        # falls back to the direct scrape.
        self.fleet = None
        # Cluster-wide capacity planner (kubeai_tpu/fleet/planner): when
        # wired, the planner's bin-packed allocation overrides this
        # model's own desire before ModelClient.scale/scale_role; a
        # stale or missing plan falls back to direct per-model scaling.
        self.planner = None
        # SLO evaluator (kubeai_tpu/fleet/slo): when wired, a model whose
        # objectives are fast-burning gets one replica of headroom beyond
        # its signal-derived desire — a latency regression burns budget
        # before queues back up, so waiting for queue pressure means
        # paying a cold start AFTER the page instead of before it.
        self.slo = None
        self.interval = cfg.model_autoscaling.interval_seconds
        self.window_count = cfg.model_autoscaling.average_window_count
        self._averages: dict[str, SimpleMovingAverage] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        # Hysteresis wiring: scale-downs require N consecutive votes
        # (reference: config/system.go:131-137 + modelclient/scale.go).
        model_client.required_consecutive_scale_downs_fn = (
            lambda m: max(
                1,
                cfg.model_autoscaling.required_consecutive_scale_downs(
                    m.spec.scale_down_delay_seconds
                ),
            )
        )

        self._load_state()

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if not self.leader.is_leader:
                continue
            try:
                self.tick()
            except Exception as e:
                logger.warning("autoscaler tick failed: %s", e)

    # -- one tick (reference: autoscaler.go:94-166) ----------------------------

    def tick(self) -> None:
        models = self.model_client.list_all_models()
        addrs = self._self_metric_addrs()
        if not addrs:
            return
        with tracing.tracer().start_span(
            "autoscaler.tick", kind=tracing.KIND_INTERNAL
        ) as span:
            t0 = time.monotonic()
            totals = self.active_scraper(addrs)
            scrape_s = time.monotonic() - t0
            # The scrape duration lands in the histogram AND on the tick
            # span — traces and metrics must tell the same story.
            self.metrics.autoscaler_scrape_duration.observe(scrape_s)
            span.set_attribute("scrape.duration_s", scrape_s)
            span.set_attribute("scrape.replicas", len(addrs))
            span.set_attribute("models", len(models))

            decisions: list[dict] = []
            next_averages: dict[str, SimpleMovingAverage] = {}
            for model in models:
                if model.spec.autoscaling_disabled:
                    continue
                active = totals.get(model.name, 0.0)
                avg_tracker = self._avg_for(model.name)
                avg = avg_tracker.next(active)
                next_averages[model.name] = avg_tracker
                if model.spec.disaggregation.enabled:
                    # Disaggregated pod groups scale per role from their
                    # own bottleneck signals; spec.replicas is not the
                    # control surface for them.
                    record = self._disagg_decisions(
                        model, active, avg, scrape_s, len(addrs)
                    )
                    decisions.append(record)
                    decision_log.info(json.dumps(record, sort_keys=True))
                    continue
                # Queue-pressure boost: requests waiting in the engines'
                # schedulers are demand the active-request gauge cannot
                # see (they are not active yet). When the oldest waiter
                # has aged past the configured bound, fold queued depth
                # into the demand estimate — a saturated replica set
                # otherwise plateaus at "looks fully utilized" while its
                # queues (and TTFT) grow without bound.
                queue, queue_src = self._queue_signals(model.name)
                desired = desired_unified_replicas(
                    avg, queue, model.spec.target_requests,
                    self.cfg.model_autoscaling.queue_pressure_max_wait_seconds,
                )
                burn = self._slo_pressure(model.name)
                slo_fast = bool(burn and burn["level"] >= 2)
                if slo_fast and desired > 0:
                    desired += 1
                # Cluster capacity plan override: a fresh plan's
                # bin-packed allocation wins over this model's solo
                # desire (the planner already saw the desire's inputs
                # plus every OTHER model's); stale/no plan = the
                # pre-planner direct path.
                alloc = self._plan_allocation(model.name)
                if alloc is not None and "replicas" in alloc:
                    target = int(alloc["replicas"])
                    scaling_source = "planner"
                else:
                    target = desired
                    scaling_source = "direct"
                applied = self.model_client.scale(model.name, target)
                votes = self.model_client.consecutive_scale_downs(model.name)
                record = {
                    "ts": time.time(),
                    "model": model.name,
                    "signal": active,
                    "average": avg,
                    "target_requests": model.spec.target_requests,
                    "computed_replicas": desired,
                    "applied_replicas": applied,
                    "scale_down_votes": votes,
                    "scrape_duration_s": scrape_s,
                    "scraped_replicas": len(addrs),
                    "queue_depth": queue["depth"],
                    "queue_oldest_wait_s": queue["oldest_wait_s"],
                    "queue_per_class": dict(queue["per_class"]),
                    "telemetry_source": queue_src,
                    "scaling_source": scaling_source,
                    "slo_pressure": slo_fast,
                    "slo_burn": (burn or {}).get("state", ""),
                }
                if scaling_source == "planner":
                    record["planner_replicas"] = target
                decisions.append(record)
                decision_log.info(json.dumps(record, sort_keys=True))
                self.metrics.autoscaler_signal.set(active, model=model.name)
                self.metrics.autoscaler_average.set(avg, model=model.name)
                self.metrics.autoscaler_desired_replicas.set(
                    desired, model=model.name
                )
                self.metrics.autoscaler_applied_replicas.set(
                    applied, model=model.name
                )
                self.metrics.autoscaler_scale_down_votes.set(
                    votes, model=model.name
                )
                self.metrics.autoscaler_queue_depth.set(
                    queue["depth"], model=model.name
                )
                self.metrics.autoscaler_queue_oldest_wait.set(
                    queue["oldest_wait_s"], model=model.name
                )
            self.last_decisions = decisions
            self.metrics.autoscaler_ticks.inc()

            # Keep state only for models that still exist — deleted models'
            # averages must not accumulate in memory or the state ConfigMap
            # (reference: autoscaler.go:115,159-163 rebuilds state per tick).
            self._averages = next_averages
            self._save_state()

    # -- capacity-plan consultation (planner-first, direct fallback) -----------

    def _plan_allocation(self, model_name: str) -> dict | None:
        """The fleet planner's arbitrated allocation for one model, or
        None when there is no planner, the plan is stale, or the model
        is not under plan control (→ the caller scales directly). A
        planner crash must degrade to direct scaling, never fail the
        tick."""
        if self.planner is None:
            return None
        try:
            return self.planner.allocation_for(model_name)
        except Exception as e:  # noqa: BLE001 — planner is advisory
            logger.warning("capacity plan lookup failed: %s", e)
            return None

    def current_average(self, model_name: str) -> float | None:
        """This model's moving-average signal as of the last tick — the
        capacity planner reads it so plan desires use the SAME smoothed
        signal the direct scaling path uses."""
        avg = self._averages.get(model_name)
        return avg.average() if avg is not None else None

    # -- engine-signal reads (aggregator-first, direct-scrape fallback) --------

    def _queue_signals(self, model_name: str) -> tuple[dict, str]:
        """One model's queue-pressure signals and where they came from
        ("aggregator" | "scrape"). The aggregator answers from its last
        fleet sweep; a stale/missing snapshot degrades to the same
        direct scrape the pre-fleet autoscaler ran."""
        if self.fleet is not None:
            queue = self.fleet.queue_pressure(model_name)
            if queue is not None:
                return queue, "aggregator"
        return (
            self.queue_scraper(self.lb.group(model_name).addresses()),
            "scrape",
        )

    def _role_signals(
        self, model_name: str, role: str, addrs: list[str]
    ) -> tuple[dict, str]:
        if self.fleet is not None:
            sig = self.fleet.role_signals(model_name, role)
            if sig is not None:
                return sig, "aggregator"
        return self.role_scraper(addrs), "scrape"

    def _disagg_decisions(
        self, model, active: float, avg: float,
        scrape_s: float, scraped_replicas: int,
    ) -> dict:
        """Per-role desired replicas for one disaggregated model.

        Prefill is queue-shaped: scale for the prefills WAITING (depth /
        target per replica), boosted when the oldest waiter or the mean
        TTFT has aged past bounds — by then every queued request is
        eating TTFT budget. Decode is occupancy-shaped: scale to keep
        max(KV-pool utilization, slot occupancy) at the target fraction —
        decode replicas die by running out of pages/slots, not by queue
        depth. Both land in the Model's role annotations via
        ModelClient.scale_role (hysteresis + CRD bounds applied there)."""
        from kubeai_tpu.crd import metadata as md

        dis = model.spec.disaggregation
        group = self.lb.group(model.name)
        pre_addrs = group.addresses(role=md.ROLE_PREFILL)
        dec_addrs = group.addresses(role=md.ROLE_DECODE)
        pre, pre_src = self._role_signals(
            model.name, md.ROLE_PREFILL, pre_addrs
        )
        dec, dec_src = self._role_signals(
            model.name, md.ROLE_DECODE, dec_addrs
        )
        threshold = (
            self.cfg.model_autoscaling.queue_pressure_max_wait_seconds
        )

        desired_pre = desired_prefill_replicas(
            pre, len(pre_addrs), dis, threshold
        )
        desired_dec, slot_occ, util = desired_decode_replicas(
            dec, len(dec_addrs), dis
        )
        # TTFT lives in prefill: a fast-burning objective buys prefill
        # headroom (decode scales on occupancy, which the burn already
        # reflects if decode is the bottleneck).
        burn = self._slo_pressure(model.name)
        slo_fast = bool(burn and burn["level"] >= 2)
        if slo_fast:
            desired_pre += 1
        # Capacity plan override: the planner damps the prefill/decode
        # pair JOINTLY (both roles shrink toward their desired ratio
        # under chip pressure) — per-role direct scaling is the stale-
        # plan fallback.
        alloc = self._plan_allocation(model.name)
        roles_alloc = (alloc or {}).get("roles") or {}
        if md.ROLE_PREFILL in roles_alloc and md.ROLE_DECODE in roles_alloc:
            target_pre = int(roles_alloc[md.ROLE_PREFILL])
            target_dec = int(roles_alloc[md.ROLE_DECODE])
            scaling_source = "planner"
        else:
            target_pre, target_dec = desired_pre, desired_dec
            scaling_source = "direct"
        applied_pre = self.model_client.scale_role(
            model.name, md.ROLE_PREFILL, target_pre
        )
        applied_dec = self.model_client.scale_role(
            model.name, md.ROLE_DECODE, target_dec
        )

        for role, desired, applied, signal in (
            (md.ROLE_PREFILL, desired_pre, applied_pre, pre["depth"]),
            (md.ROLE_DECODE, desired_dec, applied_dec, util),
        ):
            self.metrics.autoscaler_role_desired_replicas.set(
                desired, model=model.name, role=role
            )
            self.metrics.autoscaler_role_applied_replicas.set(
                applied, model=model.name, role=role
            )
            self.metrics.autoscaler_role_signal.set(
                signal, model=model.name, role=role
            )
        self.metrics.autoscaler_signal.set(active, model=model.name)
        self.metrics.autoscaler_average.set(avg, model=model.name)
        return {
            "ts": time.time(),
            "model": model.name,
            "disaggregated": True,
            "signal": active,
            "average": avg,
            "scrape_duration_s": scrape_s,
            "scraped_replicas": scraped_replicas,
            "telemetry_source": {
                md.ROLE_PREFILL: pre_src,
                md.ROLE_DECODE: dec_src,
            },
            "scaling_source": scaling_source,
            "slo_pressure": slo_fast,
            "slo_burn": (burn or {}).get("state", ""),
            "roles": {
                md.ROLE_PREFILL: {
                    "endpoints": len(pre_addrs),
                    "queue_depth": pre["depth"],
                    "queue_oldest_wait_s": pre["oldest_wait_s"],
                    "ttft_mean_s": pre["ttft_mean_s"],
                    "computed_replicas": desired_pre,
                    "applied_replicas": applied_pre,
                },
                md.ROLE_DECODE: {
                    "endpoints": len(dec_addrs),
                    "kv_utilization": dec["kv_utilization"],
                    "slot_occupancy": slot_occ,
                    "computed_replicas": desired_dec,
                    "applied_replicas": applied_dec,
                },
            },
        }

    def _self_metric_addrs(self) -> list[str]:
        if self.cfg.fixed_self_metric_addrs:
            return list(self.cfg.fixed_self_metric_addrs)
        return self.lb.get_self_ips()

    def _slo_pressure(self, model: str) -> dict | None:
        """The SLO evaluator's pressure read, or None when no evaluator
        is wired / the model was not judged this tick."""
        if self.slo is None:
            return None
        try:
            return self.slo.pressure(model)
        except Exception:  # noqa: BLE001 — advisory signal only
            return None

    def _avg_for(self, model: str) -> SimpleMovingAverage:
        if model not in self._averages:
            self._averages[model] = SimpleMovingAverage(self.window_count)
        return self._averages[model]

    # -- state persistence (reference: state.go:32-65) --------------------------

    @property
    def _cm_name(self) -> str:
        return self.cfg.model_autoscaling.state_configmap_name

    def _save_state(self) -> None:
        state = {
            name: {"average": avg.average()}
            for name, avg in self._averages.items()
        }
        data = {"state": json.dumps(state)}
        try:
            cm = self.store.get("ConfigMap", self.namespace, self._cm_name)
            cm["data"] = data
            self.store.update(cm)
        except NotFound:
            self.store.create(
                {
                    "apiVersion": "v1",
                    "kind": "ConfigMap",
                    "metadata": {
                        "name": self._cm_name,
                        "namespace": self.namespace,
                    },
                    "data": data,
                }
            )

    def _load_state(self) -> None:
        """Preload averages so a restart doesn't forget recent load — the
        scale-to-zero edge case (reference: autoscaler.go:43-66)."""
        try:
            cm = self.store.get("ConfigMap", self.namespace, self._cm_name)
        except NotFound:
            return
        try:
            state = json.loads((cm.get("data") or {}).get("state", "{}"))
        except json.JSONDecodeError:
            return
        for name, entry in state.items():
            avg = float(entry.get("average", 0.0))
            self._averages[name] = SimpleMovingAverage(
                self.window_count, seed=avg
            )
