"""Fixed-window moving average (reference: internal/movingaverage/simple.go).

A ring buffer of the last N samples. Unlike an EMA it reaches EXACTLY zero
when all samples are zero — the property scale-to-zero depends on
(reference: simple.go:10-18)."""

from __future__ import annotations


class SimpleMovingAverage:
    def __init__(self, window: int, seed: float = 0.0):
        if window < 1:
            raise ValueError("window must be >= 1")
        self._samples = [seed] * window
        self._idx = 0

    def next(self, value: float) -> float:
        self._samples[self._idx] = value
        self._idx = (self._idx + 1) % len(self._samples)
        return self.average()

    def average(self) -> float:
        return sum(self._samples) / len(self._samples)

    def history(self) -> list[float]:
        return list(self._samples)
