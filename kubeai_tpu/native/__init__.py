"""ctypes bindings for the native C++ data-plane library.

Builds on demand (g++ is a one-second compile) and caches the .so next to
the sources; everything degrades to the pure-Python implementations when
no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LOCK = threading.Lock()
_TRIED = False

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libkubeai_native.so")


def _build() -> bool:
    src = os.path.join(_NATIVE_DIR, "kubeai_native.cpp")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def load_native():
    """Returns the loaded library or None."""
    global _LIB, _TRIED
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.kubeai_xxhash64.restype = ctypes.c_uint64
        lib.kubeai_xxhash64.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
        ]
        lib.kubeai_ring_new.restype = ctypes.c_void_p
        lib.kubeai_ring_new.argtypes = [ctypes.c_double, ctypes.c_int]
        lib.kubeai_ring_free.argtypes = [ctypes.c_void_p]
        lib.kubeai_ring_add.restype = ctypes.c_int
        lib.kubeai_ring_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kubeai_ring_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kubeai_ring_lookup.restype = ctypes.c_int
        lib.kubeai_ring_lookup.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
            ctypes.c_char_p,
        ]
        _LIB = lib
        return _LIB


def xxhash64_native(data: bytes, seed: int = 0) -> int | None:
    lib = load_native()
    if lib is None:
        return None
    return lib.kubeai_xxhash64(data, len(data), seed)


class NativeCHWBL:
    """Native consistent-hash ring with bounded loads (see chwbl.py for
    the contract; the Python CHWBL is the oracle)."""

    def __init__(self, load_factor: float = 1.25, replication: int = 256):
        lib = load_native()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.kubeai_ring_new(load_factor, replication)
        self._ids: dict[str, int] = {}
        self._names: list[str] = []
        self._lock = threading.Lock()

    def __del__(self):
        if getattr(self, "_h", None) and getattr(self, "_lib", None):
            self._lib.kubeai_ring_free(self._h)
            self._h = None

    def add(self, endpoint: str) -> None:
        with self._lock:
            eid = self._lib.kubeai_ring_add(self._h, endpoint.encode())
            self._ids[endpoint] = eid
            while len(self._names) <= eid:
                self._names.append("")
            self._names[eid] = endpoint

    def remove(self, endpoint: str) -> None:
        with self._lock:
            self._lib.kubeai_ring_remove(self._h, endpoint.encode())
            eid = self._ids.pop(endpoint, None)
            if eid is not None and eid < len(self._names):
                self._names[eid] = ""

    def get(
        self,
        key: str,
        loads: dict[str, int],
        adapter_endpoints: set[str] | None = None,
    ) -> str | None:
        with self._lock:
            n = len(self._names)
            if n == 0:
                return None
            arr = (ctypes.c_int64 * n)()
            for name, load in loads.items():
                eid = self._ids.get(name)
                if eid is not None:
                    arr[eid] = load
            mask = None
            if adapter_endpoints is not None:
                mask_bytes = bytearray(n)
                for name in adapter_endpoints:
                    eid = self._ids.get(name)
                    if eid is not None:
                        mask_bytes[eid] = 1
                mask = bytes(mask_bytes)
            kb = key.encode()
            eid = self._lib.kubeai_ring_lookup(
                self._h, kb, len(kb), arr, n, mask
            )
            if eid < 0 or eid >= len(self._names) or not self._names[eid]:
                return None
            return self._names[eid]
