"""Well-known labels/annotations/finalizers (reference: api/k8s/v1/metadata.go:3-31)."""

GROUP = "kubeai.org"

# Labels
POD_MODEL_LABEL = "model"
# Pod-hash of the rendered spec, drives rollouts
# (reference: api/k8s/v1/metadata.go:8, k8sutils/pods.go:26-42).
POD_HASH_LABEL = "pod-hash"

MODEL_FEATURE_LABEL_DOMAIN = "features.kubeai.org"


def feature_label(feature: str) -> str:
    return f"{MODEL_FEATURE_LABEL_DOMAIN}/{feature}"


# Annotations
MODEL_POD_IP_ANNOTATION = "model-pod-ip"
MODEL_POD_PORT_ANNOTATION = "model-pod-port"
# Multi-host replicas (no reference analog — the reference is strictly
# one-Pod-per-replica, pod_plan.go:28-156; TPU slices >8 chips span
# hosts). Worker hosts carry serving="false" so the LB never routes to
# them; group/host labels identify a replica's Pod group.
MODEL_POD_SERVING_ANNOTATION = "model-pod-serving"
POD_GROUP_LABEL = "model-group-index"
POD_HOST_LABEL = "model-host-index"
# Expected member count of the pod's slice group, stamped on every
# member so consumers that see only pods (LB sync, fleet aggregation)
# can tell a complete group from a partial one without re-resolving the
# model's profile.
POD_GROUP_SIZE_LABEL = "model-group-size"

# Disaggregated serving (kubeai_tpu/disagg): a replica's serving role.
# Unified replicas carry no role label; prefill/decode pod groups are
# rendered with it and the LB keeps per-role endpoint groups keyed on it.
POD_ROLE_LABEL = "model-role"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_UNIFIED = "unified"
DISAGG_ROLES = (ROLE_PREFILL, ROLE_DECODE)


def role_replicas_annotation(role: str) -> str:
    """Model annotation holding the autoscaler's per-role replica count
    for disaggregated pod groups (spec.replicas stays the unified knob)."""
    return f"{GROUP}/{role}-replicas"


# Capacity planner (kubeai_tpu/fleet/planner): pods the cluster-wide
# planner picked as preemption victims — chips reclaimed for a
# higher-scheduling-class model. pod_plan's deletion ordering deletes
# marked pods first, so the replicas that die when the autoscaler applies
# the planner's shrunken allocation are exactly the planner's picks.
# Value: the planner's stable reason string (e.g. "CapacityPreemption").
PLANNER_PREEMPT_ANNOTATION = "kubeai.org/planner-preempt"
PREEMPT_REASON_CAPACITY = "CapacityPreemption"

# Actuation governor (kubeai_tpu/operator/governor): the last replica
# shape applied under healthy telemetry, persisted on the Model so a
# restarted operator rehydrates its static-stability floor before the
# first tick. Value: JSON {"replicas": n} or {"roles": {role: n}}.
LAST_KNOWN_GOOD_ANNOTATION = "kubeai.org/last-known-good-replicas"

# Federation planner (kubeai_tpu/federation/planner): stamped on a Model
# when a peer cluster partitions and this cluster takes over serving it.
# Value: the failed peer's cluster name, so heal-time failback can clear
# exactly the takeovers it owns. Every write is gated by
# ActuationGovernor.allow_federation_failover.
FEDERATION_FAILOVER_ANNOTATION = "kubeai.org/federation-failover-from"

# Progressive rollouts (kubeai_tpu/operator/rollout): stamped on a Model
# when the rollout judge condemns the in-flight spec hash — the pod plan
# treats the pinned (last-good) hash as desired and tears the condemned
# hash down. Value: the pod-hash to keep serving. Every write is gated by
# ActuationGovernor.allow_rollback and pinned to operator/rollout.py
# (scripts/check_actuation_paths.py enforces both).
ROLLOUT_PINNED_HASH_ANNOTATION = "kubeai.org/rollout-pinned-hash"

# Self-healing repair-backoff state (kubeai_tpu/operator/controller):
# JSON {"count": n, "last": wall_ts} persisted on the Model so an
# operator restart mid-backoff cannot issue duplicate repairs.
REPAIR_STATE_ANNOTATION = "kubeai.org/repair-state"

ADAPTER_LABEL_DOMAIN = "adapter.kubeai.org"
# Comma-separated adapter names whose routing label was removed but whose
# engine unload hasn't succeeded yet (409 while requests drain). Keeps the
# orphan discoverable across reconciles without querying every engine.
ADAPTER_PENDING_UNLOAD_ANNOTATION = "adapter.kubeai.org/pending-unload"


def adapter_label(adapter_id: str) -> str:
    return f"{ADAPTER_LABEL_DOMAIN}/{adapter_id}"


# Finalizer used for cache eviction on Model deletion
# (reference: api/k8s/v1/metadata.go:29-31).
CACHE_EVICTION_FINALIZER = "kubeai.org/cache-eviction"

# PVC annotation prefix tracking which model UID was loaded
# (reference: internal/modelcontroller/cache.go:94-123).
PVC_MODEL_ANNOTATION_PREFIX = "models.kubeai.org/"


def pvc_model_annotation(model_name: str) -> str:
    return PVC_MODEL_ANNOTATION_PREFIX + model_name
