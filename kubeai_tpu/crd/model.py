"""The Model custom resource (reference: api/k8s/v1/model_types.go).

Python dataclasses standing in for the CRD structs, with `validate()`
enforcing the reference's CEL + kubebuilder rules
(reference: api/k8s/v1/model_types.go:27-35,54-66,210-248) so invalid Models
are rejected at admission just like the real CRD would.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

FEATURE_TEXT_GENERATION = "TextGeneration"
FEATURE_TEXT_EMBEDDING = "TextEmbedding"
FEATURE_SPEECH_TO_TEXT = "SpeechToText"
ALL_FEATURES = (
    FEATURE_TEXT_GENERATION,
    FEATURE_TEXT_EMBEDDING,
    FEATURE_SPEECH_TO_TEXT,
)

# Engines (reference: api/k8s/v1/model_types.go:64-66 enum OLlama;VLLM;
# FasterWhisper;Infinity). KubeAITPU is the in-tree TPU-native engine that
# replaces external vLLM images for the TPU path.
ENGINE_KUBEAI_TPU = "KubeAITPU"
ENGINE_OLLAMA = "OLlama"
ENGINE_VLLM = "VLLM"
ENGINE_FASTER_WHISPER = "FasterWhisper"
ENGINE_INFINITY = "Infinity"
ALL_ENGINES = (
    ENGINE_KUBEAI_TPU,
    ENGINE_OLLAMA,
    ENGINE_VLLM,
    ENGINE_FASTER_WHISPER,
    ENGINE_INFINITY,
)

LB_STRATEGY_LEAST_LOAD = "LeastLoad"
LB_STRATEGY_PREFIX_HASH = "PrefixHash"

URL_SCHEMES = ("hf", "pvc", "ollama", "s3", "gs", "oss")

MAX_NAME_LEN = 40  # reference: api/k8s/v1/model_types.go:248
MAX_FILES = 10  # reference: api/k8s/v1/model_types.go:210-214
MAX_FILE_PATH_LEN = 1024
MAX_FILE_CONTENT_LEN = 100_000


class ValidationError(ValueError):
    pass


@dataclasses.dataclass
class Adapter:
    """(reference: api/k8s/v1/model_types.go:155-170)"""

    name: str = ""
    url: str = ""

    def validate(self) -> None:
        if not re.fullmatch(r"^[a-z0-9]+(?:[-a-z0-9]*[a-z0-9])?$", self.name or ""):
            raise ValidationError(f"adapter name {self.name!r} must be lowercase DNS label")
        if len(self.name) > 63:
            raise ValidationError("adapter name too long")
        if not self.url:
            raise ValidationError("adapter url required")


@dataclasses.dataclass
class File:
    """(reference: api/k8s/v1/model_types.go:210-224)"""

    path: str = ""
    content: str = ""

    def validate(self) -> None:
        if not self.path or len(self.path) > MAX_FILE_PATH_LEN:
            raise ValidationError("file path required, <= 1024 chars")
        if not self.path.startswith("/") or ".." in self.path:
            raise ValidationError(f"file path {self.path!r} must be absolute without '..'")
        if len(self.content) > MAX_FILE_CONTENT_LEN:
            raise ValidationError("file content too large")


@dataclasses.dataclass
class PrefixHash:
    """CHWBL tuning (reference: api/k8s/v1/model_types.go:190-208)."""

    mean_load_percentage: int = 125
    replication: int = 256
    prefix_char_length: int = 100

    def validate(self) -> None:
        if self.mean_load_percentage < 100:
            raise ValidationError("prefixHash.meanLoadPercentage must be >= 100")


@dataclasses.dataclass
class CircuitBreakerSpec:
    """Per-model circuit-breaker tuning (no reference analog — the
    reference trusts readiness probes alone). Every field defaults to 0
    meaning "inherit the system config `resilience:` default"; set
    fields override per model (kubeai_tpu/routing/health.py holds the
    state machine)."""

    # Sliding window of attempt outcomes considered by the rate rule.
    window: int = 0
    # Trip after this many consecutive failures.
    consecutive_failures: int = 0
    # Trip when >= minSamples outcomes are windowed and the failure
    # fraction reaches this rate (percent-free fraction in (0, 1]).
    failure_rate: float = 0.0
    min_samples: int = 0
    # Seconds an open circuit waits before admitting a half-open probe.
    open_seconds: float = 0.0

    def enabled(self) -> bool:
        return bool(
            self.window or self.consecutive_failures or self.failure_rate
            or self.min_samples or self.open_seconds
        )

    def validate(self) -> None:
        if self.window < 0:
            raise ValidationError("circuitBreaker.window must be >= 0")
        if self.consecutive_failures < 0:
            raise ValidationError(
                "circuitBreaker.consecutiveFailures must be >= 0"
            )
        if not 0.0 <= self.failure_rate <= 1.0:
            raise ValidationError(
                "circuitBreaker.failureRate must be in [0, 1], got "
                f"{self.failure_rate}"
            )
        if self.min_samples < 0:
            raise ValidationError("circuitBreaker.minSamples must be >= 0")
        if self.open_seconds < 0:
            raise ValidationError("circuitBreaker.openSeconds must be >= 0")


@dataclasses.dataclass
class LoadBalancing:
    """(reference: api/k8s/v1/model_types.go:172-188)"""

    strategy: str = LB_STRATEGY_LEAST_LOAD
    prefix_hash: PrefixHash = dataclasses.field(default_factory=PrefixHash)
    circuit_breaker: CircuitBreakerSpec = dataclasses.field(
        default_factory=CircuitBreakerSpec
    )

    def validate(self) -> None:
        if self.strategy not in (LB_STRATEGY_LEAST_LOAD, LB_STRATEGY_PREFIX_HASH):
            raise ValidationError(f"unknown loadBalancing.strategy {self.strategy!r}")
        self.prefix_hash.validate()
        self.circuit_breaker.validate()


# Priority classes of the in-tree engine's scheduler
# (kubeai_tpu/scheduling/scheduler.py PRIORITY_CLASSES — duplicated here so
# the CRD layer stays import-light and admission errors mention CRD terms).
SCHEDULING_PRIORITY_CLASSES = ("realtime", "standard", "batch")


@dataclasses.dataclass
class Scheduling:
    """SLO-aware queue discipline for the in-tree engine (no reference
    analog — the reference delegates queueing to vLLM). Rendered as
    engine flags --default-priority / --queue-shares / --max-deadline-ms
    (kubeai_tpu/operator/engines/kubeai_tpu_engine.py)."""

    # Priority class for requests without an X-Priority header.
    # "" = engine default ("standard").
    default_priority: str = ""
    # class -> guaranteed fraction of dispatches while backlogged, e.g.
    # {"batch": 0.05} keeps batch work trickling under realtime load.
    queue_shares: dict[str, float] = dataclasses.field(default_factory=dict)
    # Cap on client X-Deadline-Ms values AND the default admission
    # deadline when none is sent. 0 disables deadline admission.
    max_deadline_ms: int = 0

    def enabled(self) -> bool:
        return bool(
            self.default_priority or self.queue_shares or self.max_deadline_ms
        )

    def validate(self) -> None:
        if (
            self.default_priority
            and self.default_priority not in SCHEDULING_PRIORITY_CLASSES
        ):
            raise ValidationError(
                "scheduling.defaultPriority must be one of "
                f"{SCHEDULING_PRIORITY_CLASSES}, got {self.default_priority!r}"
            )
        for cls, share in self.queue_shares.items():
            if cls not in SCHEDULING_PRIORITY_CLASSES:
                raise ValidationError(
                    f"scheduling.queueShares: unknown class {cls!r}"
                )
            try:
                share = float(share)
            except (TypeError, ValueError):
                raise ValidationError(
                    f"scheduling.queueShares[{cls!r}] must be a number"
                )
            if not 0.0 <= share < 1.0:
                raise ValidationError(
                    f"scheduling.queueShares[{cls!r}] must be in [0, 1), "
                    f"got {share}"
                )
        if self.max_deadline_ms < 0:
            raise ValidationError("scheduling.maxDeadlineMs must be >= 0")


@dataclasses.dataclass
class Tenancy:
    """Per-model overrides for the front door's tenant admission layer
    (kubeai_tpu/fleet/tenancy; system `tenancy:` config holds the
    defaults). DOOR state: enforced before any work is queued, rendered
    into no engine flag or pod spec, and valid for every engine — the
    door fronts them all. A field set to 0 inherits the system default;
    `exempt: true` opts the model out of door admission entirely."""

    requests_per_second: float = 0.0
    request_burst: float = 0.0
    tokens_per_second: float = 0.0
    token_burst: float = 0.0
    window_seconds: float = 0.0
    window_token_budget: int = 0
    exempt: bool = False

    def enabled(self) -> bool:
        return bool(
            self.requests_per_second or self.request_burst
            or self.tokens_per_second or self.token_burst
            or self.window_seconds or self.window_token_budget
            or self.exempt
        )

    def validate(self) -> None:
        for field, value in (
            ("requestsPerSecond", self.requests_per_second),
            ("requestBurst", self.request_burst),
            ("tokensPerSecond", self.tokens_per_second),
            ("tokenBurst", self.token_burst),
            ("windowSeconds", self.window_seconds),
            ("windowTokenBudget", self.window_token_budget),
        ):
            try:
                ok = float(value) >= 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValidationError(
                    f"tenancy.{field} must be a number >= 0"
                )


@dataclasses.dataclass
class Slo:
    """Per-model service-level objectives for the SLO plane
    (kubeai_tpu/fleet/slo; system `slo:` config holds the defaults and
    the burn-rate windows). Pure observability/control-bias state: the
    evaluator judges these each tick from fleet snapshots, and a breach
    biases scaling — no engine flag or pod spec renders from this
    block. A field set to 0 inherits the system default; a model whose
    resolved targets are all 0 has no objectives and is never judged."""

    ttft_p95_seconds: float = 0.0   # 95% of requests see TTFT <= this
    itl_p99_seconds: float = 0.0    # 99% of tokens see ITL <= this
    availability: float = 0.0       # request success target, e.g. 0.999
    max_shed_rate: float = 0.0      # max fraction door-shed, e.g. 0.05

    def enabled(self) -> bool:
        return bool(
            self.ttft_p95_seconds or self.itl_p99_seconds
            or self.availability or self.max_shed_rate
        )

    def validate(self) -> None:
        for field, value in (
            ("ttftP95Seconds", self.ttft_p95_seconds),
            ("itlP99Seconds", self.itl_p99_seconds),
        ):
            try:
                ok = float(value) >= 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValidationError(f"slo.{field} must be a number >= 0")
        for field, value in (
            ("availability", self.availability),
            ("maxShedRate", self.max_shed_rate),
        ):
            try:
                ok = 0.0 <= float(value) < 1.0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValidationError(f"slo.{field} must be in [0, 1)")


ROLLOUT_STRATEGY_CANARY = "canary"
ROLLOUT_STRATEGIES = ("", ROLLOUT_STRATEGY_CANARY)


@dataclasses.dataclass
class RolloutJudge:
    """Comparative-judgment thresholds for a progressive rollout: the
    new hash is condemned when it looks WORSE than the old one by these
    margins, from the fleet plane's per-version aggregates. A field set
    to 0 inherits the rollout controller's default."""

    window_seconds: float = 0.0     # observation window per judgment
    ttft_p95_ratio: float = 0.0     # max new/old TTFT p95 ratio, e.g. 1.5
    max_breaker_trips: int = 0      # open circuits tolerated on the new hash

    def validate(self) -> None:
        for field, value in (
            ("windowSeconds", self.window_seconds),
            ("ttftP95Ratio", self.ttft_p95_ratio),
            ("maxBreakerTrips", self.max_breaker_trips),
        ):
            try:
                ok = float(value) >= 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValidationError(
                    f"rollout.judge.{field} must be a number >= 0"
                )


@dataclasses.dataclass
class Rollout:
    """Progressive-delivery policy for spec-hash changes
    (kubeai_tpu/operator/rollout). Operator-plane state: nothing here
    renders into an engine flag or pod spec — the rollout controller
    paces the pod plan through canary → ramp → complete, the LB
    enforces the canary traffic share at routing time, and the SLO
    machinery judges new vs old comparatively. No `rollout:` block (or
    strategy "") keeps the classic surge rollout byte-identical."""

    strategy: str = ""              # "" = classic surge; "canary"
    canary_percent: float = 10.0    # traffic+replica share of the canary step
    step_seconds: float = 60.0      # dwell per governed step
    max_unavailable: int = 0        # extra replicas replaceable per step
    auto_rollback: bool = True      # pin the old hash on a failed judgment
    judge: RolloutJudge = dataclasses.field(default_factory=RolloutJudge)

    def enabled(self) -> bool:
        return self.strategy == ROLLOUT_STRATEGY_CANARY

    def validate(self) -> None:
        if self.strategy not in ROLLOUT_STRATEGIES:
            raise ValidationError(
                f"rollout.strategy must be one of {ROLLOUT_STRATEGIES}"
            )
        try:
            pct_ok = 0.0 < float(self.canary_percent) <= 100.0
        except (TypeError, ValueError):
            pct_ok = False
        if self.enabled() and not pct_ok:
            raise ValidationError(
                "rollout.canaryPercent must be in (0, 100]"
            )
        for field, value in (
            ("stepSeconds", self.step_seconds),
            ("maxUnavailable", self.max_unavailable),
        ):
            try:
                ok = float(value) >= 0
            except (TypeError, ValueError):
                ok = False
            if not ok:
                raise ValidationError(
                    f"rollout.{field} must be a number >= 0"
                )
        self.judge.validate()


@dataclasses.dataclass
class RoleScaling:
    """Replica bounds for one disaggregated role's pod group. The
    autoscaler writes the applied count into a Model annotation
    (crd.metadata.role_replicas_annotation); these bounds clamp it."""

    min_replicas: int = 1
    max_replicas: int | None = None

    def validate(self, role: str) -> None:
        if self.min_replicas < 1:
            # Disaggregated groups do not scale to zero: a pool with no
            # prefill (or no decode) replicas can serve nothing, and the
            # proxy's fallback would silently absorb the whole model.
            raise ValidationError(
                f"disaggregation.{role}.minReplicas must be >= 1"
            )
        if self.max_replicas is not None and self.max_replicas < self.min_replicas:
            raise ValidationError(
                f"disaggregation.{role}.maxReplicas must be >= minReplicas"
            )


@dataclasses.dataclass
class Disaggregation:
    """Disaggregated prefill/decode serving (kubeai_tpu/disagg; no
    reference analog — the reference's vLLM replicas are monolithic).
    When enabled, the operator renders TWO pod groups (role labels
    prefill/decode, engine flag --role), the LB routes the two-hop
    prefill→decode flow, and the autoscaler scales each role from its
    own bottleneck signal: prefill from queue depth/oldest-wait/TTFT,
    decode from KV utilization and active-slot occupancy."""

    enabled: bool = False
    prefill: RoleScaling = dataclasses.field(default_factory=RoleScaling)
    decode: RoleScaling = dataclasses.field(default_factory=RoleScaling)
    # Queued prefills per prefill replica before another replica is asked
    # for (the prefill-role demand target).
    prefill_target_queue: int = 4
    # Mean engine TTFT (seconds) past which prefill is considered
    # pressured regardless of queue depth. 0 disables the TTFT signal.
    prefill_target_ttft_seconds: float = 0.0
    # KV-pool / slot-occupancy fraction the decode group scales to hold.
    decode_target_utilization: float = 0.8
    # Transfer limits: serialized-handoff size cap (0 = unlimited) and
    # the prefill engine's push timeout toward the decode pool.
    max_transfer_mb: int = 0
    transfer_timeout_seconds: float = 30.0

    def role(self, role: str) -> RoleScaling:
        if role == "prefill":
            return self.prefill
        if role == "decode":
            return self.decode
        raise KeyError(role)

    def validate(self) -> None:
        if not self.enabled:
            return
        self.prefill.validate("prefill")
        self.decode.validate("decode")
        if self.prefill_target_queue < 1:
            raise ValidationError(
                "disaggregation.prefillTargetQueue must be >= 1"
            )
        if self.prefill_target_ttft_seconds < 0:
            raise ValidationError(
                "disaggregation.prefillTargetTtftSeconds must be >= 0"
            )
        if not 0.0 < self.decode_target_utilization <= 1.0:
            raise ValidationError(
                "disaggregation.decodeTargetUtilization must be in (0, 1]"
            )
        if self.max_transfer_mb < 0:
            raise ValidationError(
                "disaggregation.maxTransferMB must be >= 0"
            )
        if self.transfer_timeout_seconds <= 0:
            raise ValidationError(
                "disaggregation.transferTimeoutSeconds must be > 0"
            )


@dataclasses.dataclass
class KVSharing:
    """Cluster-shared prefix/KV cache tier (in-tree engine only; no
    reference analog). When enabled, replicas publish their held
    page-hash chains through /v1/state, the LB routes base-model
    requests to the endpoint holding the deepest matching chain
    (falling back to classic CHWBL when the holdings map is stale or
    empty), and the serving replica pulls the common-prefix KV pages
    from the holding peer over the chunked-HTTP page-export transport
    instead of recomputing them."""

    enabled: bool = False
    # KV page size in tokens — must match the engine's --page-size so
    # the front-door chain hashes line up with the engine's prefix
    # cache keys.
    page_size: int = 16
    # Optional tokenizer directory for the front-door chain computer.
    # Empty = the deterministic byte tokenizer (matches an engine
    # serving without a model directory).
    tokenizer_dir: str = ""
    # Serialized page-export size cap per fetch (0 = unlimited) and the
    # requester's fetch timeout toward the holding peer.
    max_transfer_mb: int = 0
    fetch_timeout_seconds: float = 5.0
    # Optional object-store URL evicted idle pages spill to (and are
    # re-filled from). Empty = in-memory spill only.
    spill_url: str = ""

    def validate(self) -> None:
        if not self.enabled:
            return
        if self.page_size < 1:
            raise ValidationError("kvSharing.pageSize must be >= 1")
        if self.max_transfer_mb < 0:
            raise ValidationError("kvSharing.maxTransferMB must be >= 0")
        if self.fetch_timeout_seconds <= 0:
            raise ValidationError(
                "kvSharing.fetchTimeoutSeconds must be > 0"
            )


SNAPSHOT_URL_SCHEMES = ("gs", "s3", "oss", "file")


@dataclasses.dataclass
class ColdStart:
    """Serverless-grade cold start via engine snapshots (in-tree engine
    only; no reference analog). When enabled, a replica that boots the
    slow path (HF conversion + XLA compile) publishes its post-warmup
    state — orbax params + compilation-cache artifacts — under
    `snapshotURL`, keyed by a fingerprint of (model, engine config,
    mesh shape, snapshot version); later replicas restore from the
    snapshot instead, skipping conversion and most compilation. The
    operator tightens the startup-probe budget accordingly, and the
    capacity planner may prewarm replicas ahead of forecast demand
    (docs/concepts/cold-start.md)."""

    enabled: bool = False
    # Object-store URL the snapshot tree lives under (gs://, s3://,
    # oss://, or file:// for a shared filesystem mount).
    snapshot_url: str = ""
    # Whether a full-load boot publishes its snapshot for later
    # replicas (false = restore-only consumers).
    publish: bool = True
    # Whether the capacity planner may order predictive prewarm
    # replicas for this model.
    prewarm: bool = True

    def validate(self) -> None:
        if not self.enabled:
            return
        if not self.snapshot_url:
            raise ValidationError(
                "coldStart.snapshotURL required when coldStart.enabled"
            )
        scheme = (
            self.snapshot_url.split("://", 1)[0]
            if "://" in self.snapshot_url else ""
        )
        if scheme not in SNAPSHOT_URL_SCHEMES:
            raise ValidationError(
                "coldStart.snapshotURL scheme must be one of "
                f"{list(SNAPSHOT_URL_SCHEMES)}, got {self.snapshot_url!r}"
            )


KV_CACHE_DTYPES = ("bfloat16", "int8")


@dataclasses.dataclass
class KVCacheSpec:
    """Paged KV-cache storage configuration (in-tree engine only).
    dtype "int8" stores pages quantized with per-token-per-head scales
    (engine flag --kv-dtype): ~2x slot capacity at equal HBM and half
    the KV bytes on every disagg handoff, peer prefix fetch and
    objstore spill. Replicas of one model must agree on the dtype —
    bf16 and int8 pools refuse each other's KV on the wire rather
    than cast."""

    dtype: str = ""  # "" = engine default (bfloat16)

    def enabled(self) -> bool:
        return bool(self.dtype)

    def validate(self) -> None:
        if self.dtype and self.dtype not in KV_CACHE_DTYPES:
            raise ValidationError(
                f"kvCache.dtype must be one of {list(KV_CACHE_DTYPES)}"
            )


STEP_OVERLAP_MODES = ("auto", "on", "off")


@dataclasses.dataclass
class EngineStep:
    """Engine step-loop tuning (in-tree engine only). `overlap` drives
    the overlapped step pipeline (engine flag --step-overlap): dispatch
    decode chunk N+1 before reaping chunk N so readback, admission,
    detokenize and SSE fan-out hide behind device compute —
    token-identical to the synchronous loop. "auto" (the engine default)
    overlaps wherever the topology allows and degrades to synchronous
    for lockstep multihost and pipeline parallelism; "on" requires it
    (the engine refuses with a typed error where unsupported); "off"
    forces the synchronous loop."""

    overlap: str = ""  # "" = engine default (auto)

    def enabled(self) -> bool:
        return bool(self.overlap)

    def validate(self) -> None:
        if self.overlap and self.overlap not in STEP_OVERLAP_MODES:
            raise ValidationError(
                "engineStep.overlap must be one of "
                f"{list(STEP_OVERLAP_MODES)}"
            )


# Logical mesh axes a sharding block may size (SpecLayout vocabulary:
# data-parallel replicas, FSDP weight shards, tensor-parallel shards).
MESH_AXES = ("data", "fsdp", "tp")

_TOPOLOGY_RE = re.compile(r"^\d+x\d+(x\d+)?$")


@dataclasses.dataclass
class Sharding:
    """Multi-host slice-group serving (in-tree engine only). Declares
    that one replica is a *process group* of `hosts` pods spanning one
    ICI-connected TPU slice of the given `topology` (e.g. "4x4"), with
    the model partitioned over the logical `mesh` axes (data/fsdp/tp).
    The operator then plans, repairs, routes, and bin-packs the group
    as one atomic unit — never a partial group. hosts=0 / topology=""
    inherit the resource profile's values; an explicit value here wins
    over the profile."""

    hosts: int = 0  # host pods per replica; 0 = profile default
    topology: str = ""  # ICI slice topology, e.g. "4x4" / "4x4x4"
    mesh: dict[str, int] = dataclasses.field(default_factory=dict)

    def enabled(self) -> bool:
        return bool(self.hosts or self.topology or self.mesh)

    def validate(self) -> None:
        if self.hosts < 0:
            raise ValidationError("sharding.hosts must be >= 0")
        if self.topology and not _TOPOLOGY_RE.match(self.topology):
            raise ValidationError(
                'sharding.topology must look like "4x4" or "4x4x4", '
                f"got {self.topology!r}"
            )
        for axis, size in self.mesh.items():
            if axis not in MESH_AXES:
                raise ValidationError(
                    f"sharding.mesh axis must be one of {list(MESH_AXES)}, "
                    f"got {axis!r}"
                )
            if not isinstance(size, int) or size < 1:
                raise ValidationError(
                    f"sharding.mesh[{axis!r}] must be an integer >= 1"
                )


@dataclasses.dataclass
class ModelSpec:
    """(reference: api/k8s/v1/model_types.go:36-144)"""

    url: str = ""
    engine: str = ENGINE_KUBEAI_TPU
    features: list[str] = dataclasses.field(default_factory=list)
    adapters: list[Adapter] = dataclasses.field(default_factory=list)
    resource_profile: str = ""  # "name:count"
    cache_profile: str = ""  # immutable (reference: model_types.go:76)
    image: str = ""
    args: list[str] = dataclasses.field(default_factory=list)
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    env_from: list[dict] = dataclasses.field(default_factory=list)
    replicas: int | None = None
    min_replicas: int = 0
    max_replicas: int | None = None
    autoscaling_disabled: bool = False
    target_requests: int = 100  # reference: model_types.go:115
    scale_down_delay_seconds: int = 30  # reference: model_types.go:120
    load_balancing: LoadBalancing = dataclasses.field(default_factory=LoadBalancing)
    files: list[File] = dataclasses.field(default_factory=list)
    priority_class_name: str = ""
    owner: str = ""
    # Speculative decoding (in-tree engine only; no reference analog —
    # there, engine features ride spec.args, model_types.go:85-90):
    # speculativeTokens > 0 turns on prompt-lookup speculation;
    # draftUrl additionally loads a small same-family draft model that
    # proposes instead of the lookup (engine flags --speculate /
    # --draft-url, kubeai_tpu/engine/server.py).
    speculative_tokens: int = 0
    draft_url: str = ""
    # SLO-aware queue discipline (in-tree engine only).
    scheduling: Scheduling = dataclasses.field(default_factory=Scheduling)
    # Front-door tenant admission overrides (door state, every engine).
    tenancy: Tenancy = dataclasses.field(default_factory=Tenancy)
    # Per-model SLO targets (observability/control-bias, every engine).
    slo: Slo = dataclasses.field(default_factory=Slo)
    # Progressive-delivery policy (operator plane, every engine).
    rollout: Rollout = dataclasses.field(default_factory=Rollout)
    # Disaggregated prefill/decode serving (in-tree engine only).
    disaggregation: Disaggregation = dataclasses.field(
        default_factory=Disaggregation
    )
    # Cluster-shared prefix/KV cache tier (in-tree engine only).
    kv_sharing: KVSharing = dataclasses.field(default_factory=KVSharing)
    # Paged KV-cache storage dtype (in-tree engine only).
    kv_cache: KVCacheSpec = dataclasses.field(default_factory=KVCacheSpec)
    # Engine snapshot/restore cold-start path (in-tree engine only).
    cold_start: ColdStart = dataclasses.field(default_factory=ColdStart)
    # Engine step-loop tuning (overlapped step pipeline; in-tree only).
    engine_step: EngineStep = dataclasses.field(default_factory=EngineStep)
    # Multi-host slice-group serving (in-tree engine only).
    sharding: Sharding = dataclasses.field(default_factory=Sharding)
    # Graceful-drain budget: seconds an engine waits for in-flight
    # generations after SIGTERM / POST /v1/drain before terminating the
    # remainder. 0 = the system config `resilience.drainTimeout`
    # default. Rendered as the engine's --drain-timeout flag plus the
    # Pod's terminationGracePeriodSeconds and preStop hook.
    drain_timeout_seconds: int = 0

    def url_scheme(self) -> str:
        return self.url.split("://", 1)[0] if "://" in self.url else ""

    def validate(self) -> None:
        # url scheme CEL rule (reference: model_types.go:54).
        if not self.url:
            raise ValidationError("spec.url required")
        if self.url_scheme() not in URL_SCHEMES:
            raise ValidationError(
                f"spec.url scheme must be one of {URL_SCHEMES}, got {self.url!r}"
            )
        if self.engine not in ALL_ENGINES:
            raise ValidationError(f"spec.engine must be one of {ALL_ENGINES}")
        for f in self.features:
            if f not in ALL_FEATURES:
                raise ValidationError(f"unknown feature {f!r}")
        # cross-field CEL rules (reference: model_types.go:27-35):
        if self.engine == ENGINE_OLLAMA and self.url_scheme() not in ("ollama", "pvc"):
            raise ValidationError("OLlama engine requires ollama:// or pvc:// url")
        if self.url_scheme() == "ollama" and self.engine != ENGINE_OLLAMA:
            raise ValidationError("ollama:// url requires engine OLlama")
        if self.min_replicas < 0:
            raise ValidationError("minReplicas must be >= 0")
        if self.max_replicas is not None and self.max_replicas < max(self.min_replicas, 1):
            raise ValidationError("maxReplicas must be >= minReplicas and >= 1")
        if self.replicas is not None and self.replicas < 0:
            raise ValidationError("replicas must be >= 0")
        # A nil maxReplicas is VALID (unbounded autoscaling) — reference
        # CEL only relates the bounds when both are set
        # (reference: model_types.go:30, test replicas-1-2-nil-valid).
        if self.cache_profile and self.url_scheme() not in (
            "hf", "s3", "gs", "oss"
        ):
            # reference CEL rule (model_types.go:27).
            raise ValidationError(
                'cacheProfile is only supported with urls of format "hf://", '
                '"s3://", "gs://", or "oss://"'
            )
        if self.adapters and self.engine not in (ENGINE_VLLM, ENGINE_KUBEAI_TPU):
            # reference CEL restricts adapters to VLLM (model_types.go:31);
            # the in-tree TPU engine hot-swaps adapters natively too.
            raise ValidationError(
                "adapters only supported with VLLM or KubeAITPU engines"
            )
        if self.speculative_tokens < 0:
            raise ValidationError("speculativeTokens must be >= 0")
        if (
            self.speculative_tokens or self.draft_url
        ) and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "speculativeTokens/draftUrl require the KubeAITPU engine"
            )
        if self.draft_url:
            if self.speculative_tokens < 1:
                # Mirrors the engine-server flag contract (--draft-url
                # requires --speculate > 0, kubeai_tpu/engine/server.py).
                raise ValidationError(
                    "draftUrl requires speculativeTokens >= 1"
                )
            draft_scheme = (
                self.draft_url.split("://", 1)[0]
                if "://" in self.draft_url else ""
            )
            if draft_scheme not in ("hf", "pvc", "s3", "gs", "oss"):
                raise ValidationError(
                    'draftUrl must use "hf://", "pvc://", "s3://", '
                    f'"gs://", or "oss://", got {self.draft_url!r}'
                )
        self.scheduling.validate()
        if self.scheduling.enabled() and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.scheduling requires the KubeAITPU engine"
            )
        # Deliberately no engine gate: tenancy is door state, enforced
        # before any engine sees the request.
        self.tenancy.validate()
        # Same: SLO targets are judged from the fleet plane — no engine
        # needs to know them.
        self.slo.validate()
        # Same: rollout pacing is operator-plane state; no engine flag
        # or pod spec renders from it.
        self.rollout.validate()
        self.disaggregation.validate()
        if self.disaggregation.enabled and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.disaggregation requires the KubeAITPU engine"
            )
        self.kv_sharing.validate()
        if self.kv_sharing.enabled and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.kvSharing requires the KubeAITPU engine"
            )
        self.kv_cache.validate()
        if self.kv_cache.enabled() and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.kvCache requires the KubeAITPU engine"
            )
        self.cold_start.validate()
        if self.cold_start.enabled and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.coldStart requires the KubeAITPU engine"
            )
        self.engine_step.validate()
        if self.engine_step.enabled() and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.engineStep requires the KubeAITPU engine"
            )
        self.sharding.validate()
        if self.sharding.enabled() and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.sharding requires the KubeAITPU engine"
            )
        if self.kv_cache.dtype == "int8" and self.speculative_tokens:
            raise ValidationError(
                "kvCache.dtype=int8 does not compose with "
                "speculativeTokens (the verify kernels read bf16 pools)"
            )
        if self.drain_timeout_seconds < 0:
            raise ValidationError("drainTimeoutSeconds must be >= 0")
        if self.drain_timeout_seconds and self.engine != ENGINE_KUBEAI_TPU:
            raise ValidationError(
                "spec.drainTimeoutSeconds requires the KubeAITPU engine"
            )
        if self.target_requests < 1:
            raise ValidationError("targetRequests must be >= 1")
        if self.scale_down_delay_seconds < 0:
            raise ValidationError("scaleDownDelaySeconds must be >= 0")
        if self.resource_profile:
            parts = self.resource_profile.split(":")
            if len(parts) != 2 or not parts[0]:
                raise ValidationError(
                    'resourceProfile must be "name:count"'
                )
            try:
                count = int(parts[1])
            except ValueError:
                raise ValidationError("resourceProfile count must be an integer")
            if count < 1:
                raise ValidationError("resourceProfile count must be >= 1")
        if len(self.files) > MAX_FILES:
            raise ValidationError(f"at most {MAX_FILES} files allowed")
        seen_paths = set()
        for f in self.files:
            f.validate()
            if f.path in seen_paths:
                raise ValidationError(f"duplicate file path {f.path}")
            seen_paths.add(f.path)
        seen_adapters = set()
        for a in self.adapters:
            a.validate()
            if a.name in seen_adapters:
                raise ValidationError(f"duplicate adapter {a.name}")
            seen_adapters.add(a.name)
        self.load_balancing.validate()


def disagg_role_replicas(model: "Model", role: str) -> int:
    """The replica count a disaggregated role's pod group should run:
    the autoscaler's annotation when present, else the role's floor —
    always clamped into the CRD bounds (and never below 1; a role pool
    at zero can serve nothing)."""
    from kubeai_tpu.crd import metadata as md

    rs = model.spec.disaggregation.role(role)
    raw = model.annotations.get(md.role_replicas_annotation(role))
    try:
        n = int(raw) if raw is not None else rs.min_replicas
    except (TypeError, ValueError):
        n = rs.min_replicas
    n = max(n, rs.min_replicas, 1)
    if rs.max_replicas is not None:
        n = min(n, rs.max_replicas)
    return n


@dataclasses.dataclass
class ModelStatus:
    """(reference: api/k8s/v1/model_types.go:226-239; `conditions` has no
    reference analog — the reference Model publishes bare replica counts)."""

    replicas_all: int = 0
    replicas_ready: int = 0
    cache_loaded: bool = False
    # Kubernetes-style conditions maintained by the reconciler's
    # pod-health pass: Ready / Progressing / Degraded, each a dict with
    # stable `type` / `status` ("True"/"False") / `reason` / `message`
    # keys (reasons documented in docs/concepts/resilience.md).
    conditions: list[dict] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Model:
    """A Model resource instance (metadata + spec + status)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 1
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: dict[str, str] = dataclasses.field(default_factory=dict)
    finalizers: list[str] = dataclasses.field(default_factory=list)
    deletion_timestamp: float | None = None
    spec: ModelSpec = dataclasses.field(default_factory=ModelSpec)
    status: ModelStatus = dataclasses.field(default_factory=ModelStatus)

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("metadata.name required")
        # name <= 40 chars so name+suffixes fit k8s limits
        # (reference: api/k8s/v1/model_types.go:248).
        if len(self.name) > MAX_NAME_LEN:
            raise ValidationError(f"model name must be <= {MAX_NAME_LEN} chars")
        # DNS-1123 subdomain: dot-separated DNS labels — the reference
        # catalog ships names like "llama-3.1-8b-instruct-tpu"
        # (reference: charts/models/values.yaml). Each label must stand
        # alone ("a..b" / "a.-b" are invalid).
        label = r"[a-z0-9](?:[-a-z0-9]*[a-z0-9])?"
        if not re.fullmatch(rf"{label}(?:\.{label})*", self.name):
            raise ValidationError(
                "model name must be a lowercase DNS subdomain"
            )
        self.spec.validate()

    def validate_update(self, old: "Model") -> None:
        self.validate()
        # cacheProfile is immutable (reference: model_types.go:76-78).
        if old.spec.cache_profile != self.spec.cache_profile:
            raise ValidationError("spec.cacheProfile is immutable")
        if old.spec.url != self.spec.url and old.spec.cache_profile:
            raise ValidationError("spec.url is immutable when cacheProfile is set")

    # -- dict round trip (k8s manifest shape) --------------------------------

    def to_dict(self) -> dict:
        return {
            "apiVersion": "kubeai.org/v1",
            "kind": "Model",
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "uid": self.uid,
                "resourceVersion": str(self.resource_version),
                "generation": self.generation,
                "labels": dict(self.labels),
                "annotations": dict(self.annotations),
                "finalizers": list(self.finalizers),
                **(
                    {"deletionTimestamp": self.deletion_timestamp}
                    if self.deletion_timestamp
                    else {}
                ),
            },
            "spec": _spec_to_dict(self.spec),
            "status": {
                "replicas": {
                    "all": self.status.replicas_all,
                    "ready": self.status.replicas_ready,
                },
                "cache": {"loaded": self.status.cache_loaded},
                **(
                    {"conditions": [dict(c) for c in self.status.conditions]}
                    if self.status.conditions
                    else {}
                ),
            },
        }

    @staticmethod
    def from_dict(d: dict) -> "Model":
        meta = d.get("metadata", {})
        spec = d.get("spec", {})
        status = d.get("status", {}) or {}
        lb = spec.get("loadBalancing", {}) or {}
        ph = lb.get("prefixHash", {}) or {}
        cb = lb.get("circuitBreaker", {}) or {}
        dis = spec.get("disaggregation", {}) or {}
        kvs = spec.get("kvSharing", {}) or {}
        kvc = spec.get("kvCache", {}) or {}
        cold = spec.get("coldStart", {}) or {}
        estep = spec.get("engineStep", {}) or {}
        shd = spec.get("sharding", {}) or {}
        ten = spec.get("tenancy", {}) or {}
        slo = spec.get("slo", {}) or {}
        ro = spec.get("rollout", {}) or {}
        roj = ro.get("judge", {}) or {}

        def _role_scaling(key: str) -> RoleScaling:
            r = dis.get(key) or {}
            return RoleScaling(
                min_replicas=int(r.get("minReplicas", 1) or 1),
                max_replicas=r.get("maxReplicas"),
            )

        return Model(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            resource_version=int(meta.get("resourceVersion", 0) or 0),
            generation=int(meta.get("generation", 1)),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
            finalizers=list(meta.get("finalizers") or []),
            deletion_timestamp=meta.get("deletionTimestamp"),
            spec=ModelSpec(
                url=spec.get("url", ""),
                engine=spec.get("engine", ENGINE_KUBEAI_TPU),
                features=list(spec.get("features") or []),
                adapters=[
                    Adapter(name=a.get("name", ""), url=a.get("url", ""))
                    for a in (spec.get("adapters") or [])
                ],
                resource_profile=spec.get("resourceProfile", ""),
                cache_profile=spec.get("cacheProfile", ""),
                image=spec.get("image", ""),
                args=list(spec.get("args") or []),
                env=dict(spec.get("env") or {}),
                env_from=list(spec.get("envFrom") or []),
                replicas=spec.get("replicas"),
                min_replicas=int(spec.get("minReplicas", 0) or 0),
                max_replicas=spec.get("maxReplicas"),
                autoscaling_disabled=bool(spec.get("autoscalingDisabled", False)),
                target_requests=int(spec.get("targetRequests", 100)),
                scale_down_delay_seconds=int(spec.get("scaleDownDelaySeconds", 30)),
                load_balancing=LoadBalancing(
                    strategy=lb.get("strategy", LB_STRATEGY_LEAST_LOAD),
                    prefix_hash=PrefixHash(
                        mean_load_percentage=int(ph.get("meanLoadPercentage", 125)),
                        replication=int(ph.get("replication", 256)),
                        prefix_char_length=int(ph.get("prefixCharLength", 100)),
                    ),
                    circuit_breaker=CircuitBreakerSpec(
                        window=int(cb.get("window", 0) or 0),
                        consecutive_failures=int(
                            cb.get("consecutiveFailures", 0) or 0
                        ),
                        failure_rate=float(cb.get("failureRate", 0) or 0),
                        min_samples=int(cb.get("minSamples", 0) or 0),
                        open_seconds=float(cb.get("openSeconds", 0) or 0),
                    ),
                ),
                files=[
                    File(path=f.get("path", ""), content=f.get("content", ""))
                    for f in (spec.get("files") or [])
                ],
                priority_class_name=spec.get("priorityClassName", ""),
                owner=spec.get("owner", ""),
                speculative_tokens=int(spec.get("speculativeTokens", 0) or 0),
                draft_url=spec.get("draftUrl", ""),
                drain_timeout_seconds=int(
                    spec.get("drainTimeoutSeconds", 0) or 0
                ),
                scheduling=Scheduling(
                    default_priority=(
                        (spec.get("scheduling") or {}).get("defaultPriority", "")
                    ),
                    queue_shares={
                        k: float(v)
                        for k, v in (
                            (spec.get("scheduling") or {}).get("queueShares")
                            or {}
                        ).items()
                    },
                    max_deadline_ms=int(
                        (spec.get("scheduling") or {}).get("maxDeadlineMs", 0)
                        or 0
                    ),
                ),
                tenancy=Tenancy(
                    requests_per_second=float(
                        ten.get("requestsPerSecond", 0) or 0
                    ),
                    request_burst=float(ten.get("requestBurst", 0) or 0),
                    tokens_per_second=float(
                        ten.get("tokensPerSecond", 0) or 0
                    ),
                    token_burst=float(ten.get("tokenBurst", 0) or 0),
                    window_seconds=float(ten.get("windowSeconds", 0) or 0),
                    window_token_budget=int(
                        ten.get("windowTokenBudget", 0) or 0
                    ),
                    exempt=bool(ten.get("exempt", False)),
                ),
                slo=Slo(
                    ttft_p95_seconds=float(
                        slo.get("ttftP95Seconds", 0) or 0
                    ),
                    itl_p99_seconds=float(slo.get("itlP99Seconds", 0) or 0),
                    availability=float(slo.get("availability", 0) or 0),
                    max_shed_rate=float(slo.get("maxShedRate", 0) or 0),
                ),
                rollout=Rollout(
                    strategy=ro.get("strategy", "") or "",
                    canary_percent=float(
                        ro.get("canaryPercent", 10.0) or 10.0
                    ),
                    step_seconds=float(ro.get("stepSeconds", 60.0) or 60.0),
                    max_unavailable=int(ro.get("maxUnavailable", 0) or 0),
                    auto_rollback=bool(ro.get("autoRollback", True)),
                    judge=RolloutJudge(
                        window_seconds=float(
                            roj.get("windowSeconds", 0) or 0
                        ),
                        ttft_p95_ratio=float(
                            roj.get("ttftP95Ratio", 0) or 0
                        ),
                        max_breaker_trips=int(
                            roj.get("maxBreakerTrips", 0) or 0
                        ),
                    ),
                ),
                disaggregation=Disaggregation(
                    enabled=bool(dis.get("enabled", False)),
                    prefill=_role_scaling("prefill"),
                    decode=_role_scaling("decode"),
                    prefill_target_queue=int(
                        dis.get("prefillTargetQueue", 4) or 4
                    ),
                    prefill_target_ttft_seconds=float(
                        dis.get("prefillTargetTtftSeconds", 0) or 0
                    ),
                    decode_target_utilization=float(
                        dis.get("decodeTargetUtilization", 0.8) or 0.8
                    ),
                    max_transfer_mb=int(dis.get("maxTransferMB", 0) or 0),
                    transfer_timeout_seconds=float(
                        dis.get("transferTimeoutSeconds", 30) or 30
                    ),
                ),
                kv_sharing=KVSharing(
                    enabled=bool(kvs.get("enabled", False)),
                    page_size=int(kvs.get("pageSize", 16) or 16),
                    tokenizer_dir=kvs.get("tokenizerDir", ""),
                    max_transfer_mb=int(kvs.get("maxTransferMB", 0) or 0),
                    fetch_timeout_seconds=float(
                        kvs.get("fetchTimeoutSeconds", 5) or 5
                    ),
                    spill_url=kvs.get("spillURL", ""),
                ),
                kv_cache=KVCacheSpec(
                    dtype=kvc.get("dtype", "") or "",
                ),
                cold_start=ColdStart(
                    enabled=bool(cold.get("enabled", False)),
                    snapshot_url=cold.get("snapshotURL", ""),
                    publish=bool(cold.get("publish", True)),
                    prewarm=bool(cold.get("prewarm", True)),
                ),
                engine_step=EngineStep(
                    overlap=estep.get("overlap", "") or "",
                ),
                sharding=Sharding(
                    hosts=int(shd.get("hosts", 0) or 0),
                    topology=shd.get("topology", "") or "",
                    mesh={
                        k: int(v)
                        for k, v in (shd.get("mesh") or {}).items()
                    },
                ),
            ),
            status=ModelStatus(
                replicas_all=int(
                    ((status.get("replicas") or {}).get("all", 0))
                ),
                replicas_ready=int(
                    ((status.get("replicas") or {}).get("ready", 0))
                ),
                cache_loaded=bool((status.get("cache") or {}).get("loaded", False)),
                conditions=[
                    dict(c) for c in (status.get("conditions") or [])
                    if isinstance(c, dict)
                ],
            ),
        )


def _spec_to_dict(s: ModelSpec) -> dict:
    d: dict[str, Any] = {
        "url": s.url,
        "engine": s.engine,
        "features": list(s.features),
    }
    if s.adapters:
        d["adapters"] = [{"name": a.name, "url": a.url} for a in s.adapters]
    if s.resource_profile:
        d["resourceProfile"] = s.resource_profile
    if s.cache_profile:
        d["cacheProfile"] = s.cache_profile
    if s.image:
        d["image"] = s.image
    if s.args:
        d["args"] = list(s.args)
    if s.env:
        d["env"] = dict(s.env)
    if s.env_from:
        d["envFrom"] = list(s.env_from)
    if s.replicas is not None:
        d["replicas"] = s.replicas
    d["minReplicas"] = s.min_replicas
    if s.max_replicas is not None:
        d["maxReplicas"] = s.max_replicas
    if s.autoscaling_disabled:
        d["autoscalingDisabled"] = True
    d["targetRequests"] = s.target_requests
    d["scaleDownDelaySeconds"] = s.scale_down_delay_seconds
    d["loadBalancing"] = {
        "strategy": s.load_balancing.strategy,
        "prefixHash": {
            "meanLoadPercentage": s.load_balancing.prefix_hash.mean_load_percentage,
            "replication": s.load_balancing.prefix_hash.replication,
            "prefixCharLength": s.load_balancing.prefix_hash.prefix_char_length,
        },
    }
    cb = s.load_balancing.circuit_breaker
    if cb.enabled():
        cbd: dict[str, Any] = {}
        if cb.window:
            cbd["window"] = cb.window
        if cb.consecutive_failures:
            cbd["consecutiveFailures"] = cb.consecutive_failures
        if cb.failure_rate:
            cbd["failureRate"] = cb.failure_rate
        if cb.min_samples:
            cbd["minSamples"] = cb.min_samples
        if cb.open_seconds:
            cbd["openSeconds"] = cb.open_seconds
        d["loadBalancing"]["circuitBreaker"] = cbd
    if s.drain_timeout_seconds:
        d["drainTimeoutSeconds"] = s.drain_timeout_seconds
    if s.files:
        d["files"] = [{"path": f.path, "content": f.content} for f in s.files]
    if s.priority_class_name:
        d["priorityClassName"] = s.priority_class_name
    if s.owner:
        d["owner"] = s.owner
    if s.speculative_tokens:
        d["speculativeTokens"] = s.speculative_tokens
    if s.draft_url:
        d["draftUrl"] = s.draft_url
    if s.scheduling.enabled():
        sched: dict[str, Any] = {}
        if s.scheduling.default_priority:
            sched["defaultPriority"] = s.scheduling.default_priority
        if s.scheduling.queue_shares:
            sched["queueShares"] = dict(s.scheduling.queue_shares)
        if s.scheduling.max_deadline_ms:
            sched["maxDeadlineMs"] = s.scheduling.max_deadline_ms
        d["scheduling"] = sched
    if s.tenancy.enabled():
        ten: dict[str, Any] = {}
        if s.tenancy.requests_per_second:
            ten["requestsPerSecond"] = s.tenancy.requests_per_second
        if s.tenancy.request_burst:
            ten["requestBurst"] = s.tenancy.request_burst
        if s.tenancy.tokens_per_second:
            ten["tokensPerSecond"] = s.tenancy.tokens_per_second
        if s.tenancy.token_burst:
            ten["tokenBurst"] = s.tenancy.token_burst
        if s.tenancy.window_seconds:
            ten["windowSeconds"] = s.tenancy.window_seconds
        if s.tenancy.window_token_budget:
            ten["windowTokenBudget"] = s.tenancy.window_token_budget
        if s.tenancy.exempt:
            ten["exempt"] = True
        d["tenancy"] = ten
    if s.slo.enabled():
        slo: dict[str, Any] = {}
        if s.slo.ttft_p95_seconds:
            slo["ttftP95Seconds"] = s.slo.ttft_p95_seconds
        if s.slo.itl_p99_seconds:
            slo["itlP99Seconds"] = s.slo.itl_p99_seconds
        if s.slo.availability:
            slo["availability"] = s.slo.availability
        if s.slo.max_shed_rate:
            slo["maxShedRate"] = s.slo.max_shed_rate
        d["slo"] = slo
    if s.rollout.enabled():
        ro = s.rollout
        rod: dict[str, Any] = {
            "strategy": ro.strategy,
            "canaryPercent": ro.canary_percent,
            "stepSeconds": ro.step_seconds,
        }
        if ro.max_unavailable:
            rod["maxUnavailable"] = ro.max_unavailable
        if not ro.auto_rollback:
            rod["autoRollback"] = False
        jd: dict[str, Any] = {}
        if ro.judge.window_seconds:
            jd["windowSeconds"] = ro.judge.window_seconds
        if ro.judge.ttft_p95_ratio:
            jd["ttftP95Ratio"] = ro.judge.ttft_p95_ratio
        if ro.judge.max_breaker_trips:
            jd["maxBreakerTrips"] = ro.judge.max_breaker_trips
        if jd:
            rod["judge"] = jd
        d["rollout"] = rod
    if s.disaggregation.enabled:
        dis = s.disaggregation

        def _role_dict(r: RoleScaling) -> dict:
            out: dict[str, Any] = {"minReplicas": r.min_replicas}
            if r.max_replicas is not None:
                out["maxReplicas"] = r.max_replicas
            return out

        d["disaggregation"] = {
            "enabled": True,
            "prefill": _role_dict(dis.prefill),
            "decode": _role_dict(dis.decode),
            "prefillTargetQueue": dis.prefill_target_queue,
            "decodeTargetUtilization": dis.decode_target_utilization,
            **(
                {"prefillTargetTtftSeconds": dis.prefill_target_ttft_seconds}
                if dis.prefill_target_ttft_seconds
                else {}
            ),
            **(
                {"maxTransferMB": dis.max_transfer_mb}
                if dis.max_transfer_mb
                else {}
            ),
            "transferTimeoutSeconds": dis.transfer_timeout_seconds,
        }
    if s.kv_sharing.enabled:
        kvs = s.kv_sharing
        d["kvSharing"] = {
            "enabled": True,
            "pageSize": kvs.page_size,
            **(
                {"tokenizerDir": kvs.tokenizer_dir}
                if kvs.tokenizer_dir
                else {}
            ),
            **(
                {"maxTransferMB": kvs.max_transfer_mb}
                if kvs.max_transfer_mb
                else {}
            ),
            "fetchTimeoutSeconds": kvs.fetch_timeout_seconds,
            **({"spillURL": kvs.spill_url} if kvs.spill_url else {}),
        }
    if s.kv_cache.enabled():
        d["kvCache"] = {"dtype": s.kv_cache.dtype}
    if s.engine_step.enabled():
        d["engineStep"] = {"overlap": s.engine_step.overlap}
    if s.sharding.enabled():
        shd: dict[str, Any] = {}
        if s.sharding.hosts:
            shd["hosts"] = s.sharding.hosts
        if s.sharding.topology:
            shd["topology"] = s.sharding.topology
        if s.sharding.mesh:
            shd["mesh"] = dict(s.sharding.mesh)
        d["sharding"] = shd
    if s.cold_start.enabled:
        cold = s.cold_start
        d["coldStart"] = {
            "enabled": True,
            "snapshotURL": cold.snapshot_url,
            **({} if cold.publish else {"publish": False}),
            **({} if cold.prewarm else {"prewarm": False}),
        }
    return d
