"""Model custom-resource types (reference: api/k8s/v1)."""

from kubeai_tpu.crd.model import (
    Model,
    ModelSpec,
    ModelStatus,
    Adapter,
    File,
    LoadBalancing,
    PrefixHash,
    ValidationError,
    FEATURE_TEXT_GENERATION,
    FEATURE_TEXT_EMBEDDING,
    FEATURE_SPEECH_TO_TEXT,
    ENGINE_KUBEAI_TPU,
    ENGINE_OLLAMA,
    ENGINE_VLLM,
    ENGINE_FASTER_WHISPER,
    ENGINE_INFINITY,
)
from kubeai_tpu.crd import metadata
