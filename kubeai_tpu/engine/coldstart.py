"""Serverless-grade engine cold start: snapshot restore-first boot.

Replica birth used to cost full HF-weight conversion plus XLA
compilation on every scale-from-zero, preemption repair, and planner
preemption. This module makes it cost a streamed restore instead
(PAPERS.md: SLINFER — replica birth should be a snapshot restore, not a
recompilation):

  1. `ColdStartManager.acquire_params` asks the `SnapshotStore` for a
     snapshot keyed by (model, engine-config fingerprint, mesh shape,
     snapshot version). Hit → chunk-parallel fetch + orbax restore of
     the post-conversion param tree, and the bundled JAX persistent
     compilation cache makes the first jit ~a cache read. Miss or
     `SnapshotMismatch` (NEVER serve a stale layout) → the full load
     path, unchanged.
  2. After warm-up (so the compilation cache holds the serving graphs),
     `maybe_publish` writes the snapshot back on first boot — the next
     replica of this exact configuration restores.

Every boot is phase-timed (`fetch` / `restore` / `load` / `compile` /
`warmup`) into `ColdStartTracker`, exported as `kubeai_coldstart_*`
metrics and on `/v1/state` so the fleet's demand forecaster can price
each model's measured cold-start cost into prewarm and preemption
decisions.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import shutil
import tempfile
import time
from collections.abc import Mapping

logger = logging.getLogger(__name__)

# Phase vocabulary (fixed so dashboards and the forecaster can rely on
# it): restore-path boots time fetch/restore, full-load boots time load;
# compile (first generate, jit) and warmup (second generate,
# steady-state) are measured on both paths.
PHASES = ("fetch", "restore", "load", "compile", "warmup")

# Snapshot events exported with counter semantics.
EVENTS = ("restored", "published", "mismatch", "absent", "error")


class ColdStartTracker:
    """Per-phase wall timings for one engine boot (injectable clock so
    the fake-clock sim drives it deterministically)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self._total: float | None = None
        self.phases: dict[str, float] = {}
        self.events: list[str] = []
        self.restored = False
        self.fingerprint = ""

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = self._clock()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (
                self._clock() - t0
            )

    def event(self, name: str) -> None:
        self.events.append(name)

    def finish(self) -> float:
        self._total = self._clock() - self._t0
        return self._total

    @property
    def total_s(self) -> float:
        return self._total if self._total is not None else (
            self._clock() - self._t0
        )

    def snapshot(self) -> dict:
        """The `/v1/state` cold_start block (and the metric source)."""
        return {
            "restored": self.restored,
            "fingerprint": self.fingerprint,
            "phases": dict(self.phases),
            "total_s": round(self.total_s, 6),
            "events": list(self.events),
        }


def mesh_signature(mesh) -> list:
    """Deterministic mesh identity for the snapshot key: axis sizes when
    the mesh exposes a name->size mapping, device-grid shape otherwise.
    Any change here must miss the snapshot — a tree sharded for a
    different slice shape is a stale layout."""
    shape = getattr(mesh, "shape", None)
    if isinstance(shape, Mapping):
        return [f"{k}={v}" for k, v in shape.items()]
    devices = getattr(mesh, "devices", None)
    if devices is not None and hasattr(devices, "shape"):
        return list(devices.shape)
    return []


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at `cache_dir` with the
    thresholds zeroed so every serving graph is cached (the defaults
    skip fast compiles — exactly the ones a CPU-fallback test produces).
    Best-effort: platforms without cache support boot normally."""
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        with contextlib.suppress(Exception):
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0
            )
        with contextlib.suppress(Exception):
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return True
    except Exception as e:  # noqa: BLE001 — never fail boot over the cache
        logger.warning("persistent compilation cache unavailable: %s", e)
        return False


class ColdStartManager:
    """Restore-first boot orchestration for `engine/server.py`.

    With no snapshot URL the manager degrades to a pure phase timer
    around the full load path — `/v1/state` and the coldstart metrics
    stay populated either way."""

    def __init__(
        self,
        snapshot_url: str,
        model_name: str,
        engine_config,
        mesh,
        *,
        work_dir: str | None = None,
        clock=time.monotonic,
        store=None,
        publish: bool = True,
    ):
        from kubeai_tpu.objstore import SnapshotStore

        self.enabled = bool(snapshot_url)
        # publish=False boots are restore-only consumers (CRD
        # coldStart.publish): they never write a snapshot back.
        self.publish = publish
        self.model = model_name
        self.tracker = ColdStartTracker(clock)
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="kubeai-snap-")
        self.cache_dir = os.path.join(self.work_dir, "xla_cache")
        self.params_dir = os.path.join(self.work_dir, "params")
        cfg = (
            dataclasses.asdict(engine_config)
            if dataclasses.is_dataclass(engine_config)
            else dict(engine_config or {})
        )
        self.fingerprint = SnapshotStore.fingerprint(
            model_name, cfg, mesh_signature(mesh)
        )
        self.tracker.fingerprint = self.fingerprint
        self.store = store or (
            SnapshotStore(snapshot_url) if self.enabled else None
        )

    def acquire_params(self, full_load, like=None):
        """Restore the param tree from the snapshot when a complete one
        exists under this boot's fingerprint; otherwise run `full_load`
        (HF conversion). A `SnapshotMismatch` is a hard fallback — the
        mismatched tree is never restored."""
        from kubeai_tpu.objstore import SnapshotMismatch

        # The cache dir is configured up front: a restore fills it
        # before the first compile, a full load populates it for the
        # write-back.
        if self.enabled:
            enable_compilation_cache(self.cache_dir)
        manifest = None
        if self.enabled:
            try:
                with self.tracker.phase("fetch"):
                    manifest = self.store.fetch(
                        self.model, self.fingerprint, self.work_dir
                    )
            except SnapshotMismatch as e:
                logger.warning("%s", e)
                self.tracker.event("mismatch")
            except Exception as e:  # noqa: BLE001 — boot must survive the store
                logger.warning("snapshot fetch failed: %s", e)
                self.tracker.event("error")
            else:
                if manifest is None:
                    self.tracker.event("absent")
        if manifest is not None:
            try:
                from kubeai_tpu.engine.weights import load_native_checkpoint

                with self.tracker.phase("restore"):
                    params = load_native_checkpoint(self.params_dir, like=like)
                self.tracker.restored = True
                self.tracker.event("restored")
                logger.info(
                    "restored snapshot %s/%s", self.model, self.fingerprint
                )
                return params
            except Exception as e:  # noqa: BLE001 — fall back, don't crash-loop
                logger.warning(
                    "snapshot restore failed (%s): falling back to full load",
                    e,
                )
                self.tracker.event("error")
        with self.tracker.phase("load"):
            return full_load()

    def maybe_publish(self, params) -> bool:
        """Write-back on first boot, called AFTER warm-up so the bundled
        compilation cache holds the serving graphs. No-op when restore
        succeeded (the key is already complete) or snapshots are off."""
        if not self.enabled or not self.publish or self.tracker.restored:
            return False
        stage = os.path.join(self.work_dir, "publish")
        try:
            from kubeai_tpu.engine.weights import save_native_checkpoint

            shutil.rmtree(stage, ignore_errors=True)
            os.makedirs(stage, exist_ok=True)
            save_native_checkpoint(os.path.join(stage, "params"), params)
            if os.path.isdir(self.cache_dir) and os.listdir(self.cache_dir):
                shutil.copytree(
                    self.cache_dir, os.path.join(stage, "xla_cache")
                )
            self.store.publish(
                self.model,
                self.fingerprint,
                stage,
                meta={"boot_phases": dict(self.tracker.phases)},
            )
            self.tracker.event("published")
            logger.info(
                "published snapshot %s/%s", self.model, self.fingerprint
            )
            return True
        except Exception as e:  # noqa: BLE001 — publish is best-effort
            logger.warning("snapshot publish failed: %s", e)
            self.tracker.event("error")
            return False
        finally:
            shutil.rmtree(stage, ignore_errors=True)
