"""Weight-only int8 quantization for serving.

Decode throughput is bounded by streaming the weights from HBM each step;
int8 storage halves that traffic. Symmetric per-output-channel scales:

    w ≈ w8 * scale,   w8 = round(w / scale) ∈ [-127, 127]

Dequantization happens inside the matmul's operand read (XLA fuses
`convert(int8→bf16) * scale` into the dot input), so no bf16 copy of the
weights ever materializes.

The engine applies this at load time (EngineConfig.quantization="int8");
quantized leaves are dicts {"w8": int8, "scale": f32} and the model's
matmul helper dispatches on leaf type, so the same forward code serves
both precisions. KV cache and activations stay bf16.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Stacked-weight leaves eligible for quantization, per family tree path.
# Last axis = output channels (per-channel scales).
QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_tensor(w: jnp.ndarray) -> dict:
    """[..., in, out] -> {"w8": int8, "scale": f32[..., 1, out]}."""
    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)  # per output channel
    scale = np.maximum(amax / 127.0, 1e-8)
    w8 = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return {"w8": jnp.asarray(w8), "scale": jnp.asarray(scale, np.float32)}


def dequantize(leaf) -> jnp.ndarray:
    if is_quantized(leaf):
        return (
            leaf["w8"].astype(jnp.bfloat16)
            * leaf["scale"].astype(jnp.bfloat16)
        )
    return leaf


def is_quantized(leaf) -> bool:
    return isinstance(leaf, dict) and "w8" in leaf and "scale" in leaf


def quantize_params(params: dict, targets=QUANTIZABLE) -> dict:
    """Quantize the named layer weights of a stacked-layer param tree."""
    out = dict(params)
    layers = dict(params["layers"])
    for name in targets:
        if name in layers:
            layers[name] = quantize_tensor(layers[name])
    out["layers"] = layers
    return out


def quantized_specs(specs: dict, layers_params: dict) -> dict:
    """Mirror the sharding-spec tree onto the quantized structure: the w8
    leaf keeps the weight's axes; scales shard like the output axis."""
    out = dict(specs)
    lspecs = dict(specs["layers"])
    for name, leaf in layers_params.items():
        if is_quantized(leaf) and name in lspecs:
            axes = lspecs[name]
            # scale shape [..., 1, out]: the singleton input axis must be
            # replicated; the output axis shards like the weight's.
            scale_axes = tuple(axes[:-2]) + (None,) + (axes[-1],)
            lspecs[name] = {"w8": axes, "scale": scale_axes}
    out["layers"] = lspecs
    return out
